"""On-disk layout + shard format for the distributed checkpoint subsystem.

Layout of one checkpoint step under a storage root::

    root/
      checkpoint_000042.tmp/            # phase 1: shards write here
        shard_00000/
          leaves.npz                    # this shard's leaf slices
          skeleton.json | skeleton.pkl  # tree structure (shard 0 only)
          MANIFEST.json                 # per-shard manifest
        shard_00001/ ...
      checkpoint_000042/                # phase 2: atomic rename = commit
        ... same files ...
        MANIFEST.json                   # global manifest (coordinator)
        COMMIT                          # commit marker (written pre-rename)

The *commit point* is the directory rename: the coordinator writes the
global manifest and the ``COMMIT`` marker inside the ``.tmp`` directory,
fsyncs, then ``os.replace``s it to the final name.  A reader therefore
never sees a partially written checkpoint under a committed name, and a
crash at any point leaves either the previous committed step intact or a
``.tmp`` directory that restore ignores.  ``is_committed_dir`` requires
BOTH the final name and the marker, so a torn directory produced by any
other writer is never selected either.

Leaf partitioning is deterministic from (tree, world_size): leaves whose
axis-0 extent divides evenly across the world are split along axis 0, one
slice per shard; everything else is "replicated" and written by shard 0
only.  The skeleton records the choice, so restore reassembles full host
arrays from any number of shard files — which is what makes restore
*elastic*: the new job's mesh/world size never has to match the writer's
(see elastic.py for the device placement half).
"""

from __future__ import annotations

import io
import json
import os
import pickle
import re
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

COMMIT_MARKER = "COMMIT"
GLOBAL_MANIFEST = "MANIFEST.json"
SHARD_MANIFEST = "MANIFEST.json"
TMP_SUFFIX = ".tmp"

_STEP_RE = re.compile(r"^checkpoint_(\d{6,})$")


def step_dirname(step: int) -> str:
    return f"checkpoint_{step:06d}"


def shard_dirname(shard_id: int) -> str:
    return f"shard_{shard_id:05d}"


def tmp_dir(root: str, step: int) -> str:
    return os.path.join(root, step_dirname(step) + TMP_SUFFIX)


def final_dir(root: str, step: int) -> str:
    return os.path.join(root, step_dirname(step))


def parse_step(dirname: str) -> Optional[int]:
    m = _STEP_RE.match(dirname)
    return int(m.group(1)) if m else None


def is_committed_dir(path: str) -> bool:
    """Committed = final (non-.tmp) name AND the COMMIT marker exists."""
    name = os.path.basename(os.path.normpath(path))
    if parse_step(name) is None:
        return False
    return os.path.exists(os.path.join(path, COMMIT_MARKER))


def list_committed_steps(root: str) -> List[int]:
    if not os.path.isdir(root):
        return []
    steps = []
    for name in os.listdir(root):
        step = parse_step(name)
        if step is None:
            continue
        if os.path.exists(os.path.join(root, name, COMMIT_MARKER)):
            steps.append(step)
    return sorted(steps)


def latest_committed_step(root: str) -> Optional[int]:
    steps = list_committed_steps(root)
    return steps[-1] if steps else None


def list_stale_tmp_dirs(root: str) -> List[str]:
    """Leftover ``checkpoint_*.tmp`` dirs (crashed/aborted saves)."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        if name.endswith(TMP_SUFFIX) and parse_step(name[: -len(TMP_SUFFIX)]) is not None:
            out.append(os.path.join(root, name))
    return sorted(out)


def fsync_dir(path: str) -> None:
    """fsync a directory entry so a rename survives power loss (best
    effort — some filesystems refuse O_RDONLY dir fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_json(path: str, obj: Any) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# --------------------------------------------------------------- skeleton

def _is_leaf(node: Any) -> bool:
    return not isinstance(node, (dict, list, tuple))


def _encode_json(node: Any, leaves: List[Any]):
    """JSON skeleton for plain containers; raises TypeError on anything
    fancier (namedtuples, dataclasses, custom pytree nodes) so the caller
    falls back to the pickle skeleton."""
    if isinstance(node, dict):
        if type(node) is not dict or not all(isinstance(k, str) for k in node):
            raise TypeError("non-plain dict")
        return {"t": "d", "k": list(node.keys()),
                "v": [_encode_json(v, leaves) for v in node.values()]}
    if type(node) is list:
        return {"t": "l", "v": [_encode_json(v, leaves) for v in node]}
    if type(node) is tuple:
        return {"t": "t", "v": [_encode_json(v, leaves) for v in node]}
    if isinstance(node, (dict, list, tuple)):
        # Container *subclass* (namedtuple, OrderedDict, flax FrozenDict
        # lookalikes): not a leaf — force the pickled-treedef path.
        raise TypeError("container subclass")
    i = len(leaves)
    leaves.append(node)
    return {"t": "x", "i": i}


def _decode_json(node: dict, leaves: List[Any]):
    t = node["t"]
    if t == "d":
        return {k: _decode_json(v, leaves) for k, v in zip(node["k"], node["v"])}
    if t == "l":
        return [_decode_json(v, leaves) for v in node["v"]]
    if t == "t":
        return tuple(_decode_json(v, leaves) for v in node["v"])
    return leaves[node["i"]]


class _LeafMarker:
    """Placeholder leaf inside the pickle-fallback skeleton."""

    __slots__ = ("i",)

    def __init__(self, i: int):
        self.i = i


def flatten_tree(tree: Any) -> Tuple[Any, List[Any], str]:
    """-> (skeleton_obj, leaves, kind) where kind is 'json' or 'pkl'.

    The json path covers plain dict/list/tuple pytrees; everything else
    (flax structs, namedtuples, optax states) goes through jax's registry
    with a pickled treedef."""
    leaves: List[Any] = []
    try:
        skeleton = _encode_json(tree, leaves)
        return skeleton, leaves, "json"
    except TypeError:
        pass
    import jax

    leaves, treedef = jax.tree.flatten(tree)
    return pickle.dumps(treedef), leaves, "pkl"


def unflatten_tree(skeleton: Any, kind: str, leaves: List[Any]) -> Any:
    if kind == "json":
        return _decode_json(skeleton, leaves)
    import jax

    return jax.tree.unflatten(pickle.loads(skeleton), leaves)


# ----------------------------------------------------------- partitioning

def partition_for(shape: Tuple[int, ...], world_size: int) -> Dict[str, Any]:
    """Deterministic leaf partition: split axis 0 across shards when it
    divides evenly, else replicate (shard 0 owns the write)."""
    if world_size > 1 and len(shape) >= 1 and shape[0] >= world_size \
            and shape[0] % world_size == 0:
        return {"kind": "sharded", "axis": 0, "count": world_size}
    return {"kind": "replicated", "owner": 0}


def build_shard(host_tree: Any, shard_id: int, world_size: int):
    """Split a *host* pytree into this shard's piece.

    Returns (skeleton_doc, arrays) where arrays maps ``leaf_<i>`` to the
    numpy slice this shard owns (possibly empty for replicated leaves on
    shard_id > 0), and skeleton_doc fully describes the tree + global leaf
    metadata (identical on every shard — only shard 0 writes it).
    """
    skeleton, leaves, kind = flatten_tree(host_tree)
    leaf_meta = []
    arrays: Dict[str, np.ndarray] = {}
    for i, leaf in enumerate(leaves):
        if leaf is None:
            # None is a leaf on the json-skeleton path; np.asarray(None)
            # is an object array that npz would *pickle* — the save would
            # commit but allow_pickle=False restore could never load it.
            # Inline it in the skeleton doc instead of the npz.
            leaf_meta.append({"dtype": "none", "shape": [],
                              "partition": {"kind": "inline", "value": None}})
            continue
        a = np.asarray(leaf)
        if a.dtype == object:
            raise TypeError(
                f"checkpoint leaf {i} ({type(leaf).__name__}) is not "
                "numeric/string data: saving it would pickle an object "
                "array that restore (allow_pickle=False) can never load — "
                "a committed-but-unrestorable checkpoint. Convert the leaf "
                "to an array or drop it from the checkpointed tree.")
        part = partition_for(a.shape, world_size)
        leaf_meta.append({"dtype": str(a.dtype), "shape": list(a.shape),
                          "partition": part})
        if part["kind"] == "sharded":
            rows = a.shape[0] // part["count"]
            arrays[f"leaf_{i}"] = a[shard_id * rows:(shard_id + 1) * rows]
        elif shard_id == part["owner"]:
            arrays[f"leaf_{i}"] = a
    doc = {"format": 1, "world_size": world_size, "kind": kind,
           "num_leaves": len(leaves), "leaves": leaf_meta}
    if kind == "json":
        doc["skeleton"] = skeleton
    return doc, skeleton, kind, arrays


def write_shard(step_dir: str, shard_id: int, doc: dict, skeleton: Any,
                kind: str, arrays: Dict[str, np.ndarray], step: int,
                extra_manifest: Optional[dict] = None) -> dict:
    """Write one shard's files under ``step_dir`` and return its manifest."""
    sdir = os.path.join(step_dir, shard_dirname(shard_id))
    os.makedirs(sdir, exist_ok=True)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    blob = buf.getvalue()
    npz_path = os.path.join(sdir, "leaves.npz")
    with open(npz_path + ".tmp", "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(npz_path + ".tmp", npz_path)
    total = len(blob)
    if shard_id == 0:
        if kind == "json":
            atomic_write_json(os.path.join(sdir, "skeleton.json"), doc)
        else:
            pkl_doc = dict(doc)
            with open(os.path.join(sdir, "skeleton.pkl"), "wb") as f:
                pickle.dump({"doc": pkl_doc, "treedef": skeleton}, f)
                f.flush()
                os.fsync(f.fileno())
            total += os.path.getsize(os.path.join(sdir, "skeleton.pkl"))
    manifest = {
        "step": step,
        "shard_id": shard_id,
        "world_size": doc["world_size"],
        "arrays": sorted(arrays.keys()),
        "bytes": total,
        "crc32": zlib.crc32(blob) & 0xFFFFFFFF,
    }
    if extra_manifest:
        manifest.update(extra_manifest)
    atomic_write_json(os.path.join(sdir, SHARD_MANIFEST), manifest)
    return manifest


def commit_step_dir(root: str, step: int, shard_manifests: Dict[int, dict],
                    extra: Optional[dict] = None) -> str:
    """Phase 2: global manifest + COMMIT marker inside the tmp dir, fsync,
    then the atomic rename that IS the commit point.  Returns the final
    committed path."""
    import time as _time

    tmp = tmp_dir(root, step)
    final = final_dir(root, step)
    manifest = {
        "step": step,
        "num_shards": len(shard_manifests),
        "shards": {str(sid): m for sid, m in sorted(shard_manifests.items())},
        "total_bytes": sum(m.get("bytes", 0) for m in shard_manifests.values()),
        "time": _time.time(),
    }
    if extra:
        manifest.update(extra)
    atomic_write_json(os.path.join(tmp, GLOBAL_MANIFEST), manifest)
    atomic_write_json(os.path.join(tmp, COMMIT_MARKER), {
        "step": step, "num_shards": len(shard_manifests),
        "time": manifest["time"]})
    fsync_dir(tmp)
    if os.path.isdir(final):
        # A same-step committed dir already exists (re-commit after a
        # partial retention race); replace it via a sibling swap.
        import shutil

        trash = final + ".old"
        shutil.rmtree(trash, ignore_errors=True)
        os.replace(final, trash)
        os.replace(tmp, final)
        shutil.rmtree(trash, ignore_errors=True)
    else:
        os.replace(tmp, final)
    fsync_dir(root)
    return final


def write_committed_from_payloads(root: str, step: int,
                                  payloads: Dict[int, dict]) -> str:
    """Materialize a committed checkpoint dir from in-memory replica
    payloads (the Gemini-style fast restore path: peers hand back their
    shard payloads and we rebuild a committed step locally without
    touching the original storage)."""
    import shutil

    tmp = tmp_dir(root, step)
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp, exist_ok=True)
    manifests = {}
    for sid, p in payloads.items():
        manifests[sid] = write_shard(tmp, sid, p["doc"], p["skeleton"],
                                     p["kind"], p["arrays"], step)
    return commit_step_dir(root, step, manifests, extra={"source": "replica"})


def assemble_from_payloads(payloads: Dict[int, dict]) -> Any:
    """Reassemble the full host pytree purely from in-memory replica
    payloads — no disk involved."""
    p0 = payloads[0]
    doc, skeleton, kind = p0["doc"], p0["skeleton"], p0["kind"]
    leaves: List[Any] = []
    for i, meta in enumerate(doc["leaves"]):
        key = f"leaf_{i}"
        part = meta["partition"]
        if part["kind"] == "inline":
            leaves.append(part.get("value"))
        elif part["kind"] == "sharded":
            pieces = [np.asarray(payloads[s]["arrays"][key])
                      for s in range(part["count"])]
            leaves.append(np.concatenate(pieces, axis=part["axis"]))
        else:
            leaves.append(np.asarray(payloads[part.get("owner", 0)]["arrays"][key]))
    return unflatten_tree(skeleton, kind, leaves)


def read_skeleton(step_dir: str) -> Tuple[dict, Any, str]:
    """-> (doc, skeleton, kind) from shard 0."""
    sdir = os.path.join(step_dir, shard_dirname(0))
    jpath = os.path.join(sdir, "skeleton.json")
    if os.path.exists(jpath):
        with open(jpath) as f:
            doc = json.load(f)
        return doc, doc["skeleton"], "json"
    with open(os.path.join(sdir, "skeleton.pkl"), "rb") as f:
        payload = pickle.load(f)
    return payload["doc"], payload["treedef"], "pkl"


def assemble_tree(step_dir: str,
                  shard_arrays: Optional[Dict[int, Dict[str, np.ndarray]]] = None) -> Any:
    """Reassemble the full host pytree from a checkpoint step directory.

    ``shard_arrays`` (shard_id -> {leaf_i: array}) lets the in-memory
    replica tier bypass disk: any shard present there is used as-is and
    its files are never opened.
    """
    doc, skeleton, kind = read_skeleton(step_dir)
    shard_arrays = shard_arrays or {}

    opened: Dict[int, Any] = {}

    def shard_data(sid: int):
        if sid in shard_arrays:
            return shard_arrays[sid]
        if sid not in opened:
            opened[sid] = np.load(
                os.path.join(step_dir, shard_dirname(sid), "leaves.npz"))
        return opened[sid]

    leaves: List[Any] = []
    for i, meta in enumerate(doc["leaves"]):
        key = f"leaf_{i}"
        part = meta["partition"]
        if part["kind"] == "inline":
            leaves.append(part.get("value"))
        elif part["kind"] == "sharded":
            pieces = [np.asarray(shard_data(s)[key]) for s in range(part["count"])]
            leaves.append(np.concatenate(pieces, axis=part["axis"]))
        else:
            leaves.append(np.asarray(shard_data(part.get("owner", 0))[key]))
    try:
        return unflatten_tree(skeleton, kind, leaves)
    finally:
        for z in opened.values():
            try:
                z.close()
            except Exception:
                pass
