"""Fused causal flash attention for TPU.

Replaces the XLA einsum-softmax-einsum path, whose (B, H, S, S) fp32 score
tensor is pure HBM traffic (805MB/layer for GPT-2-small at S=1024 — measured
~10x over compute-bound time on v5e).  Flash attention keeps scores in VMEM
tiles and never materializes them.

Current implementation wraps jax's public pallas TPU flash kernel with block
sizes tuned on v5e (defaults were 3.8x slower there: 58.6ms -> 15.3ms fwd for
GPT-2-small's 12 layers).
"""

from __future__ import annotations

import math
from functools import lru_cache
from typing import Optional


@lru_cache(maxsize=None)
def _block_sizes(seq_len: int, block: int):
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

    # The kernel requires block | seq_len: take the largest divisor <= block.
    b = min(block, seq_len)
    while seq_len % b != 0:
        b -= 128 if b > 128 else 1
        if b < 1:
            b = seq_len
            break
    return BlockSizes(
        block_q=b, block_k_major=b, block_k=b, block_b=1,
        block_q_major_dkv=b, block_k_major_dkv=b, block_k_dkv=b, block_q_dkv=b,
        block_k_major_dq=b, block_k_dq=b, block_q_dq=b,
    )


def _splash_kernel(seq_len: int, n_heads: int, block_q: int, block_kv: int,
                   fused_bwd: bool, causal: bool = True):
    # NOT cached: the kernel object built during one jit trace captures that
    # trace's context — reusing it from a later trace raises
    # UnexpectedTracerError.  Construction is cheap (lazy mask, no arrays).
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
        splash_attention_mask as sm,
    )

    import jax

    mask_cls = sm.CausalMask if causal else sm.FullMask
    mask = sm.MultiHeadMask(
        [mask_cls((seq_len, seq_len)) for _ in range(n_heads)])
    interpret = jax.default_backend() != "tpu"
    bq = min(block_q, seq_len)
    bkv = min(block_kv, seq_len)
    bs = sk.BlockSizes(
        block_q=bq, block_kv=bkv, block_kv_compute=bkv,
        block_q_dkv=bq, block_kv_dkv=bkv, block_kv_dkv_compute=bkv,
        block_q_dq=None if fused_bwd else bq,
        block_kv_dq=None if fused_bwd else bkv,
        use_fused_bwd_kernel=fused_bwd,
    )
    return sk.make_splash_mha(mask, head_shards=1, q_seq_shards=1,
                              block_sizes=bs, interpret=interpret)


_LANE_HEAD_REQUIRED: Optional[bool] = None


def _head_pad_target(head_dim: int) -> int:
    """Older splash kernels refuse head_dim % 128 != 0 (the lane tile) at
    trace time; newer ones handle it internally.  Probe once with a shape
    eval — when the restriction exists, callers zero-pad the head axis up
    to the tile and slice the output back (zero k/v columns contribute
    nothing to scores or outputs, so the math is unchanged)."""
    global _LANE_HEAD_REQUIRED
    if head_dim % 128 == 0:
        return head_dim
    if _LANE_HEAD_REQUIRED is None:
        import jax
        import jax.numpy as jnp

        try:
            kern = _splash_kernel(128, 1, 128, 128, True, True)
            s = jax.ShapeDtypeStruct((1, 128, 64), jnp.float32)
            jax.eval_shape(kern, s, s, s)
            _LANE_HEAD_REQUIRED = False
        except Exception:  # noqa: BLE001 — padding is always safe, just wider
            _LANE_HEAD_REQUIRED = True
    if not _LANE_HEAD_REQUIRED:
        return head_dim
    return -(-head_dim // 128) * 128


def splash_attention(q, k, v, causal: bool = True,
                     sm_scale: Optional[float] = None,
                     block_q: int = 512, block_kv: int = 512,
                     fused_bwd: bool = True):
    """Production TPU attention (splash kernel): sparse over the causal
    mask when causal (no wasted upper-triangle work, unlike the stock flash
    kernel), full-mask bidirectional (ViT-style) otherwise, with a fused
    dq/dkv backward.

    q, k, v: (B, S, H, head_dim) — the model's native layout.
    """
    import jax

    B, S, H, hd = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(hd)
    kernel = _splash_kernel(S, H, block_q, block_kv, fused_bwd, causal)
    # Splash takes (H, S, hd) per example; scale q up front (no scale arg).
    qt = (q * sm_scale).transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    hp = _head_pad_target(hd)
    if hp != hd:
        import jax.numpy as jnp

        pad = ((0, 0), (0, 0), (0, 0), (0, hp - hd))
        qt, kt, vt = (jnp.pad(x, pad) for x in (qt, kt, vt))
    out = jax.vmap(kernel)(qt, kt, vt)  # (B, H, S, hp)
    if hp != hd:
        out = out[..., :hd]
    return out.transpose(0, 2, 1, 3)


def flash_attention(q, k, v, causal: bool = True, sm_scale: Optional[float] = None,
                    block: int = 1024):
    """q, k, v: (B, S, H, head_dim) — the model's native layout.

    Scaling matches the unfused path: 1/sqrt(head_dim) unless given.
    """
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as _pallas_flash,
    )

    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    # Pallas kernel wants (B, H, S, D).
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _pallas_flash(
        qt, kt, vt,
        causal=causal,
        sm_scale=sm_scale,
        block_sizes=_block_sizes(q.shape[1], block),
    )
    return out.transpose(0, 2, 1, 3)
