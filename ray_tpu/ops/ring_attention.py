"""Context parallelism: ring attention + Ulysses all-to-all attention.

The reference has NO native sequence/context parallelism (SURVEY §2.3/§5 —
delegated to DeepSpeed/HF over Ray-provided process groups).  Here it is
native and TPU-shaped:

- **Ring attention** (Liu et al. 2023): K/V chunks rotate around the `seq`
  mesh axis via `lax.ppermute` (riding the ICI ring) while each device
  accumulates its queries' attention with a streaming log-sum-exp — memory
  per device is O(S/world), and the rotation overlaps with the block matmuls.
- **Ulysses** (Jacobs et al. 2023): `lax.all_to_all` reshards
  (seq-sharded, all heads) -> (full seq, head-sharded), runs ordinary
  causal attention per head shard (flash-compatible), and reshards back.
  Cheaper than the ring when heads % world == 0 and S fits per-device.

Both are pure jnp/lax bodies meant for `shard_map`, so they are reverse-mode
differentiable (scan + ppermute transpose) and compile to one XLA program.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

_NEG = -1e30  # strictly-finite mask value: -inf breaks the streaming max

# Pallas splash kernels need KV blocks that are multiples of the 128-lane
# register tile; the fused ring path activates only when the per-device
# sequence shard admits such a block.
_LANE = 128


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _axis_size(axis_name: str) -> int:
    """lax.axis_size is a recent addition; psum of a constant 1 is the
    long-standing spelling and folds to a static int on every version."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def _ring_block(seq_len: int) -> Optional[int]:
    """Largest multiple-of-128 divisor of seq_len, capped at the v5e-tuned
    512 (ops/attention.py) — None when no legal splash block exists."""
    for b in (512, 384, 256, 128):
        if b <= seq_len and seq_len % b == 0:
            return b
    return None


_FUSED_PROBE: Optional[bool] = None


def _fused_available() -> bool:
    """The fused backward reaches into jax's splash internals (the public
    custom-VJP can't merge per-block lse across ring steps); probe the
    private surface so a jax upgrade degrades impl='auto' to the einsum
    body instead of breaking every gradient at trace time.

    hasattr checks aren't enough — a surface can survive by name while its
    shape changes (BlockSizes growing a required ctor arg, kwargs keys
    renamed, bwd params reshuffled).  So this CONSTRUCTS a tiny kernel via
    the same ``_block_kernel`` path the real fwd/bwd use and touches every
    attribute/key/parameter ``_fused_ring_bwd`` reads.  Probed once per
    process; failure downgrades impl='auto' with a one-time loud warning.
    """
    global _FUSED_PROBE
    if _FUSED_PROBE is None:
        _FUSED_PROBE = _probe_fused_surfaces()
    return _FUSED_PROBE


def _bwd_dkv_leading_params(sk) -> list:
    """Names of _splash_attention_bwd_dkv's positional-or-keyword params
    (everything before the keyword-only marker), in order."""
    import inspect

    out = []
    for name, p in inspect.signature(
            sk._splash_attention_bwd_dkv).parameters.items():
        if p.kind is not inspect.Parameter.POSITIONAL_OR_KEYWORD:
            break
        out.append(name)
    return out


def _probe_fused_surfaces() -> bool:
    import inspect
    import warnings

    try:
        from jax.experimental.pallas.ops.tpu.splash_attention import (
            splash_attention_kernel as sk,
        )
        # Construction exercises the 9-kwarg BlockSizes ctor and
        # _make_splash_attention's full signature (head_shards,
        # save_residuals, interpret, ...) exactly as the ring body does.
        kern = _block_kernel(128, 1, 128, "diag", True)
        # Surfaces read by _fused_ring_bwd:
        if kern.dkv_mask_info is None:
            raise AttributeError("kernel lost its dkv mask_info (was "
                                 "use_fused_bwd_kernel dropped?)")
        bs = kern.kwargs["block_sizes"]
        _ = (bs.q_layout, bs.k_layout, bs.v_layout)
        _ = kern.kwargs["mask_function"]  # key must exist (value may be None)
        _ = sk.DEFAULT_MASK_VALUE
        # The bwd helper is called entirely with keyword args: every name we
        # pass must still be a parameter (or a **kwargs catch-all), and the
        # tensor args we bind by name must still be leading params.
        params = inspect.signature(sk._splash_attention_bwd_dkv).parameters
        needed = {"bq", "bkv", "bkv_compute", "is_mqa", "mask_info",
                  "mask_value", "attn_logits_soft_cap",
                  "use_fused_bwd_kernel", "q_layout", "k_layout", "v_layout",
                  "mask_function", "interpret"}
        has_var_kw = any(p.kind is inspect.Parameter.VAR_KEYWORD
                         for p in params.values())
        missing = needed - set(params)
        if missing and not has_var_kw:
            raise TypeError(
                f"_splash_attention_bwd_dkv lost parameters: {sorted(missing)}")
        lead = _bwd_dkv_leading_params(sk)
        tensor_args = {"q", "k", "v", "logsumexp", "do", "di"}
        if not tensor_args <= set(lead):
            raise TypeError(
                "_splash_attention_bwd_dkv renamed leading params: "
                f"{sorted(tensor_args - set(lead))} missing from {lead}")
        return True
    except Exception as e:  # noqa: BLE001 — ANY probe failure means einsum
        warnings.warn(
            "ray_tpu.ops.ring_attention: the fused splash ring-attention "
            f"path is unavailable ({type(e).__name__}: {e}); impl='auto' "
            "falls back to the einsum body, which materializes per-block "
            "(B,H,S,S) scores — expect higher HBM traffic. Pin a jax "
            "version with the splash_attention private surfaces, or pass "
            "impl='einsum' to silence this.",
            RuntimeWarning, stacklevel=2)
        return False


def _block_kernel(seq_len: int, n_heads: int, block: int, kind: str,
                  interp: bool):
    """One ring-step splash kernel over a (seq_len x seq_len) chunk pair.

    kind="diag" masks causally within the chunk (the rotation step where the
    K/V chunk is the device's own); kind="full" is the unmasked block (chunks
    strictly earlier in the global order, and every step when non-causal).
    save_residuals=True so each step yields (out, lse) for the streaming
    merge.  NOT cached across traces (see ops/attention.py:_splash_kernel).
    """
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
        splash_attention_mask as sm,
    )

    mask_cls = sm.CausalMask if kind == "diag" else sm.FullMask
    mask = sm.MultiHeadMask([mask_cls((seq_len, seq_len))
                             for _ in range(n_heads)])
    bs = sk.BlockSizes(
        block_q=block, block_kv=block, block_kv_compute=block,
        block_q_dkv=block, block_kv_dkv=block, block_kv_dkv_compute=block,
        block_q_dq=None, block_kv_dq=None, use_fused_bwd_kernel=True,
    )
    return sk._make_splash_attention(
        mask, block_sizes=bs, is_mqa=False, save_residuals=True,
        head_shards=1, q_seq_shards=1, interpret=interp)


def _mark_varying(ref, *arrs):
    """shard_map vma plumbing: scan carries must enter with the same
    device-varying type their ppermute-mixing bodies produce."""
    if hasattr(lax, "pcast"):
        mesh_axes = tuple(jax.typeof(ref).vma) if hasattr(jax, "typeof") else ()
        if mesh_axes:
            return tuple(lax.pcast(x, mesh_axes, to="varying") for x in arrs)
    return arrs


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_ring_core(q, k, v, axis_name: str, causal: bool, block: int):
    """Ring attention whose per-rotation block is the splash flash kernel.

    q/k/v: (B, H, S_local, D), q pre-scaled.  Forward merges per-block
    normalized outputs with their logsumexp; backward re-rotates K/V and runs
    the fused splash dq/dkv kernel per block with the GLOBAL (merged) lse and
    di — the standard flash decomposition, so block backward passes sum to
    the exact dense gradient.
    """
    out, _ = _fused_ring_fwd(q, k, v, axis_name, causal, block)
    return out


def _fused_ring_fwd(q, k, v, axis_name: str, causal: bool, block: int):
    world = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, H, S, D = q.shape
    interp = _interpret()
    diag_kern = _block_kernel(S, H, block, "diag", interp)
    full_kern = _block_kernel(S, H, block, "full", interp)
    perm = [(i, (i + 1) % world) for i in range(world)]

    def run(kern):
        def f(k_cur, v_cur):
            o_b, (lse_b,) = jax.vmap(kern)(q, k_cur, v_cur)
            return o_b.astype(jnp.float32), lse_b
        return f

    def skip(k_cur, v_cur):
        return (jnp.zeros((B, H, S, D), jnp.float32),
                jnp.full((B, H, S), _NEG, jnp.float32))

    o0 = jnp.zeros((B, H, S, D), jnp.float32)
    lse0 = jnp.full((B, H, S), _NEG, jnp.float32)
    o0, lse0 = _mark_varying(q, o0, lse0)

    def step(carry, s):
        k_cur, v_cur, o_acc, lse_acc = carry
        if causal:
            src = (idx - s) % world
            case = jnp.where(src > idx, 0, jnp.where(src == idx, 1, 2))
            o_b, lse_b = lax.switch(
                case, [skip, run(diag_kern), run(full_kern)], k_cur, v_cur)
        else:
            o_b, lse_b = run(full_kern)(k_cur, v_cur)
        lse_new = jnp.logaddexp(lse_acc, lse_b)
        o_new = (o_acc * jnp.exp(lse_acc - lse_new)[..., None]
                 + o_b * jnp.exp(lse_b - lse_new)[..., None])
        return (lax.ppermute(k_cur, axis_name, perm),
                lax.ppermute(v_cur, axis_name, perm), o_new, lse_new), None

    (_, _, o, lse), _ = lax.scan(step, (k, v, o0, lse0), jnp.arange(world))
    return o.astype(q.dtype), (q, k, v, o, lse)


def _fused_ring_bwd(axis_name: str, causal: bool, block: int, res, do):
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
    )

    q, k, v, o, lse = res
    world = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, H, S, D = q.shape
    interp = _interpret()
    diag_kern = _block_kernel(S, H, block, "diag", interp)
    full_kern = _block_kernel(S, H, block, "full", interp)
    bs = diag_kern.kwargs["block_sizes"]
    perm = [(i, (i + 1) % world) for i in range(world)]

    do = do.astype(q.dtype)
    di = jnp.sum(o * do.astype(jnp.float32), axis=-1)  # (B, H, S) global

    # The leading (positional-or-keyword) params drift across jax versions
    # (segment_ids grew neighbours): bind q/k/v/logsumexp/do/di BY NAME and
    # default every other leading param to None.  _probe_fused_surfaces
    # guarantees the names exist before impl='auto' ever routes here.
    lead = _bwd_dkv_leading_params(sk)

    def run(kern):
        def per_ex(q1, k1, v1, lse1, do1, di1):
            vals = dict.fromkeys(lead)
            vals.update(q=q1, k=k1, v=v1, logsumexp=lse1, do=do1, di=di1)
            return sk._splash_attention_bwd_dkv(
                **vals,
                bq=block, bkv=block, bkv_compute=block, is_mqa=False,
                mask_info=kern.dkv_mask_info,
                mask_value=sk.DEFAULT_MASK_VALUE,
                attn_logits_soft_cap=None, use_fused_bwd_kernel=True,
                q_layout=bs.q_layout, k_layout=bs.k_layout,
                v_layout=bs.v_layout,
                mask_function=kern.kwargs["mask_function"], interpret=interp)

        def f(k_cur, v_cur):
            dq_c, dk_c, dv_c = jax.vmap(per_ex)(q, k_cur, v_cur, lse, do, di)
            return (dq_c.astype(jnp.float32), dk_c.astype(jnp.float32),
                    dv_c.astype(jnp.float32))
        return f

    def skip(k_cur, v_cur):
        z = jnp.zeros((B, H, S, D), jnp.float32)
        return z, z, z

    zq = jnp.zeros((B, H, S, D), jnp.float32)
    zq, zk, zv = _mark_varying(q, zq, zq, zq)

    def step(carry, s):
        k_cur, v_cur, dk_cur, dv_cur, dq = carry
        if causal:
            src = (idx - s) % world
            case = jnp.where(src > idx, 0, jnp.where(src == idx, 1, 2))
            dq_c, dk_c, dv_c = lax.switch(
                case, [skip, run(diag_kern), run(full_kern)], k_cur, v_cur)
        else:
            dq_c, dk_c, dv_c = run(full_kern)(k_cur, v_cur)
        # dk/dv ride the ring WITH their chunk: after `world` rotations the
        # accumulated gradients land back on the chunk's home device.
        return (lax.ppermute(k_cur, axis_name, perm),
                lax.ppermute(v_cur, axis_name, perm),
                lax.ppermute(dk_cur + dk_c, axis_name, perm),
                lax.ppermute(dv_cur + dv_c, axis_name, perm),
                dq + dq_c), None

    (_, _, dk, dv, dq), _ = lax.scan(
        step, (k, v, zk, zv, zq), jnp.arange(world))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_fused_ring_core.defvjp(_fused_ring_fwd, _fused_ring_bwd)


def fused_ring_attention_local(q, k, v, *, axis_name: str = "seq",
                               causal: bool = True,
                               sm_scale: Optional[float] = None,
                               block: Optional[int] = None):
    """Pallas-fused ring attention body for shard_map: (B, S_local, H, D).

    Per rotation step the local block runs the splash flash kernel (scores
    never leave VMEM); fully-masked steps (K/V chunk strictly after the
    queries, causal) skip compute entirely — half the ring for free.
    """
    B, S, H, D = q.shape
    if block is None:
        block = _ring_block(S)
    if block is None:
        raise ValueError(
            f"fused ring needs S_local ({S}) divisible by a 128-multiple "
            "block; use impl='einsum'")
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)
    qt = (q * scale).transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    # Old splash kernels require head_dim % 128 == 0: zero-pad the head
    # axis (padding is exact — zero k/v columns add nothing) and slice
    # back.  Outside the custom VJP, so the backward sees padded shapes too.
    from ray_tpu.ops.attention import _head_pad_target

    hp = _head_pad_target(D)
    if hp != D:
        pad = ((0, 0), (0, 0), (0, 0), (0, hp - D))
        qt, kt, vt = (jnp.pad(x, pad) for x in (qt, kt, vt))
    out = _fused_ring_core(qt, kt, vt, axis_name, causal, block)
    if hp != D:
        out = out[..., :D]
    return out.transpose(0, 2, 1, 3)


# ---------------------------------------------------------------- ring local
def ring_attention_local(q, k, v, *, axis_name: str = "seq",
                         causal: bool = True,
                         sm_scale: Optional[float] = None,
                         impl: str = "auto"):
    """Body for shard_map: q/k/v are (B, S_local, H, D) sequence shards.

    impl="fused" runs the splash flash kernel per rotation block (VERDICT r4
    #2: the einsum block materialized (B,H,S,S) scores — exactly the HBM
    traffic flash exists to kill); "einsum" is the streaming-LSE reference
    body below; "auto" picks fused whenever the shard admits a legal splash
    block (S_local % 128 == 0).

    Streaming-softmax accumulation over `world` rotation steps; the k/v
    chunk held at step s originated on rank (idx - s) mod world, which
    fixes the global positions for causal masking.
    """
    if impl == "auto":
        impl = "fused" if (_ring_block(q.shape[1]) is not None
                           and _fused_available()) else "einsum"
    if impl == "fused":
        return fused_ring_attention_local(q, k, v, axis_name=axis_name,
                                          causal=causal, sm_scale=sm_scale)
    world = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, S, H, D = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)
    qpos = idx * S + jnp.arange(S)

    m0 = jnp.full((B, H, S), _NEG, jnp.float32)
    l0 = jnp.zeros((B, H, S), jnp.float32)
    o0 = jnp.zeros((B, S, H, D), jnp.float32)
    # Mark the carry init as device-varying: the scan body's outputs vary
    # over the mesh (they mix in ppermuted k/v), and shard_map's vma check
    # requires carry-in types to match carry-out.
    if hasattr(lax, "pcast"):
        mesh_axes = tuple(jax.typeof(q).vma) if hasattr(jax, "typeof") else ()
        if mesh_axes:
            m0, l0, o0 = (lax.pcast(x, mesh_axes, to="varying")
                          for x in (m0, l0, o0))
    perm = [(i, (i + 1) % world) for i in range(world)]

    def step(carry, s):
        k_cur, v_cur, m, l, o = carry
        src_chunk = (idx - s) % world
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_cur,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            kpos = src_chunk * S + jnp.arange(S)
            mask = kpos[None, :] <= qpos[:, None]  # (Sq, Sk)
            scores = jnp.where(mask, scores, _NEG)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        if causal:
            # exp(_NEG - _NEG) == 1 on fully-masked rows: zero them by hand.
            p = jnp.where(mask[None, None], p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v_cur.dtype), v_cur,
                        preferred_element_type=jnp.float32)
        o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, m_new, l_new, o_new), None

    (_, _, m, l, o), _ = lax.scan(step, (k, v, m0, l0, o0),
                                  jnp.arange(world))
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


# ------------------------------------------------------------- ulysses local
def ulysses_attention_local(q, k, v, *, axis_name: str = "seq",
                            causal: bool = True,
                            sm_scale: Optional[float] = None,
                            attn_fn=None):
    """Body for shard_map: all_to_all (B, S/w, H, D) -> (B, S, H/w, D),
    full-sequence attention per head shard, then the inverse reshard."""
    world = _axis_size(axis_name)
    H = q.shape[2]
    if H % world != 0:
        raise ValueError(f"Ulysses needs heads ({H}) % seq axis ({world}) == 0")
    if world > 1:
        q, k, v = (lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True) for x in (q, k, v))
    if attn_fn is None:
        attn_fn = partial(_xla_attention, causal=causal, sm_scale=sm_scale)
    out = attn_fn(q, k, v)
    if world > 1:
        out = lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                             tiled=True)
    return out


def _xla_attention(q, k, v, causal: bool = True,
                   sm_scale: Optional[float] = None):
    """Plain einsum-softmax-einsum causal attention (fp32 softmax)."""
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        S, K = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((S, K), bool))
        scores = jnp.where(mask, scores, _NEG)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# ------------------------------------------------------------ shard_map APIs
def _shard_map(fn, *, mesh, in_specs, out_specs, check_vma=True):
    """shard_map moved (jax.experimental.shard_map → jax.shard_map) and
    renamed its replication-check kwarg (check_rep → check_vma) across jax
    releases; jax_compat resolves whichever spelling this jax ships."""
    from ray_tpu._private.jax_compat import shard_map as _sm

    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_vma=check_vma)


def _specs(axis_name: str, batch_axes):
    P = jax.sharding.PartitionSpec
    return P(batch_axes, axis_name, "tensor", None)


def ring_attention(q, k, v, *, mesh=None, axis_name: str = "seq",
                   causal: bool = True, sm_scale: Optional[float] = None,
                   batch_axes=("data", "fsdp"), impl: str = "auto"):
    """Context-parallel causal attention over seq-sharded (B, S, H, D).

    With mesh=None the ambient mesh (jax.set_mesh / enclosing shard_map)
    is used, so model code stays mesh-agnostic.
    """
    spec = _specs(axis_name, batch_axes)
    fn = partial(ring_attention_local, axis_name=axis_name, causal=causal,
                 sm_scale=sm_scale, impl=impl)
    # check_vma off: the splash pallas_call inside the fused body does not
    # declare vma on its output avals, which the vma checker rejects.
    return _shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec, check_vma=False)(q, k, v)


def ulysses_attention(q, k, v, *, mesh=None, axis_name: str = "seq",
                      causal: bool = True, sm_scale: Optional[float] = None,
                      attn_fn=None, batch_axes=("data", "fsdp")):
    """Ulysses sequence parallelism over seq-sharded (B, S, H, D)."""
    spec = _specs(axis_name, batch_axes)
    fn = partial(ulysses_attention_local, axis_name=axis_name, causal=causal,
                 sm_scale=sm_scale, attn_fn=attn_fn)
    return _shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec)(q, k, v)
