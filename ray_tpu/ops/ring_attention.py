"""Context parallelism: ring attention + Ulysses all-to-all attention.

The reference has NO native sequence/context parallelism (SURVEY §2.3/§5 —
delegated to DeepSpeed/HF over Ray-provided process groups).  Here it is
native and TPU-shaped:

- **Ring attention** (Liu et al. 2023): K/V chunks rotate around the `seq`
  mesh axis via `lax.ppermute` (riding the ICI ring) while each device
  accumulates its queries' attention with a streaming log-sum-exp — memory
  per device is O(S/world), and the rotation overlaps with the block matmuls.
- **Ulysses** (Jacobs et al. 2023): `lax.all_to_all` reshards
  (seq-sharded, all heads) -> (full seq, head-sharded), runs ordinary
  causal attention per head shard (flash-compatible), and reshards back.
  Cheaper than the ring when heads % world == 0 and S fits per-device.

Both are pure jnp/lax bodies meant for `shard_map`, so they are reverse-mode
differentiable (scan + ppermute transpose) and compile to one XLA program.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

_NEG = -1e30  # strictly-finite mask value: -inf breaks the streaming max


# ---------------------------------------------------------------- ring local
def ring_attention_local(q, k, v, *, axis_name: str = "seq",
                         causal: bool = True,
                         sm_scale: Optional[float] = None):
    """Body for shard_map: q/k/v are (B, S_local, H, D) sequence shards.

    Streaming-softmax accumulation over `world` rotation steps; the k/v
    chunk held at step s originated on rank (idx - s) mod world, which
    fixes the global positions for causal masking.
    """
    world = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, S, H, D = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)
    qpos = idx * S + jnp.arange(S)

    m0 = jnp.full((B, H, S), _NEG, jnp.float32)
    l0 = jnp.zeros((B, H, S), jnp.float32)
    o0 = jnp.zeros((B, S, H, D), jnp.float32)
    # Mark the carry init as device-varying: the scan body's outputs vary
    # over the mesh (they mix in ppermuted k/v), and shard_map's vma check
    # requires carry-in types to match carry-out.
    if hasattr(lax, "pcast"):
        mesh_axes = tuple(jax.typeof(q).vma) if hasattr(jax, "typeof") else ()
        if mesh_axes:
            m0, l0, o0 = (lax.pcast(x, mesh_axes, to="varying")
                          for x in (m0, l0, o0))
    perm = [(i, (i + 1) % world) for i in range(world)]

    def step(carry, s):
        k_cur, v_cur, m, l, o = carry
        src_chunk = (idx - s) % world
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_cur,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            kpos = src_chunk * S + jnp.arange(S)
            mask = kpos[None, :] <= qpos[:, None]  # (Sq, Sk)
            scores = jnp.where(mask, scores, _NEG)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        if causal:
            # exp(_NEG - _NEG) == 1 on fully-masked rows: zero them by hand.
            p = jnp.where(mask[None, None], p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v_cur.dtype), v_cur,
                        preferred_element_type=jnp.float32)
        o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, m_new, l_new, o_new), None

    (_, _, m, l, o), _ = lax.scan(step, (k, v, m0, l0, o0),
                                  jnp.arange(world))
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


# ------------------------------------------------------------- ulysses local
def ulysses_attention_local(q, k, v, *, axis_name: str = "seq",
                            causal: bool = True,
                            sm_scale: Optional[float] = None,
                            attn_fn=None):
    """Body for shard_map: all_to_all (B, S/w, H, D) -> (B, S, H/w, D),
    full-sequence attention per head shard, then the inverse reshard."""
    world = lax.axis_size(axis_name)
    H = q.shape[2]
    if H % world != 0:
        raise ValueError(f"Ulysses needs heads ({H}) % seq axis ({world}) == 0")
    if world > 1:
        q, k, v = (lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True) for x in (q, k, v))
    if attn_fn is None:
        attn_fn = partial(_xla_attention, causal=causal, sm_scale=sm_scale)
    out = attn_fn(q, k, v)
    if world > 1:
        out = lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                             tiled=True)
    return out


def _xla_attention(q, k, v, causal: bool = True,
                   sm_scale: Optional[float] = None):
    """Plain einsum-softmax-einsum causal attention (fp32 softmax)."""
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        S, K = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((S, K), bool))
        scores = jnp.where(mask, scores, _NEG)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# ------------------------------------------------------------ shard_map APIs
def _specs(axis_name: str, batch_axes):
    P = jax.sharding.PartitionSpec
    return P(batch_axes, axis_name, "tensor", None)


def ring_attention(q, k, v, *, mesh=None, axis_name: str = "seq",
                   causal: bool = True, sm_scale: Optional[float] = None,
                   batch_axes=("data", "fsdp")):
    """Context-parallel causal attention over seq-sharded (B, S, H, D).

    With mesh=None the ambient mesh (jax.set_mesh / enclosing shard_map)
    is used, so model code stays mesh-agnostic.
    """
    spec = _specs(axis_name, batch_axes)
    fn = partial(ring_attention_local, axis_name=axis_name, causal=causal,
                 sm_scale=sm_scale)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)


def ulysses_attention(q, k, v, *, mesh=None, axis_name: str = "seq",
                      causal: bool = True, sm_scale: Optional[float] = None,
                      attn_fn=None, batch_axes=("data", "fsdp")):
    """Ulysses sequence parallelism over seq-sharded (B, S, H, D)."""
    spec = _specs(axis_name, batch_axes)
    fn = partial(ulysses_attention_local, axis_name=axis_name, causal=causal,
                 sm_scale=sm_scale, attn_fn=attn_fn)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)
