"""Fused LM-head cross-entropy: logits never materialize in HBM.

The LM head's (B, S, V) logits tensor is the largest intermediate of a
GPT-style train step (1.6 GB for GPT-2-small at B=16 even in bf16, in both
passes).  This kernel computes `mean(logsumexp(x W^T) - x W^T[target])`
with the logits living only in VMEM tiles: the forward streams vocab
blocks through an online logsumexp (same trick flash attention plays over
keys), and the backward recomputes each logits tile to form
`softmax - onehot` on the fly.

Cost model (why this is auto-gated, not the default, for GPT-2-small):
the fully-fused backward recomputes logits twice (once per dx / dW pass),
so the fused step runs 5 head-matmul passes against dense's 3 — and XLA
overlaps dense's logits HBM traffic with those matmuls, so the traffic is
only the binding cost when it EXCEEDS the matmul time.  Measured on v5e
(BENCH_FUSED_CE.json): at GPT-2-small's D=768 dense wins outright
(fused 0.48x); at D=128/V=64k the fusion wins 1.81x against dense-fp32
(exact softmax, traffic-bound) and 1.39x even against dense-bf16; and
when the logits tensor cannot materialize at all (64k tokens x 128k
vocab) the fusion is the only path that runs.  The cost model keeps a
conservative bf16 boundary (~D<120) — the D=128/bf16 row shows a
measured win just past it, deliberately left on dense by `auto`.
`fused_ce_wins` is this model made executable; models/gpt2.py's
loss_impl="auto" flips on it.  `bwd_impl="xla"` gives a middle point
(fused forward, one XLA recompute + materialized dlogits in the
backward).  All paths are equivalence-tested.

Ref: the reference has no analogue (torch materializes logits and calls
cross_entropy); this is a TPU-roofline-driven design, same family as
Liger's fused CE on GPU but built on the pallas grid/online-reduction
model instead of atomics.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401 — VMEM scratch


def _pick_block(n: int, candidates=(1024, 512, 256, 128, 64, 32, 16, 8)) -> int:
    for c in candidates:
        if n % c == 0 and c <= n:
            return c
    return n


# ----------------------------------------------------------------- forward
def _fwd_kernel(x_ref, w_ref, t_ref, lse_ref, tgt_ref, m_scr, s_scr, g_scr,
                *, bv: int, n_vb: int):
    vb = pl.program_id(1)

    @pl.when(vb == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, -jnp.inf, m_scr.dtype)
        s_scr[...] = jnp.zeros(s_scr.shape, s_scr.dtype)
        g_scr[...] = jnp.zeros(g_scr.shape, g_scr.dtype)

    logits = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)          # (bn, bv)
    m_prev = m_scr[...]                              # (bn, 1)
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1, keepdims=True))
    s_scr[...] = s_scr[...] * jnp.exp(m_prev - m_new) \
        + jnp.sum(jnp.exp(logits - m_new), axis=1, keepdims=True)
    m_scr[...] = m_new
    v_ids = vb * bv + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    g_scr[...] += jnp.sum(
        jnp.where(v_ids == t_ref[...], logits, 0.0), axis=1, keepdims=True)

    @pl.when(vb == n_vb - 1)
    def _done():
        lse_ref[...] = m_scr[...] + jnp.log(s_scr[...])
        tgt_ref[...] = g_scr[...]


def _fwd_pallas(x2, w, t2, bn: int, bv: int, interpret: bool):
    n, d = x2.shape
    v = w.shape[0]
    n_rb, n_vb = n // bn, v // bv
    kernel = functools.partial(_fwd_kernel, bv=bv, n_vb=n_vb)
    lse, tgt = pl.pallas_call(
        kernel,
        grid=(n_rb, n_vb),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
            pl.BlockSpec((bv, d), lambda i, j: (j, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bn, 1), jnp.float32),
            pltpu.VMEM((bn, 1), jnp.float32),
            pltpu.VMEM((bn, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2, w, t2)
    return lse, tgt


# ---------------------------------------------------------------- backward
def _dx_kernel(x_ref, w_ref, t_ref, lse_ref, dx_ref, *, bv: int):
    vb = pl.program_id(1)
    logits = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    p = jnp.exp(logits - lse_ref[...])
    v_ids = vb * bv + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    p = p - (v_ids == t_ref[...]).astype(jnp.float32)

    @pl.when(vb == 0)
    def _init():
        dx_ref[...] = jnp.zeros(dx_ref.shape, dx_ref.dtype)

    dx_ref[...] += jax.lax.dot_general(
        p.astype(w_ref.dtype), w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _dw_kernel(x_ref, w_ref, t_ref, lse_ref, dw_ref, *, bv: int):
    rb = pl.program_id(1)
    logits = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    p = jnp.exp(logits - lse_ref[...])
    vb = pl.program_id(0)
    v_ids = vb * bv + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    p = p - (v_ids == t_ref[...]).astype(jnp.float32)

    @pl.when(rb == 0)
    def _init():
        dw_ref[...] = jnp.zeros(dw_ref.shape, dw_ref.dtype)

    dw_ref[...] += jax.lax.dot_general(
        p.astype(x_ref.dtype), x_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _bwd_pallas(x2, w, t2, lse, bn: int, bv: int, interpret: bool):
    n, d = x2.shape
    v = w.shape[0]
    n_rb, n_vb = n // bn, v // bv
    dx = pl.pallas_call(
        functools.partial(_dx_kernel, bv=bv),
        grid=(n_rb, n_vb),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
            pl.BlockSpec((bv, d), lambda i, j: (j, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), jnp.float32),
        interpret=interpret,
    )(x2, w, t2, lse)
    dw = pl.pallas_call(
        functools.partial(_dw_kernel, bv=bv),
        grid=(n_vb, n_rb),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (j, 0)),
            pl.BlockSpec((bv, d), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (j, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((bv, d), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((v, d), jnp.float32),
        interpret=interpret,
    )(x2, w, t2, lse)
    return dx, dw


# ------------------------------------------------------------- public entry
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_ce(x2, w, t2, block_rows: int, bwd_impl: str):
    loss, _ = _fused_ce_fwd(x2, w, t2, block_rows, bwd_impl)
    return loss


def _blocks(x2, w, block_rows: int) -> Tuple[int, int]:
    # Largest legal (bn, bv) under a ~6 MiB working-set budget: the fp32
    # logits tile (bn*bv) plus the x/w tiles ((bn+bv)*d).  (1024, 1024)
    # measured fastest on v5e at d<=256; at d=512 that pair overflows VMEM
    # at compile (r5 sweep) and the budget steps bv down to 512.
    n, d = x2.shape
    v = w.shape[0]
    budget = 6 << 20
    for bn in (block_rows, 1024, 512, 256, 128, 64, 32, 16, 8):
        if bn > n or n % bn:
            continue
        for bv in (1024, 512, 256, 128, 64, 32, 16, 8):
            if bv > v or v % bv:
                continue
            if bn * bv * 4 + (bn + bv) * d * 4 <= budget:
                return bn, bv
    return (_pick_block(n, (128, 64, 32, 16, 8)),
            _pick_block(v, (128, 64, 32, 16, 8)))


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fused_ce_fwd(x2, w, t2, block_rows: int, bwd_impl: str):
    bn, bv = _blocks(x2, w, block_rows)
    lse, tgt = _fwd_pallas(x2, w, t2, bn, bv, _interpret())
    loss = jnp.mean(lse - tgt)
    return loss, (x2, w, t2, lse)


def _fused_ce_bwd(block_rows: int, bwd_impl: str, res, g):
    x2, w, t2, lse = res
    n = x2.shape[0]
    scale = (g / n).astype(jnp.float32)
    if bwd_impl == "pallas":
        bn, bv = _blocks(x2, w, block_rows)
        dx, dw = _bwd_pallas(x2, w, t2, lse, bn, bv, _interpret())
        dx = dx * scale
        dw = dw * scale
    else:  # "xla": one recompute, dlogits materializes (but fwd logits never did)
        logits = jax.lax.dot_general(
            x2, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        p = jnp.exp(logits - lse)
        onehot = jax.nn.one_hot(t2[:, 0], w.shape[0], dtype=jnp.float32)
        dlogits = ((p - onehot) * scale).astype(x2.dtype)
        dx = jax.lax.dot_general(
            dlogits, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dw = jax.lax.dot_general(
            dlogits, x2, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    return dx.astype(x2.dtype), dw.astype(w.dtype), None


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)


def fused_ce_wins(d_model: int, logits_dtype_bytes: int = 2,
                  matmul_eff: float = 0.5, peak_flops: float = 197e12,
                  hbm_bw: float = 819e9) -> bool:
    """Roofline cost model, overlap-aware (measured r5, BENCH_FUSED_CE):
    XLA overlaps the dense path's logits traffic with its matmuls, so per
    (token, vocab) element dense costs max(3 matmul passes, ~5
    bytes-per-logit of HBM) while fused costs 5 matmul passes (fwd + 2x
    bwd recompute + dx/dW) with zero logits traffic.  Fused therefore
    wins only when dense is TRAFFIC-bound and D is small enough:
    ~D<120 for bf16 logits, ~D<240 for fp32 — i.e. the exact-softmax
    (fp32) regime on small heads (measured 1.81x at D=128/V=64k), plus
    the absolute win when logits cannot materialize at all.
    GPT-2-small's D=768 correctly stays dense.  `auto` loss dispatch
    (models/gpt2.py loss_fn) flips on this."""
    per_elem = 2.0 * d_model / (matmul_eff * peak_flops)  # one matmul pass
    dense_s = max(3.0 * per_elem, 5.0 * logits_dtype_bytes / hbm_bw)
    fused_s = 5.0 * per_elem
    return fused_s < dense_s


def fused_lm_head_ce(x, wte, targets, block_rows: int = 1024,
                     bwd_impl: str = "pallas"):
    """Mean token cross-entropy of a tied LM head, logits never in HBM.

    x: (B, S, D) hidden states (any float dtype; matmuls run in x.dtype on
    the MXU with fp32 accumulation); wte: (V, D); targets: (B, S) int32.
    bwd_impl: "pallas" = fully fused backward (2x logits recompute, zero
    HBM logits); "xla" = single XLA recompute with materialized dlogits.
    """
    if bwd_impl not in ("pallas", "xla"):
        raise ValueError(f"bwd_impl must be pallas|xla, got {bwd_impl!r}")
    b, s, d = x.shape
    x2 = x.reshape(b * s, d)
    t2 = targets.reshape(b * s, 1).astype(jnp.int32)
    return _fused_ce(x2, wte.astype(x.dtype), t2, block_rows, bwd_impl)
