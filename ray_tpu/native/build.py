"""Builds the native C++ components into shared libraries.

Compilation happens on first import (g++ -O2 -shared), keyed by a content
hash of the sources so edits trigger rebuilds; the cached .so lives in
``ray_tpu/native/_build/``.  A CMakeLists.txt is provided for standalone
builds, but the in-tree path deliberately needs nothing beyond g++ so the
framework works in hermetic environments.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src")
_BUILD = os.path.join(_DIR, "_build")
_LOCK = threading.Lock()


def _source_hash(sources) -> str:
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def _compile(prefix: str, suffix: str, sources, flags) -> str:
    """Shared compile-with-cache path: <prefix><tag><suffix> in _build/,
    double-checked in-process lock, pid-suffixed tmp + atomic replace (safe
    under concurrent PROCESSES too), stale-artifact cleanup that never
    touches another process's in-flight .tmp output."""
    srcs = [os.path.join(_SRC, s) for s in sources]
    tag = _source_hash(srcs)
    out = os.path.join(_BUILD, f"{prefix}{tag}{suffix}")
    if os.path.exists(out):
        return out
    with _LOCK:
        if os.path.exists(out):
            return out
        os.makedirs(_BUILD, exist_ok=True)
        tmp = out + f".tmp{os.getpid()}"
        cmd = ["g++", "-O2", "-g", "-std=c++17", "-Wall", "-Werror",
               "-pthread", *flags, "-o", tmp, *srcs]
        # blocking_ok: compile-once cache; the lock exists to serialize builders
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)
        for f in os.listdir(_BUILD):
            if f.startswith(prefix) and f != os.path.basename(out) \
                    and ".tmp" not in f:
                try:
                    os.unlink(os.path.join(_BUILD, f))
                except OSError:
                    pass
    return out


def build_library(name: str, sources, extra_flags=()) -> str:
    """Compile `sources` (paths relative to src/) into lib<name>-<hash>.so and
    return its path. No-op when the cached artifact is current."""
    return _compile(f"lib{name}-", ".so", sources,
                    ("-shared", "-fPIC", *extra_flags))


def build_executable(name: str, sources, extra_flags=()) -> str:
    """Compile `sources` into a standalone binary (same caching scheme)."""
    return _compile(f"{name}-", "", sources, tuple(extra_flags))


def plasma_library() -> str:
    return build_library("tpuplasma", ["plasma.cc"])


def cpp_client_binary() -> str:
    """The C++ object-plane client demo binary (src/client.cc)."""
    return build_executable("ray_tpu_cpp_client", ["client.cc"],
                            extra_flags=("-DRAY_TPU_CLIENT_MAIN",))
