"""ctypes binding for the native shared-memory object store.

The C++ side (src/plasma.cc) owns allocation, the object table, locking, and
LRU eviction; this binding adds the Python-facing niceties: ids are hashed to
the fixed 20-byte wire form, payloads are exposed as zero-copy memoryviews
over one long-lived mmap of the arena, and `put_bytes`/`get_bytes` compose
create+seal / get for the common case.

Equivalent of the reference's plasma client (ref: src/ray/object_manager/
plasma/client.h) minus the socket protocol — clients here attach the arena
file directly (see plasma.cc header comment for why).
"""

from __future__ import annotations

import ctypes
import hashlib
import mmap
import os
from typing import Optional, Tuple

from ray_tpu.native.build import plasma_library

ID_LEN = 20


class PlasmaOOMError(MemoryError):
    """Create failed even after LRU eviction — caller should spill to disk."""


class PlasmaObjectExists(ValueError):
    pass


def _lib() -> ctypes.CDLL:
    lib = ctypes.CDLL(plasma_library())
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.tps_connect.restype = ctypes.c_void_p
    lib.tps_connect.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32, ctypes.c_int]
    lib.tps_disconnect.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p]
    lib.tps_create.restype = ctypes.c_int
    lib.tps_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, u64p]
    lib.tps_seal.restype = ctypes.c_int
    lib.tps_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tps_unseal.restype = ctypes.c_int
    lib.tps_unseal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tps_get.restype = ctypes.c_int
    lib.tps_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, u64p, u64p]
    lib.tps_release.restype = ctypes.c_int
    lib.tps_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tps_delete.restype = ctypes.c_int
    lib.tps_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tps_contains.restype = ctypes.c_int
    lib.tps_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tps_evict.restype = ctypes.c_uint64
    lib.tps_evict.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.tps_usage.argtypes = [ctypes.c_void_p, u64p, u64p, u64p]
    lib.tps_refcount.restype = ctypes.c_int64
    lib.tps_refcount.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    return lib


_LIB: Optional[ctypes.CDLL] = None


def _get_lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is None:
        _LIB = _lib()
    return _LIB


def object_key(object_id) -> bytes:
    """20-byte wire id from any hashable id (ObjectID, str, bytes)."""
    if isinstance(object_id, bytes) and len(object_id) == ID_LEN:
        return object_id
    raw = object_id if isinstance(object_id, bytes) else str(object_id).encode()
    return hashlib.sha1(raw).digest()


def default_arena_path(session_name: str) -> str:
    root = "/dev/shm" if os.path.isdir("/dev/shm") else "/tmp"
    return os.path.join(root, f"tpu_plasma_{session_name}")


class PlasmaClient:
    """One per process. The creating process passes create=True and owns the
    arena file's lifetime; workers attach with create=False."""

    def __init__(self, path: str, capacity: int = 0, *, create: bool,
                 max_entries: int = 1 << 16) -> None:
        self._lib = _get_lib()
        self.path = path
        self._owner = create
        if create and capacity <= 0:
            capacity = 1 << 30
        self._h = self._lib.tps_connect(path.encode(), capacity, max_entries, int(create))
        if not self._h:
            raise OSError(f"plasma connect failed (path={path}, create={create})")
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
        self._fd = os.open(path, os.O_RDWR)
        self._map = mmap.mmap(self._fd, size)
        self._view = memoryview(self._map)


    def _handle(self):
        h = self._h
        if not h:
            raise ConnectionError("plasma client is closed")
        return h

    # ------------------------------------------------------------- lifecycle
    def create(self, object_id, size: int) -> memoryview:
        """Allocate a writable buffer; write into it, then seal()."""
        off = ctypes.c_uint64()
        rc = self._lib.tps_create(self._handle(), object_key(object_id), size, ctypes.byref(off))
        if rc == -1:
            raise PlasmaObjectExists(f"{object_id} already in store")
        if rc == -2:
            raise PlasmaOOMError(f"no space for {size} bytes (after eviction)")
        if rc == -3:
            raise PlasmaOOMError("object table full")
        return self._view[off.value : off.value + size]

    def seal(self, object_id) -> None:
        if self._lib.tps_seal(self._handle(), object_key(object_id)) != 0:
            raise ValueError(f"seal failed for {object_id}")

    def unseal(self, object_id) -> None:
        """Reopen for in-place mutation (compiled-graph channels)."""
        if self._lib.tps_unseal(self._handle(), object_key(object_id)) != 0:
            raise ValueError(f"unseal failed for {object_id}")

    def get(self, object_id, timeout: Optional[float] = None) -> Optional[memoryview]:
        """Zero-copy view of a sealed object; increments its refcount.
        None on timeout. timeout=None blocks forever; 0 polls."""
        off, size = ctypes.c_uint64(), ctypes.c_uint64()
        tmo = -1 if timeout is None else max(0, int(timeout * 1000))
        rc = self._lib.tps_get(self._handle(), object_key(object_id), tmo,
                               ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            return None
        return self._view[off.value : off.value + size.value]

    def get_region(self, object_id,
                   timeout: Optional[float] = None) -> Optional[Tuple[int, int]]:
        """(arena-file offset, size) of a sealed object; increments its
        refcount like get() — release() when done.  Lets the object server
        ship payloads with ``os.sendfile`` straight from the tmpfs arena
        file (ref: the reference's object_buffer_pool.h chunk reader, minus
        its copy)."""
        off, size = ctypes.c_uint64(), ctypes.c_uint64()
        tmo = -1 if timeout is None else max(0, int(timeout * 1000))
        rc = self._lib.tps_get(self._handle(), object_key(object_id), tmo,
                               ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            return None
        return off.value, size.value

    @property
    def fd(self) -> int:
        """File descriptor of the mapped arena (for sendfile)."""
        return self._fd

    def view_at(self, offset: int, size: int) -> memoryview:
        """Raw view of an arena region (sendall fallback when sendfile is
        unavailable); caller must hold a get()/get_region() refcount."""
        return self._view[offset:offset + size]

    def release(self, object_id) -> None:
        self._lib.tps_release(self._handle(), object_key(object_id))

    def delete(self, object_id) -> bool:
        return self._lib.tps_delete(self._handle(), object_key(object_id)) == 0

    def contains(self, object_id) -> bool:
        return bool(self._lib.tps_contains(self._handle(), object_key(object_id)))

    def refcount(self, object_id) -> int:
        return int(self._lib.tps_refcount(self._handle(), object_key(object_id)))

    def evict(self, nbytes: int) -> int:
        return int(self._lib.tps_evict(self._handle(), nbytes))

    def usage(self) -> Tuple[int, int, int]:
        used, cap, objs = ctypes.c_uint64(), ctypes.c_uint64(), ctypes.c_uint64()
        self._lib.tps_usage(self._handle(), ctypes.byref(used), ctypes.byref(cap), ctypes.byref(objs))
        return used.value, cap.value, objs.value

    # ------------------------------------------------------------ composites
    def put_bytes(self, object_id, data) -> None:
        buf = self.create(object_id, len(data))
        buf[:] = data
        self.seal(object_id)

    def get_bytes(self, object_id, timeout: Optional[float] = None) -> Optional[bytes]:
        view = self.get(object_id, timeout)
        if view is None:
            return None
        try:
            return bytes(view)
        finally:
            view.release()
            self.release(object_id)

    def close(self, unlink: bool = False) -> None:
        if self._h:
            try:
                self._view.release()
                self._map.close()
                os.close(self._fd)
            except (BufferError, OSError):
                pass  # zero-copy views still alive; mapping stays until GC
            self._lib.tps_disconnect(
                self._h, int(unlink and self._owner), self.path.encode())
            self._h = None

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass
