// Minimal C++ client for the ray_tpu OBJECT PLANE.
//
// Scope (deliberate): connect to a runtime's object-transfer server and
// put/get/contains byte-valued objects over the same binary protocol the
// nodes use (ref framing: ray_tpu/_private/object_transfer.py — OP_PULL=1,
// OP_CONTAINS=2, OP_PUSH=3; values are the flat serialized form:
// u32 buffer_count, u64 data_len, [u64 sizes...], pickled data, buffers).
//
// For byte values the pickled payload is a tiny fixed shape this file emits
// and parses directly (PROTO 5 + SHORT_BINBYTES/BINBYTES/BINBYTES8 + STOP,
// tolerating FRAME/MEMOIZE) — no Python, no pickle library.  The full
// task/actor C++ API (ref: cpp/include/ray/api/api.h) is descoped; see
// README "Language frontends" for the rationale.
//
// Build (build.py cpp_client_binary() does this in-tree):
//   g++ -O2 -std=c++17 -DRAY_TPU_CLIENT_MAIN -o ray_tpu_cpp_client client.cc

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <stdexcept>
#include <string>
#include <vector>

namespace ray_tpu {

namespace {

constexpr uint8_t kOpPull = 1;
constexpr uint8_t kOpContains = 2;
constexpr uint8_t kOpPush = 3;
constexpr uint8_t kOpInvoke = 13;

constexpr uint8_t kStOk = 0;
constexpr uint8_t kStNotFound = 1;
constexpr uint8_t kStPending = 3;
constexpr uint8_t kStFailed = 4;

void write_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::write(fd, p, n);
    if (w <= 0) throw std::runtime_error("socket write failed");
    p += w;
    n -= static_cast<size_t>(w);
  }
}

void read_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) throw std::runtime_error("socket read failed / peer closed");
    p += r;
    n -= static_cast<size_t>(r);
  }
}

template <typename T>
void put_le(std::string* out, T v) {
  for (size_t i = 0; i < sizeof(T); i++)
    out->push_back(static_cast<char>((v >> (8 * i)) & 0xff));
}

template <typename T>
T get_le(const uint8_t* p) {
  T v = 0;
  for (size_t i = 0; i < sizeof(T); i++)
    v |= static_cast<T>(p[i]) << (8 * i);
  return v;
}

// op(1B) + u16 id_len + id — the request header every verb shares.
std::string header(uint8_t op, const std::string& id) {
  std::string out;
  out.push_back(static_cast<char>(op));
  put_le<uint16_t>(&out, static_cast<uint16_t>(id.size()));
  out += id;
  return out;
}

// pickle(bytes value): PROTO 5, (SHORT_)BINBYTES, STOP.
std::string pickle_bytes(const std::string& data) {
  std::string out("\x80\x05", 2);
  if (data.size() < 256) {
    out.push_back('C');
    out.push_back(static_cast<char>(data.size()));
  } else {
    out.push_back('B');
    put_le<uint32_t>(&out, static_cast<uint32_t>(data.size()));
  }
  out += data;
  out.push_back('.');
  return out;
}

// Inverse for the narrow bytes shape (FRAME/MEMOIZE tolerated: CPython's
// pickler emits them around the payload).
std::string unpickle_bytes(const uint8_t* p, size_t n) {
  size_t i = 0;
  std::string value;
  bool have_value = false;
  // All bounds checks use the "remaining = n - i" form: with i <= n it
  // cannot wrap, so a hostile/corrupt u64 length fails cleanly instead of
  // overflowing "i + len" and driving an out-of-bounds read.
  auto need = [&](size_t k) {
    if (n - i < k) throw std::runtime_error("truncated pickle");
  };
  while (i < n) {
    uint8_t op = p[i++];
    switch (op) {
      case 0x80:  // PROTO <1B>
        need(1);
        i += 1;
        break;
      case 0x95:  // FRAME <8B length>
        need(8);
        i += 8;
        break;
      case 0x94:  // MEMOIZE
        break;
      case 'C': {  // SHORT_BINBYTES <1B len>
        need(1);
        size_t len = p[i++];
        need(len);
        value.assign(reinterpret_cast<const char*>(p + i), len);
        have_value = true;
        i += len;
        break;
      }
      case 'B': {  // BINBYTES <u32 len>
        need(4);
        size_t len = get_le<uint32_t>(p + i);
        i += 4;
        need(len);
        value.assign(reinterpret_cast<const char*>(p + i), len);
        have_value = true;
        i += len;
        break;
      }
      case 0x8e: {  // BINBYTES8 <u64 len>
        need(8);
        uint64_t len = get_le<uint64_t>(p + i);
        i += 8;
        if (len > n - i) throw std::runtime_error("truncated pickle");
        value.assign(reinterpret_cast<const char*>(p + i),
                     static_cast<size_t>(len));
        have_value = true;
        i += static_cast<size_t>(len);
        break;
      }
      case '.':  // STOP
        if (!have_value)
          throw std::runtime_error("object is not a plain bytes value");
        return value;
      default:
        throw std::runtime_error(
            "object is not a plain bytes value (opcode " +
            std::to_string(op) + ")");
    }
  }
  throw std::runtime_error("pickle ended without STOP");
}

}  // namespace

// One connection to a runtime's object-transfer server.
class ObjectClient {
 public:
  ObjectClient(const std::string& host, int port) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) throw std::runtime_error("socket() failed");
    struct hostent* he = ::gethostbyname(host.c_str());
    if (he == nullptr) throw std::runtime_error("cannot resolve " + host);
    struct sockaddr_in addr;
    std::memset(&addr, 0, sizeof(addr));
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    std::memcpy(&addr.sin_addr, he->h_addr, he->h_length);
    if (::connect(fd_, reinterpret_cast<struct sockaddr*>(&addr),
                  sizeof(addr)) != 0)
      throw std::runtime_error("connect failed");
    int one = 1;
    ::setsockopt(fd_, IPPROTO_TCP, 1 /* TCP_NODELAY */, &one, sizeof(one));
  }

  ~ObjectClient() {
    if (fd_ >= 0) ::close(fd_);
  }

  bool contains(const std::string& id) {
    std::string req = header(kOpContains, id);
    write_all(fd_, req.data(), req.size());
    uint8_t st;
    read_all(fd_, &st, 1);
    return st == kStOk;
  }

  // Store a bytes value under `id`; Python readers see a `bytes` object.
  void put_bytes(const std::string& id, const std::string& data,
                 const std::string& owner = "") {
    std::string pickled = pickle_bytes(data);
    std::string flat;
    put_le<uint32_t>(&flat, 0);  // no out-of-band buffers
    put_le<uint64_t>(&flat, pickled.size());
    flat += pickled;
    std::string req = header(kOpPush, id);
    put_le<uint16_t>(&req, static_cast<uint16_t>(owner.size()));
    req += owner;
    put_le<uint64_t>(&req, flat.size());
    req += flat;
    write_all(fd_, req.data(), req.size());
    uint8_t st;
    read_all(fd_, &st, 1);
    if (st != kStOk) throw std::runtime_error("push rejected");
  }

  // Fetch the bytes value stored under `id` (retries while the producer is
  // still running — ST_PENDING — up to `attempts`).
  std::string get_bytes(const std::string& id, int attempts = 100) {
    for (int k = 0; k < attempts; k++) {
      std::string req = header(kOpPull, id);
      write_all(fd_, req.data(), req.size());
      uint8_t st;
      read_all(fd_, &st, 1);
      if (st == kStPending) {
        ::usleep(100 * 1000);
        continue;
      }
      if (st == kStNotFound) throw std::runtime_error("object not found");
      uint8_t len8[8];
      read_all(fd_, len8, 8);
      uint64_t len = get_le<uint64_t>(len8);
      std::vector<uint8_t> payload(len);
      if (len > 0) read_all(fd_, payload.data(), len);
      if (st == kStFailed)
        throw std::runtime_error("producing task failed on the owner");
      if (st != kStOk) throw std::runtime_error("unexpected status");
      // Unwrap the flat form (overflow-safe: compare against remaining).
      if (len < 12) throw std::runtime_error("short payload");
      uint32_t nbuf = get_le<uint32_t>(payload.data());
      uint64_t dlen = get_le<uint64_t>(payload.data() + 4);
      if (nbuf != 0)
        throw std::runtime_error(
            "value carries out-of-band buffers (not a plain bytes object)");
      if (dlen > len - 12) throw std::runtime_error("corrupt payload");
      return unpickle_bytes(payload.data() + 12,
                            static_cast<size_t>(dlen));
    }
    throw std::runtime_error("object still pending after retries");
  }

  // Cross-language task submission: run a DRIVER-REGISTERED function by
  // name with a raw-bytes payload (ref: the reference's C++ task API,
  // cpp/include/ray/api/ — reduced to the name-registry model a
  // pickle-framed control plane admits).  Returns the result's ObjectID;
  // pull it with get_bytes (which retries while the task runs).
  std::string invoke(const std::string& fn_name, const std::string& payload) {
    std::string req = header(kOpInvoke, "");
    put_le<uint16_t>(&req, static_cast<uint16_t>(fn_name.size()));
    req += fn_name;
    put_le<uint64_t>(&req, payload.size());
    req += payload;
    write_all(fd_, req.data(), req.size());
    uint8_t st;
    read_all(fd_, &st, 1);
    if (st == kStNotFound)
      throw std::runtime_error("no function registered under that name");
    if (st != kStOk) throw std::runtime_error("invoke rejected");
    uint8_t len2[2];
    read_all(fd_, len2, 2);
    uint16_t n = get_le<uint16_t>(len2);
    std::string rid(n, '\0');
    if (n > 0) read_all(fd_, &rid[0], n);
    return rid;
  }

 private:
  int fd_ = -1;
};

}  // namespace ray_tpu

#ifdef RAY_TPU_CLIENT_MAIN
#include <cstdio>

// Demo/interop binary: pull one object, push one object, verify contains;
// optionally submit a registered function as a task and print its result.
//   ray_tpu_cpp_client <host> <port> <get_id> <put_id> [fn_name payload]
int main(int argc, char** argv) {
  if (argc != 5 && argc != 7) {
    std::fprintf(stderr, "usage: %s host port get_id put_id [fn payload]\n",
                 argv[0]);
    return 2;
  }
  try {
    ray_tpu::ObjectClient client(argv[1], std::atoi(argv[2]));
    std::string pulled = client.get_bytes(argv[3]);
    std::printf("PULLED %zu %s\n", pulled.size(), pulled.c_str());
    std::string payload = "hello-from-cpp-" + std::to_string(::getpid());
    client.put_bytes(argv[4], payload, "cpp-client");
    if (!client.contains(argv[4])) {
      std::fprintf(stderr, "pushed object missing\n");
      return 1;
    }
    std::printf("PUSHED %s %s\n", argv[4], payload.c_str());
    if (argc == 7) {
      std::string rid = client.invoke(argv[5], argv[6]);
      std::string result = client.get_bytes(rid);
      std::printf("INVOKED %s %s\n", rid.c_str(), result.c_str());
    }
    return 0;
  } catch (const std::exception& e) {
    std::fprintf(stderr, "error: %s\n", e.what());
    return 1;
  }
}
#endif  // RAY_TPU_CLIENT_MAIN
