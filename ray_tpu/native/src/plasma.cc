// TPU-host shared-memory object store ("plasma" tier).
//
// Native C++ equivalent of the reference's per-node plasma store
// (ref: src/ray/object_manager/plasma/store.h:55,
//       object_lifecycle_manager.h, eviction_policy.h, dlmalloc.cc) —
// redesigned for the TPU worker model: every process on the host (driver +
// process-tier workers) maps ONE shared arena file and talks to the store
// through lock-protected shared state *inside the arena itself*, instead of
// the reference's unix-socket + fd-passing protocol (plasma/fling.cc).  That
// removes the store server process entirely: on a TPU host the driver owns
// the chips and the store is a library, not a daemon.
//
// Layout of the arena file (mmap'd MAP_SHARED by every client):
//
//   [ Header | ObjectEntry table (open addressing) | heap ............ ]
//
// * Header holds a PTHREAD_PROCESS_SHARED + ROBUST mutex and condvar: the
//   robust attribute keeps the store usable when a worker process dies while
//   holding the lock (the reference gets the same property from the store
//   being a separate process).
// * Allocation is a boundary-tag first-fit heap with coalescing — the same
//   job dlmalloc does for the reference (plasma/dlmalloc.cc), small enough
//   to audit.
// * Eviction is LRU over sealed, unreferenced objects
//   (ref: plasma/eviction_policy.h) and runs inline inside create() when the
//   heap is full (ref: plasma/create_request_queue.h queues creates under
//   pressure; here the caller falls back to disk spilling when create still
//   fails after eviction).
//
// Object lifecycle: CREATED (writable by creator) -> SEALED (immutable,
// readable by all; get() blocks on the condvar until seal) -> deleted when
// refcount hits zero and delete/evict is requested.  Mutable re-open for
// compiled-graph channels is tps_unseal (ref:
// core_worker/experimental_mutable_object_manager.h).

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x54505553544f5245ULL;  // "TPUSTORE"
constexpr uint32_t kVersion = 2;
constexpr uint32_t kIdLen = 20;
constexpr uint32_t kBlockMagic = 0xb10cb10c;
constexpr uint64_t kAlign = 64;  // cacheline; also keeps numpy views aligned

// ---------------------------------------------------------------- shm layout

struct ObjectEntry {
  uint8_t id[kIdLen];
  uint8_t state;   // 0 empty, 1 created, 2 sealed, 3 tombstone
  uint8_t in_lru;  // member of the evictable LRU list
  uint32_t refcount;
  uint64_t offset;  // data offset from arena base
  uint64_t size;
  uint64_t lru_tick;
  uint32_t lru_next;  // entry index + 1; 0 = none
  uint32_t lru_prev;
};

enum EntryState : uint8_t { kEmpty = 0, kCreated = 1, kSealed = 2, kTomb = 3 };

struct Header {
  uint64_t magic;
  uint32_t version;
  uint32_t max_entries;
  uint64_t capacity;     // total file size
  uint64_t heap_offset;  // from base
  uint64_t heap_size;
  uint64_t bytes_in_use;  // payload bytes of live objects
  uint64_t num_objects;
  uint64_t lru_clock;
  uint64_t free_head;  // offset of first free block, 0 = none
  uint32_t lru_head;   // evictable (sealed, refcount==0) entries, LRU first;
  uint32_t lru_tail;   // entry index + 1, 0 = none
  pthread_mutex_t mutex;
  pthread_cond_t cond;
};

// Boundary tag kept immediately before each payload; free blocks embed the
// free-list links in their (unused) payload.
struct BlockHeader {
  uint64_t size;       // payload bytes (excludes header)
  uint64_t prev_size;  // payload size of the block physically before us
  uint32_t magic;
  uint32_t free;
};

struct FreeLinks {  // lives at payload[0] of free blocks
  uint64_t next;    // arena offsets of BlockHeaders; 0 = end
  uint64_t prev;
};

struct Client {
  uint8_t* base;
  Header* hdr;
  ObjectEntry* table;
  uint64_t mapped_size;
  int fd;
  int owner;
};

inline uint64_t align_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

inline BlockHeader* block_at(Client* c, uint64_t off) {
  return reinterpret_cast<BlockHeader*>(c->base + off);
}
inline uint64_t payload_off(uint64_t block_off) { return block_off + sizeof(BlockHeader); }
inline FreeLinks* links_of(Client* c, uint64_t block_off) {
  return reinterpret_cast<FreeLinks*>(c->base + payload_off(block_off));
}

// ------------------------------------------------------------------- locking

// Robust lock: if a worker died holding the mutex, adopt and repair it.
int lock(Client* c) {
  int rc = pthread_mutex_lock(&c->hdr->mutex);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&c->hdr->mutex);
    rc = 0;
  }
  return rc;
}
void unlock(Client* c) { pthread_mutex_unlock(&c->hdr->mutex); }

// ----------------------------------------------------------------- allocator

void freelist_push(Client* c, uint64_t block_off) {
  BlockHeader* b = block_at(c, block_off);
  b->free = 1;
  FreeLinks* l = links_of(c, block_off);
  l->next = c->hdr->free_head;
  l->prev = 0;
  if (c->hdr->free_head) links_of(c, c->hdr->free_head)->prev = block_off;
  c->hdr->free_head = block_off;
}

void freelist_remove(Client* c, uint64_t block_off) {
  FreeLinks* l = links_of(c, block_off);
  if (l->prev)
    links_of(c, l->prev)->next = l->next;
  else
    c->hdr->free_head = l->next;
  if (l->next) links_of(c, l->next)->prev = l->prev;
  block_at(c, block_off)->free = 0;
}

uint64_t next_block_off(Client* c, uint64_t block_off) {
  BlockHeader* b = block_at(c, block_off);
  uint64_t n = block_off + sizeof(BlockHeader) + b->size;
  uint64_t end = c->hdr->heap_offset + c->hdr->heap_size;
  return (n + sizeof(BlockHeader) <= end) ? n : 0;
}

uint64_t prev_block_off(Client* c, uint64_t block_off) {
  BlockHeader* b = block_at(c, block_off);
  if (b->prev_size == 0 && block_off == c->hdr->heap_offset) return 0;
  uint64_t p = block_off - sizeof(BlockHeader) - b->prev_size;
  return (p >= c->hdr->heap_offset) ? p : 0;
}

// First-fit allocate `want` payload bytes; returns block offset or 0.
uint64_t heap_alloc(Client* c, uint64_t want) {
  want = align_up(want < sizeof(FreeLinks) ? sizeof(FreeLinks) : want, kAlign);
  uint64_t off = c->hdr->free_head;
  while (off) {
    BlockHeader* b = block_at(c, off);
    if (b->size >= want) {
      freelist_remove(c, off);
      uint64_t leftover = b->size - want;
      if (leftover >= sizeof(BlockHeader) + align_up(sizeof(FreeLinks), kAlign)) {
        // split: carve the tail into a new free block
        b->size = want;
        uint64_t tail_off = off + sizeof(BlockHeader) + want;
        BlockHeader* tail = block_at(c, tail_off);
        tail->size = leftover - sizeof(BlockHeader);
        tail->prev_size = want;
        tail->magic = kBlockMagic;
        freelist_push(c, tail_off);
        uint64_t after = next_block_off(c, tail_off);
        if (after) block_at(c, after)->prev_size = tail->size;
      }
      return off;
    }
    off = links_of(c, off)->next;
  }
  return 0;
}

void heap_free(Client* c, uint64_t block_off) {
  BlockHeader* b = block_at(c, block_off);
  // coalesce forward
  uint64_t n = next_block_off(c, block_off);
  if (n && block_at(c, n)->free) {
    freelist_remove(c, n);
    b->size += sizeof(BlockHeader) + block_at(c, n)->size;
  }
  // coalesce backward
  uint64_t p = prev_block_off(c, block_off);
  if (p && block_at(c, p)->free) {
    freelist_remove(c, p);
    block_at(c, p)->size += sizeof(BlockHeader) + b->size;
    block_off = p;
    b = block_at(c, block_off);
  }
  freelist_push(c, block_off);
  uint64_t after = next_block_off(c, block_off);
  if (after) block_at(c, after)->prev_size = b->size;
}

// ------------------------------------------------------------------ LRU list
// Intrusive doubly-linked list of evictable entries (sealed, refcount==0),
// head = least recent (ref: plasma/eviction_policy.h).  O(1) maintenance on
// seal/get/release beats a full table scan per eviction victim.

inline ObjectEntry* entry_at(Client* c, uint32_t idx1) {
  return idx1 ? &c->table[idx1 - 1] : nullptr;
}

void lru_push_mru(Client* c, ObjectEntry* e) {
  if (e->in_lru) return;
  e->in_lru = 1;
  uint32_t me = (uint32_t)(e - c->table) + 1;
  e->lru_prev = c->hdr->lru_tail;
  e->lru_next = 0;
  if (c->hdr->lru_tail) entry_at(c, c->hdr->lru_tail)->lru_next = me;
  c->hdr->lru_tail = me;
  if (!c->hdr->lru_head) c->hdr->lru_head = me;
}

void lru_remove(Client* c, ObjectEntry* e) {
  if (!e->in_lru) return;
  e->in_lru = 0;
  if (e->lru_prev) entry_at(c, e->lru_prev)->lru_next = e->lru_next;
  else c->hdr->lru_head = e->lru_next;
  if (e->lru_next) entry_at(c, e->lru_next)->lru_prev = e->lru_prev;
  else c->hdr->lru_tail = e->lru_prev;
  e->lru_next = e->lru_prev = 0;
}

// -------------------------------------------------------------- object table

uint64_t id_hash(const uint8_t* id) {
  // FNV-1a over the 20-byte id
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdLen; ++i) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

// Find entry for id; if absent and want_insert, claim a slot. Returns null if
// the table is full or the id is absent (and !want_insert).
ObjectEntry* table_find(Client* c, const uint8_t* id, bool want_insert) {
  uint32_t n = c->hdr->max_entries;
  uint64_t h = id_hash(id) % n;
  ObjectEntry* first_tomb = nullptr;
  for (uint32_t probe = 0; probe < n; ++probe) {
    ObjectEntry* e = &c->table[(h + probe) % n];
    if (e->state == kEmpty) {
      if (!want_insert) return nullptr;
      ObjectEntry* slot = first_tomb ? first_tomb : e;
      std::memcpy(slot->id, id, kIdLen);
      return slot;
    }
    if (e->state == kTomb) {
      if (!first_tomb) first_tomb = e;
      continue;
    }
    if (std::memcmp(e->id, id, kIdLen) == 0) return e;
  }
  if (want_insert && first_tomb) {
    std::memcpy(first_tomb->id, id, kIdLen);
    return first_tomb;
  }
  return nullptr;
}

void entry_delete(Client* c, ObjectEntry* e) {
  lru_remove(c, e);
  heap_free(c, e->offset - sizeof(BlockHeader));
  c->hdr->bytes_in_use -= e->size;
  c->hdr->num_objects -= 1;
  e->state = kTomb;
  e->refcount = 0;
  e->offset = e->size = 0;
}

// Evict LRU sealed refcount==0 objects until >= want bytes of payload are
// freed (ref: plasma/eviction_policy.h LRU). Caller holds lock.
uint64_t evict_locked(Client* c, uint64_t want) {
  uint64_t freed = 0;
  while (freed < want && c->hdr->lru_head) {
    ObjectEntry* victim = entry_at(c, c->hdr->lru_head);
    freed += victim->size;
    entry_delete(c, victim);  // removes from the list
  }
  return freed;
}

}  // namespace

extern "C" {

// Create or attach the arena at `path`. `create`!=0 initializes a fresh
// store of `capacity` bytes (total file size). Returns handle or null.
void* tps_connect(const char* path, uint64_t capacity, uint32_t max_entries,
                  int create) {
  int fd = open(path, create ? (O_RDWR | O_CREAT) : O_RDWR, 0600);
  if (fd < 0) return nullptr;

  if (create) {
    if (ftruncate(fd, (off_t)capacity) != 0) {
      close(fd);
      return nullptr;
    }
  } else {
    struct stat st;
    if (fstat(fd, &st) != 0 || st.st_size < (off_t)sizeof(Header)) {
      close(fd);
      return nullptr;
    }
    capacity = (uint64_t)st.st_size;
  }

  void* base = mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }

  Client* c = new Client();
  c->base = (uint8_t*)base;
  c->hdr = (Header*)base;
  c->mapped_size = capacity;
  c->fd = fd;
  c->owner = create;

  if (create) {
    if (max_entries == 0) max_entries = 1 << 16;
    Header* h = c->hdr;
    std::memset(h, 0, sizeof(Header));
    h->magic = kMagic;
    h->version = kVersion;
    h->max_entries = max_entries;
    h->capacity = capacity;
    uint64_t table_off = align_up(sizeof(Header), kAlign);
    uint64_t table_bytes = (uint64_t)max_entries * sizeof(ObjectEntry);
    h->heap_offset = align_up(table_off + table_bytes, kAlign);
    if (h->heap_offset + sizeof(BlockHeader) + kAlign > capacity) {
      munmap(base, capacity);
      close(fd);
      delete c;
      return nullptr;
    }
    h->heap_size = capacity - h->heap_offset;
    c->table = (ObjectEntry*)(c->base + table_off);
    std::memset(c->table, 0, table_bytes);

    pthread_mutexattr_t ma;
    pthread_mutexattr_init(&ma);
    pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&h->mutex, &ma);
    pthread_condattr_t ca;
    pthread_condattr_init(&ca);
    pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
    pthread_cond_init(&h->cond, &ca);

    // one big free block spanning the heap
    BlockHeader* b = block_at(c, h->heap_offset);
    b->size = h->heap_size - sizeof(BlockHeader);
    b->prev_size = 0;
    b->magic = kBlockMagic;
    h->free_head = 0;
    freelist_push(c, h->heap_offset);
  } else {
    if (c->hdr->magic != kMagic || c->hdr->version != kVersion) {
      munmap(base, capacity);
      close(fd);
      delete c;
      return nullptr;
    }
    uint64_t table_off = align_up(sizeof(Header), kAlign);
    c->table = (ObjectEntry*)(c->base + table_off);
  }
  return c;
}

void tps_disconnect(void* h, int unlink_file, const char* path) {
  Client* c = (Client*)h;
  if (!c) return;
  munmap(c->base, c->mapped_size);
  close(c->fd);
  if (unlink_file && path) unlink(path);
  delete c;
}

// Create a writable object of `size` payload bytes. On success returns 0 and
// sets *out_off (arena offset of payload). -1 id exists, -2 out of memory
// (even after eviction), -3 table full.
int tps_create(void* h, const uint8_t* id, uint64_t size, uint64_t* out_off) {
  Client* c = (Client*)h;
  lock(c);
  ObjectEntry* existing = table_find(c, id, false);
  if (existing && existing->state != kTomb) {
    unlock(c);
    return -1;
  }
  uint64_t block = heap_alloc(c, size);
  if (!block) {
    evict_locked(c, size + sizeof(BlockHeader));
    block = heap_alloc(c, size);
  }
  if (!block) {
    unlock(c);
    return -2;
  }
  ObjectEntry* e = table_find(c, id, true);
  if (!e) {
    heap_free(c, block);
    unlock(c);
    return -3;
  }
  e->state = kCreated;
  e->in_lru = 0;
  e->lru_next = e->lru_prev = 0;
  e->refcount = 1;  // creator's reference
  e->offset = payload_off(block);
  e->size = size;
  e->lru_tick = ++c->hdr->lru_clock;
  c->hdr->bytes_in_use += size;
  c->hdr->num_objects += 1;
  *out_off = e->offset;
  unlock(c);
  return 0;
}

// Seal: object becomes immutable + visible to get(). Wakes blocked getters.
int tps_seal(void* h, const uint8_t* id) {
  Client* c = (Client*)h;
  lock(c);
  ObjectEntry* e = table_find(c, id, false);
  if (!e || e->state != kCreated) {
    unlock(c);
    return -1;
  }
  e->state = kSealed;
  if (e->refcount == 0) lru_push_mru(c, e);
  pthread_cond_broadcast(&c->hdr->cond);
  unlock(c);
  return 0;
}

// Re-open a sealed object for in-place mutation (compiled-graph channels,
// ref: experimental_mutable_object_manager.h). Requires sole ownership
// (refcount of the caller's reference only).
int tps_unseal(void* h, const uint8_t* id) {
  Client* c = (Client*)h;
  lock(c);
  ObjectEntry* e = table_find(c, id, false);
  if (!e || e->state != kSealed) {
    unlock(c);
    return -1;
  }
  if (e->refcount != 1) {  // enforce sole ownership: no readers' live views
    unlock(c);
    return -2;
  }
  e->state = kCreated;
  unlock(c);
  return 0;
}

// Blocking get: waits until sealed (timeout_ms < 0 = forever, 0 = poll).
// On success refcount++ and returns 0 with payload offset/size.
// -1 = not found & not created yet and timeout hit (or poll miss).
int tps_get(void* h, const uint8_t* id, int64_t timeout_ms, uint64_t* out_off,
            uint64_t* out_size) {
  Client* c = (Client*)h;
  struct timespec abst;
  if (timeout_ms > 0) {
    clock_gettime(CLOCK_REALTIME, &abst);
    abst.tv_sec += timeout_ms / 1000;
    abst.tv_nsec += (timeout_ms % 1000) * 1000000L;
    if (abst.tv_nsec >= 1000000000L) {
      abst.tv_sec += 1;
      abst.tv_nsec -= 1000000000L;
    }
  }
  lock(c);
  for (;;) {
    ObjectEntry* e = table_find(c, id, false);
    if (e && e->state == kSealed) {
      if (e->refcount == 0) lru_remove(c, e);  // no longer evictable
      e->refcount += 1;
      e->lru_tick = ++c->hdr->lru_clock;
      *out_off = e->offset;
      *out_size = e->size;
      unlock(c);
      return 0;
    }
    if (timeout_ms == 0) {
      unlock(c);
      return -1;
    }
    int rc;
    if (timeout_ms > 0)
      rc = pthread_cond_timedwait(&c->hdr->cond, &c->hdr->mutex, &abst);
    else
      rc = pthread_cond_wait(&c->hdr->cond, &c->hdr->mutex);
    if (rc == ETIMEDOUT) {
      unlock(c);
      return -1;
    }
  }
}

int tps_release(void* h, const uint8_t* id) {
  Client* c = (Client*)h;
  lock(c);
  ObjectEntry* e = table_find(c, id, false);
  if (!e || e->state == kTomb || e->state == kEmpty) {
    unlock(c);
    return -1;
  }
  if (e->refcount > 0) {
    e->refcount -= 1;
    if (e->refcount == 0 && e->state == kSealed) lru_push_mru(c, e);
  }
  unlock(c);
  return 0;
}

// Delete now if unreferenced; sealed+referenced objects are deleted lazily by
// eviction once released (ref: object_lifecycle_manager.h eager deletion).
int tps_delete(void* h, const uint8_t* id) {
  Client* c = (Client*)h;
  lock(c);
  ObjectEntry* e = table_find(c, id, false);
  if (!e || e->state == kTomb || e->state == kEmpty) {
    unlock(c);
    return -1;
  }
  if (e->refcount > 0) {
    unlock(c);
    return -2;
  }
  entry_delete(c, e);
  unlock(c);
  return 0;
}

int tps_contains(void* h, const uint8_t* id) {
  Client* c = (Client*)h;
  lock(c);
  ObjectEntry* e = table_find(c, id, false);
  int r = (e && e->state == kSealed) ? 1 : 0;
  unlock(c);
  return r;
}

uint64_t tps_evict(void* h, uint64_t nbytes) {
  Client* c = (Client*)h;
  lock(c);
  uint64_t freed = evict_locked(c, nbytes);
  unlock(c);
  return freed;
}

void tps_usage(void* h, uint64_t* used, uint64_t* capacity, uint64_t* objects) {
  Client* c = (Client*)h;
  lock(c);
  *used = c->hdr->bytes_in_use;
  *capacity = c->hdr->heap_size;
  *objects = c->hdr->num_objects;
  unlock(c);
}

// Refcount of an object, or -1 if absent. Test/introspection hook.
int64_t tps_refcount(void* h, const uint8_t* id) {
  Client* c = (Client*)h;
  lock(c);
  ObjectEntry* e = table_find(c, id, false);
  int64_t r = (e && e->state != kTomb && e->state != kEmpty) ? (int64_t)e->refcount : -1;
  unlock(c);
  return r;
}

}  // extern "C"
