"""Native C++ components (built on demand, cached in _build/).

- plasma: shared-memory object store arena (src/plasma.cc)
"""
