"""Actor classes and handles (ref: python/ray/actor.py — ActorClass:602,
ActorClass._remote:890, ActorHandle:1265).

``@ray_tpu.remote`` on a class yields an ActorClass; ``.remote(...)``
schedules creation (resources held for the actor's lifetime) and returns an
ActorHandle whose method stubs submit ordered actor tasks.  Handles are
serializable — they travel through the object store by actor id, like the
reference's handles travel by actor id + GCS lookup.
"""

from __future__ import annotations

import inspect
from typing import Any, Dict, Optional

from ray_tpu._private.ids import ActorID, TaskID
from ray_tpu._private.option_utils import resolve_task_options
from ray_tpu._private.runtime import get_runtime
from ray_tpu._private.task_spec import ActorSpec, TaskSpec


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 options: Optional[Dict[str, Any]] = None):
        self._handle = handle
        self._method_name = method_name
        self._options = options or {}

    def options(self, **opts) -> "ActorMethod":
        merged = dict(self._options)
        merged.update(opts)
        return ActorMethod(self._handle, self._method_name, merged)

    def remote(self, *args, **kwargs):
        return self._handle._submit_method(self._method_name, args, kwargs, self._options)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ActorMethodNode

        return ActorMethodNode(self._handle, self._method_name, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._method_name}' cannot be called directly; "
            f"use .remote()."
        )


class ActorHandle:
    def __init__(self, actor_id: ActorID, cls: type, max_task_retries: int = 0):
        self._actor_id = ActorID(actor_id)
        self._cls = cls
        self._max_task_retries = max_task_retries

    @property
    def _ray_actor_id(self) -> ActorID:
        return self._actor_id

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        if not callable(getattr(self._cls, name, None)):
            raise AttributeError(f"{self._cls.__name__} has no method '{name}'")
        return ActorMethod(self, name)

    def _submit_method(self, method_name: str, args, kwargs, options: Dict[str, Any]):
        runtime = get_runtime()
        method = getattr(self._cls, method_name)
        num_returns = options.get("num_returns", 1)
        generator = inspect.isgeneratorfunction(method) or num_returns in ("dynamic", "streaming")
        if not isinstance(num_returns, int):
            num_returns = 1
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            name=f"{self._cls.__name__}.{method_name}",
            func=method,
            args=args,
            kwargs=kwargs,
            num_returns=num_returns,
            resources={},
            strategy=None,
            max_retries=options.get("max_task_retries", self._max_task_retries),
            actor_id=self._actor_id,
            method_name=method_name,
            generator=generator,
        )
        return runtime.submit_actor_task(self._actor_id, spec)

    def __reduce__(self):
        return (_rebuild_handle, (str(self._actor_id), self._cls, self._max_task_retries))

    def __repr__(self) -> str:
        return f"ActorHandle({self._cls.__name__}, {self._actor_id})"

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id


def _rebuild_handle(actor_id: str, cls: type, max_task_retries: int) -> ActorHandle:
    return ActorHandle(ActorID(actor_id), cls, max_task_retries)


class ActorClass:
    def __init__(self, cls: type, default_options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._default_options = default_options or {}
        self.__name__ = cls.__name__

    def options(self, **options) -> "ActorClass":
        merged = dict(self._default_options)
        merged.update(options)
        return ActorClass(self._cls, merged)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self.__name__}' cannot be instantiated directly; "
            f"use {self.__name__}.remote()."
        )

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._remote(args, kwargs, **self._default_options)

    def _remote(self, args, kwargs, **options) -> ActorHandle:
        runtime = get_runtime()
        opts = resolve_task_options(options, is_actor=True)
        if opts["isolation"] == "process" or opts.get("runtime_env"):
            has_async = any(
                inspect.iscoroutinefunction(getattr(self._cls, m, None))
                for m in dir(self._cls)
                if not m.startswith("__") or m == "__call__")
            if has_async:
                # Fail at creation, not as an opaque ActorDiedError on the
                # first method call from the background start thread.
                raise ValueError(
                    "async actors cannot use isolation='process' or a "
                    "runtime_env (the dedicated worker runs methods "
                    "synchronously)")
        actor_id = ActorID.from_random()
        spec = ActorSpec(
            actor_id=actor_id,
            name=opts.get("name"),
            namespace=opts.get("namespace") or runtime.namespace,
            cls=self._cls,
            args=args,
            kwargs=kwargs,
            resources=opts["resources"],
            strategy=opts["scheduling_strategy"],
            max_restarts=opts["max_restarts"],
            max_task_retries=opts["max_task_retries"],
            max_concurrency=opts["max_concurrency"],
            isolation=opts["isolation"],
            lifetime=opts["lifetime"],
            concurrency_groups=opts.get("concurrency_groups"),
            runtime_env=opts.get("runtime_env"),
        )
        runtime.create_actor(spec)
        return ActorHandle(actor_id, self._cls, opts["max_task_retries"])

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ClassNode

        return ClassNode(self, args, kwargs)


def exit_actor() -> None:
    """Terminate the current actor from inside a method (ref: ray.actor.exit_actor)."""
    from ray_tpu._private.runtime import _ActorExit, current_task_context

    ctx = current_task_context()
    if ctx is None or ctx.actor_id is None:
        raise RuntimeError("exit_actor() called outside an actor method")
    raise _ActorExit()
