"""CLI: `python -m ray_tpu <command>`.

Counterpart of the reference's `ray` CLI surface (ref:
python/ray/scripts/scripts.py `ray status`/`ray start`,
util/state/state_cli.py `ray list/summary`, _private/state.py timeline).
`start --head` runs the standalone head daemon, `worker` joins it as a
node, `up/down` drive cluster YAML through the autoscaler layer.

Note: each invocation starts a fresh runtime in this process, so the
list/summary commands are mainly useful inside a driver (via
`ray_tpu.util.state`) or against a script run with `python -m ray_tpu run`.
"""

from __future__ import annotations

import argparse
import json
import sys


def _init(args):
    import ray_tpu

    ray_tpu.init(ignore_reinit_error=True)
    return ray_tpu


def _print_cluster_snapshot(snap: dict) -> None:
    total = snap["cluster_resources"]
    avail = snap["available_resources"]
    print("======== Cluster status ========")
    print("Resources")
    print("---------------------------------------------------------------")
    print("Usage:")
    for name in sorted(total):
        used = total[name] - avail.get(name, 0.0)
        print(f" {used:g}/{total[name]:g} {name}")
    per_node = snap.get("per_node") or []
    print(f"Nodes ({len(per_node)}):")
    for row in per_node:
        role = "head  " if row.get("is_head") else "worker"
        extras = []
        if row.get("num_actors") is not None:
            extras.append(f"actors={row['num_actors']}")
        if row.get("store_bytes_used") is not None:
            extras.append(f"store={row['store_bytes_used']}B")
        if row.get("heartbeat_age_s") is not None:
            extras.append(f"hb={row['heartbeat_age_s']}s")
        print(f" {role} {row['node_id']} alive={row.get('alive')} "
              f"res={row.get('resources')} {' '.join(extras)}")


def cmd_status(args) -> int:
    if getattr(args, "dashboard", None):
        # Query a LIVE cluster's aggregating head instead of starting a
        # fresh runtime in this process (ref: `ray status` against GCS).
        import json as _json
        import urllib.request

        base = args.dashboard.rstrip("/")
        if "://" not in base:
            base = "http://" + base  # accept bare host:port
        try:
            with urllib.request.urlopen(base + "/api/cluster",
                                        timeout=10) as resp:
                snap = _json.loads(resp.read())
        except (OSError, ValueError) as e:
            print(f"cannot reach dashboard at {base}: {e}", file=sys.stderr)
            return 1
        _print_cluster_snapshot(snap)
        return 0
    _init(args)
    from ray_tpu._private.metrics_agent import cluster_snapshot
    from ray_tpu._private.runtime import get_runtime

    _print_cluster_snapshot(cluster_snapshot(get_runtime()))
    return 0


def cmd_list(args) -> int:
    _init(args)
    from ray_tpu.util import state

    fns = {
        "tasks": state.list_tasks, "actors": state.list_actors,
        "objects": state.list_objects, "nodes": state.list_nodes,
        "placement-groups": state.list_placement_groups,
    }
    rows = fns[args.entity](limit=args.limit)
    print(json.dumps(rows, indent=2, default=str))
    return 0


def cmd_summary(args) -> int:
    _init(args)
    from ray_tpu.util import state

    fns = {"tasks": state.summarize_tasks, "actors": state.summarize_actors,
           "objects": state.summarize_objects}
    print(json.dumps(fns[args.entity](), indent=2, default=str))
    return 0


def cmd_timeline(args) -> int:
    import ray_tpu

    ray_tpu.init(ignore_reinit_error=True)
    ray_tpu.timeline(args.output)
    print(f"wrote {args.output}")
    return 0


def cmd_metrics(args) -> int:
    """Start a runtime and print the Prometheus scrape output once."""
    _init(args)
    from ray_tpu._private.metrics_agent import sample_runtime
    from ray_tpu._private.runtime import get_runtime
    from ray_tpu.util import metrics

    sample_runtime(get_runtime())
    print(metrics.registry().prometheus_text())
    return 0


def cmd_job(args) -> int:
    """`ray job submit/status/logs/list/stop` equivalents (ref:
    dashboard/modules/job/cli.py).  Jobs live for the manager's process
    lifetime, so `submit --wait` is the useful CLI mode; long-lived managers
    belong in a driver via ray_tpu.job.job_manager()."""
    from ray_tpu.job import job_manager

    jm = job_manager()
    if args.job_cmd == "submit":
        import shlex

        parts = list(args.entrypoint)
        if parts and parts[0] == "--":  # REMAINDER keeps the separator
            parts = parts[1:]
        job_id = jm.submit_job(shlex.join(parts),
                               submission_id=args.submission_id)
        print(f"submitted {job_id}")
        if args.wait:
            for chunk in jm.tail_job_logs(job_id):
                sys.stdout.write(chunk)
            status = jm.get_job_status(job_id)
            print(f"job {job_id}: {status}")
            return 0 if status == "SUCCEEDED" else 1
        return 0
    if args.job_cmd == "list":
        print(json.dumps([j.to_dict() for j in jm.list_jobs()], indent=2))
        return 0
    print("status/logs/stop need a long-lived manager; use the Python API",
          file=sys.stderr)
    return 1


def cmd_logs(args) -> int:
    """Tail per-job log files straight from disk (ref:
    _private/log_monitor.py:103 tailing session logs + `ray job logs`).
    Works without a live job manager: logs outlive the driver."""
    import os
    import time

    from ray_tpu.job.job_manager import default_log_root

    log_root = default_log_root()
    if not args.job_id:
        if not os.path.isdir(log_root):
            print(f"no job logs under {log_root}")
            return 1
        for name in sorted(os.listdir(log_root)):
            if name.endswith(".log"):
                path = os.path.join(log_root, name)
                print(f"{name[:-4]}  {os.path.getsize(path):>10} bytes  {path}")
        return 0
    path = os.path.join(log_root, f"{args.job_id}.log")
    if not os.path.exists(path):
        print(f"no log file for job {args.job_id} ({path})", file=sys.stderr)
        return 1
    with open(path, "rb") as f:
        sys.stdout.write(f.read().decode(errors="replace"))
        sys.stdout.flush()
        if not args.follow:
            return 0
        try:
            while True:
                chunk = f.read()
                if chunk:
                    sys.stdout.write(chunk.decode(errors="replace"))
                    sys.stdout.flush()
                else:
                    time.sleep(0.25)
        except KeyboardInterrupt:
            return 0


def cmd_run(args) -> int:
    """Run a driver script with ray_tpu importable (ref: `ray job submit`'s
    local path; full job manager lives in ray_tpu.job)."""
    import runpy

    sys.argv = [args.script] + args.script_args
    runpy.run_path(args.script, run_name="__main__")
    return 0


def cmd_up(args) -> int:
    """`ray up` equivalent (ref: autoscaler/_private/commands.py create_or_
    update_cluster): head + min workers + reconciler from a cluster YAML.

    Clusters here are IN-PROCESS (virtual scheduler nodes / TPU slices), so
    the cluster lives exactly as long as this command: the default mode
    blocks, reconciling until Ctrl-C tears it down.  ``--no-block`` is for
    scripting/tests (validate + bring up + exit, releasing everything).
    """
    from ray_tpu.autoscaler.launcher import launch_cluster

    handle = launch_cluster(args.config, autoscale=not args.no_autoscale)
    status = handle.status()
    print(f"cluster {status['cluster_name']!r} up: "
          f"{status['nodes']} nodes, resources={status['resources']}")
    if args.no_block:
        print("--no-block: cluster validated; it ends with this process "
              "(use launch_cluster() from Python to drive one "
              "programmatically)")
        handle.teardown()
        return 0
    print("reconciling; Ctrl-C tears the cluster down")
    import time as _t

    try:
        while True:
            _t.sleep(5)
            s = handle.status()
            print(f"[reconcile] nodes={s['nodes']} workers={s['workers']}")
    except KeyboardInterrupt:
        handle.teardown()
        print("cluster torn down")
    return 0


def cmd_down(args) -> int:
    """In-process clusters end with their `up` process; this command only
    tears down a runtime living in THIS process (programmatic use)."""
    from ray_tpu._private.runtime import runtime_or_none

    import ray_tpu

    if runtime_or_none() is None:
        print("no live runtime in this process — a `ray_tpu up` cluster "
              "ends when its process does (Ctrl-C it)")
        return 1
    ray_tpu.shutdown()
    print("cluster torn down")
    return 0


def cmd_stack(args) -> int:
    """`ray stack` equivalent: this process's threads + any process workers
    of a runtime living here (cross-process runtimes expose the same dump
    via the metrics agent's /api/stacks)."""
    import ray_tpu  # noqa: F401 — ensures package import side effects
    from ray_tpu._private import stack_profiler

    print(stack_profiler.format_stacks(stack_profiler.collect_all_stacks()))
    return 0


def cmd_memory(args) -> int:
    from ray_tpu._private import heap_profiler

    print(heap_profiler.format_heap(heap_profiler.heap_summary(args.top)))
    return 0


def cmd_start(args) -> int:
    """Standalone head daemon: the control plane with NO driver attached
    (ref: `ray start --head`, python/ray/scripts/scripts.py:start — GCS +
    raylet as long-lived services).  Drivers come and go over ray://
    (client server); worker nodes join over the node server; state persists
    to --session-dir so a kill -9'd head restarts in place and nodes
    re-register (node_manager.py:_try_rejoin)."""
    if not args.head:
        print("only `start --head` is supported; worker nodes join with "
              "`ray_tpu worker --address=...`", file=sys.stderr)
        return 2
    import os as _os
    import signal
    import threading

    import ray_tpu
    from ray_tpu.util.client import ClientServer

    sysconf = None
    if args.session_dir:
        sysconf = {"kv_persist": True, "session_dir": args.session_dir}
    runtime = ray_tpu.init(num_cpus=args.num_cpus,
                           resources=json.loads(args.resources)
                           if args.resources else None,
                           _system_config=sysconf)
    node_addr = runtime.start_node_server(port=args.port)
    client = ClientServer(port=args.client_port)
    from ray_tpu._private.metrics_agent import MetricsAgent

    dash = MetricsAgent(runtime, port=args.dashboard_port,
                        host=args.dashboard_host)
    dash_url = f"http://{args.dashboard_host}:{dash.port}"
    if args.session_dir:
        _os.makedirs(args.session_dir, exist_ok=True)
        with open(_os.path.join(args.session_dir, "head_address.json"),
                  "w") as f:
            json.dump({"node_address": node_addr,
                       "client_address": client.address,
                       "dashboard_url": dash_url,
                       "pid": _os.getpid()}, f)
    print(f"HEAD node-address={node_addr} "
          f"client-address={client.address} dashboard={dash_url}", flush=True)
    print("READY", flush=True)

    done = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, lambda *_: done.set())
        except ValueError:
            pass
    done.wait()
    dash.stop()
    client.stop()
    ray_tpu.shutdown()
    return 0


def cmd_worker(args) -> int:
    """Join a head as a worker node and serve dispatches until the head
    hangs up (ref: `ray start --address=...` joining a cluster).

    ``--host`` is the interface this node's OBJECT SERVER binds and
    advertises (the address peers pull results from) — it must be
    reachable from the head and the other nodes; the 127.0.0.1 default
    only works for single-machine clusters.  The head has the matching
    knob: RAY_TPU_OBJECT_TRANSFER_HOST + start_node_server(host=...).
    """
    import json as _json

    # Ops hook: `kill -USR1 <pid>` dumps all thread stacks to stderr
    # (the reference's `ray stack` for remote nodes).
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1, all_threads=True)

    if args.host:
        import os as _os

        _os.environ["RAY_TPU_OBJECT_TRANSFER_HOST"] = args.host
    from ray_tpu._private.node_manager import WorkerNode

    resources = _json.loads(args.resources) if args.resources else None
    labels = dict(kv.split("=", 1) for kv in (args.labels or []))
    node = WorkerNode(args.address, num_cpus=args.num_cpus,
                      resources=resources, labels=labels or None,
                      node_id=args.node_id)
    print(f"NODE {node.node_id} JOINED {args.address}", flush=True)
    node.serve_forever()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ray_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    stat = sub.add_parser("status", help="cluster resource usage")
    stat.add_argument("--dashboard", default=None,
                      help="query a live head's dashboard URL instead of "
                           "starting a runtime here")

    lp = sub.add_parser("list", help="list entities (state API)")
    lp.add_argument("entity", choices=["tasks", "actors", "objects", "nodes",
                                       "placement-groups"])
    lp.add_argument("--limit", type=int, default=100)

    sp = sub.add_parser("summary", help="summarize entities")
    sp.add_argument("entity", choices=["tasks", "actors", "objects"])

    tp = sub.add_parser("timeline", help="export chrome-tracing timeline")
    tp.add_argument("--output", "-o", default="timeline.json")

    sub.add_parser("metrics", help="print Prometheus metrics once")

    lg = sub.add_parser("logs", help="print/tail a job's log file")
    lg.add_argument("job_id", nargs="?", help="job id (omit to list logs)")
    lg.add_argument("--follow", "-f", action="store_true")

    jp = sub.add_parser("job", help="job submission")
    jsub = jp.add_subparsers(dest="job_cmd", required=True)
    jsp = jsub.add_parser("submit")
    jsp.add_argument("--submission-id", default=None)
    jsp.add_argument("--wait", action="store_true",
                     help="stream logs and wait for completion")
    jsp.add_argument("entrypoint", nargs=argparse.REMAINDER)
    jsub.add_parser("list")

    rp = sub.add_parser("run", help="run a driver script")
    rp.add_argument("script")
    rp.add_argument("script_args", nargs=argparse.REMAINDER)

    up = sub.add_parser("up", help="launch a cluster from a YAML config "
                                   "(in-process; blocks until Ctrl-C)")
    up.add_argument("config", help="cluster YAML path")
    up.add_argument("--no-autoscale", action="store_true")
    up.add_argument("--no-block", action="store_true",
                    help="validate + bring up + exit (cluster ends with "
                         "this process)")

    down = sub.add_parser("down", help="tear down the cluster in this session")
    down.add_argument("config", nargs="?", help="cluster YAML (informational)")

    sub.add_parser("stack", help="dump stacks of driver threads + process "
                                 "workers (ref: `ray stack` / py-spy)")

    mem = sub.add_parser("memory", help="heap profile via tracemalloc "
                                        "(ref: dashboard memray profiling)")
    mem.add_argument("--top", type=int, default=20)

    st = sub.add_parser("start", help="start a standalone head daemon "
                                      "(ref: ray start --head)")
    st.add_argument("--head", action="store_true",
                    help="run the head control plane (required)")
    st.add_argument("--port", type=int, default=0,
                    help="node-manager port worker nodes join on")
    st.add_argument("--client-port", type=int, default=0,
                    help="ray:// client-server port drivers attach to")
    st.add_argument("--num-cpus", type=float, default=None)
    st.add_argument("--resources", default=None,
                    help='JSON dict of custom resources on the head')
    st.add_argument("--session-dir", default=None,
                    help="persist control-plane state here (WAL KV); a "
                         "restarted head over the same dir restores it")
    st.add_argument("--dashboard-port", type=int, default=0,
                    help="HTTP port for the aggregating dashboard "
                         "(/ = cluster view, /node/<id> = drilldown)")
    st.add_argument("--dashboard-host", default="127.0.0.1",
                    help="interface the dashboard binds AND advertises "
                         "(loopback default = single-machine; use a "
                         "cluster-reachable address for remote `status "
                         "--dashboard` queries)")

    wk = sub.add_parser("worker", help="join a head as a worker node "
                                       "(ref: ray start --address)")
    wk.add_argument("--address", required=True, help="head node-manager "
                                                     "host:port")
    wk.add_argument("--host", default=None,
                    help="interface this node's object server binds AND "
                         "advertises to peers (default 127.0.0.1 — "
                         "single-machine only; use the host's cluster-"
                         "reachable address for multi-machine)")
    wk.add_argument("--num-cpus", type=float, default=None)
    wk.add_argument("--resources", default=None,
                    help='JSON dict of custom resources, e.g. \'{"gpu0": 1}\'')
    wk.add_argument("--labels", nargs="*", default=None,
                    help="node labels as key=value")
    wk.add_argument("--node-id", default=None)

    args = p.parse_args(argv)
    return {
        "status": cmd_status, "list": cmd_list, "summary": cmd_summary,
        "timeline": cmd_timeline, "metrics": cmd_metrics, "job": cmd_job,
        "logs": cmd_logs, "run": cmd_run, "up": cmd_up, "down": cmd_down,
        "stack": cmd_stack, "memory": cmd_memory, "worker": cmd_worker,
        "start": cmd_start,
    }[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
