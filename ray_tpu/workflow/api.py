"""Durable workflow execution (ref: python/ray/workflow/workflow_executor.py,
workflow_storage.py, workflow_state_from_dag.py).

``run(dag, workflow_id=...)`` executes a ``bind()``-built DAG with every
FunctionNode step checkpointed to storage the moment it completes.  Step ids
are content-derived (function identity + constant args + upstream step ids),
so ``resume(workflow_id)`` replays the saved results of finished steps and
recomputes only the rest — exactly-once per successful step, even across
driver crashes (the DAG and inputs are persisted at submission).

Storage layout (filesystem; root via init_storage() or RAY_TPU_WORKFLOW_ROOT):
  <root>/<workflow_id>/workflow.json       — status + metadata
  <root>/<workflow_id>/dag.pkl             — pickled DAG + inputs (for resume)
  <root>/<workflow_id>/steps/<step_id>.pkl — pickled step results
  <root>/<workflow_id>/output.pkl          — final result
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import serialization
from ray_tpu.dag.dag_node import (
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
)


class WorkflowStatus:
    RUNNING = "RUNNING"
    SUCCESSFUL = "SUCCESSFUL"
    FAILED = "FAILED"
    CANCELED = "CANCELED"
    RESUMABLE = "RESUMABLE"


_storage_root: Optional[str] = None
_lock = threading.Lock()


def init_storage(path: str) -> None:
    """Set the workflow storage root (ref: workflow.init storage arg)."""
    global _storage_root
    _storage_root = os.path.abspath(path)
    os.makedirs(_storage_root, exist_ok=True)


def _root() -> str:
    global _storage_root
    if _storage_root is None:
        init_storage(os.environ.get(
            "RAY_TPU_WORKFLOW_ROOT",
            os.path.join(os.path.expanduser("~"), ".ray_tpu", "workflows")))
    return _storage_root


_WF_ID_RE = None


def _wf_dir(workflow_id: str) -> str:
    global _WF_ID_RE
    if _WF_ID_RE is None:
        import re

        # No separators, no "..": ids must stay inside the storage root
        # (delete("..") would otherwise rmtree the root's parent).
        _WF_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.-]*$")
    if not _WF_ID_RE.match(workflow_id) or ".." in workflow_id:
        raise ValueError(f"invalid workflow id: {workflow_id!r}")
    return os.path.join(_root(), workflow_id)


def _atomic_write(path: str, data: bytes) -> None:
    tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:6]}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _write_meta(wf_dir: str, **updates) -> dict:
    meta_path = os.path.join(wf_dir, "workflow.json")
    meta = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    meta.update(updates)
    _atomic_write(meta_path, json.dumps(meta, indent=2).encode())
    return meta


def _read_meta(wf_dir: str) -> dict:
    with open(os.path.join(wf_dir, "workflow.json")) as f:
        return json.load(f)


# ----------------------------------------------------------------- step ids
def _const_digest(h, value) -> None:
    # Primitives digest via repr; everything else via pickle bytes — a
    # default object repr embeds the memory address, which would change the
    # step id across processes and silently break resume's exactly-once
    # replay.  Unpicklable constants fail loudly (the DAG must pickle for
    # dag.pkl anyway).
    if isinstance(value, (str, int, float, bool, bytes, type(None))):
        h.update(repr(value).encode())
    else:
        h.update(serialization.dumps(value))


def _step_ids(dag: DAGNode) -> Dict[int, str]:
    """Content-derived step id per node: function identity + constant args +
    upstream ids (ref: workflow_state_from_dag.py deterministic step names)."""
    ids: Dict[int, str] = {}
    for node in dag._topo():
        h = hashlib.sha1()
        if isinstance(node, InputNode):
            h.update(b"input")
        elif isinstance(node, InputAttributeNode):
            h.update(f"input[{node._key!r}]".encode())
        elif isinstance(node, FunctionNode):
            fn = node._remote_fn._function
            h.update(f"{fn.__module__}.{fn.__qualname__}".encode())
            code = getattr(fn, "__code__", None)
            if code is not None:
                h.update(code.co_code)
        else:
            raise TypeError(
                f"workflows support function steps and InputNode, got "
                f"{type(node).__name__} (actor nodes are not durable)")
        for a in node._bound_args:
            if isinstance(a, DAGNode):
                h.update(ids[id(a)].encode())
            else:
                _const_digest(h, a)
        for k in sorted(node._bound_kwargs):
            v = node._bound_kwargs[k]
            h.update(k.encode())
            if isinstance(v, DAGNode):
                h.update(ids[id(v)].encode())
            else:
                _const_digest(h, v)
        ids[id(node)] = h.hexdigest()[:16]
    # Disambiguate identical bind() calls (same fn, same args): they are
    # distinct steps — sharing one checkpoint would replay one draw of a
    # non-deterministic step as both.  Topo order is deterministic for a
    # given DAG, so the occurrence suffix is stable across resume.
    seen: Dict[str, int] = {}
    for node in dag._topo():
        base = ids[id(node)]
        n = seen.get(base, 0)
        seen[base] = n + 1
        if n:
            ids[id(node)] = f"{base}-{n}"
    return ids


# ---------------------------------------------------------------- execution
def _run_step_and_checkpoint(ckpt_path: str, fn, *args, **kwargs):
    """Runs INSIDE the step task: the checkpoint is durably written before
    the step's result becomes visible to any downstream step, so a driver
    (or downstream) crash can never lose a completed step — the
    exactly-once property resume depends on."""
    value = fn(*args, **kwargs)
    _atomic_write(ckpt_path, serialization.dumps(value))
    return value


def _execute(wf_dir: str, dag: DAGNode, input_args: tuple,
             input_kwargs: dict) -> Any:
    import ray_tpu

    steps_dir = os.path.join(wf_dir, "steps")
    os.makedirs(steps_dir, exist_ok=True)
    ids = _step_ids(dag)
    order = dag._topo()

    # Pass 1: per node, either load its checkpoint or submit it (wrapped in
    # the checkpoint runner) with its upstream refs/values — independent
    # branches run in parallel, and ObjectRef args are resolved by the
    # runtime before execution.
    pending: Dict[int, Any] = {}  # id(node) -> ObjectRef
    values: Dict[int, Any] = {}   # id(node) -> concrete value

    def resolved(node):
        args = tuple(
            values[id(a)] if isinstance(a, DAGNode) and id(a) in values
            else pending[id(a)] if isinstance(a, DAGNode) else a
            for a in node._bound_args)
        kwargs = {
            k: (values[id(v)] if isinstance(v, DAGNode) and id(v) in values
                else pending[id(v)] if isinstance(v, DAGNode) else v)
            for k, v in node._bound_kwargs.items()}
        return args, kwargs

    from ray_tpu.remote_function import RemoteFunction

    for node in order:
        if isinstance(node, InputNode):
            values[id(node)] = node._execute_impl({}, input_args, input_kwargs)
        elif isinstance(node, InputAttributeNode):
            values[id(node)] = (input_args[node._key]
                                if isinstance(node._key, int)
                                else input_kwargs[node._key])
        else:  # FunctionNode
            ckpt = os.path.join(steps_dir, f"{ids[id(node)]}.pkl")
            if os.path.exists(ckpt):
                with open(ckpt, "rb") as f:
                    values[id(node)] = serialization.loads(f.read())
                continue
            args, kwargs = resolved(node)
            runner = RemoteFunction(_run_step_and_checkpoint,
                                    dict(node._remote_fn._default_options))
            pending[id(node)] = runner.remote(
                ckpt, node._remote_fn._function, *args, **kwargs)

    # Pass 2: drain in topo order (results were checkpointed step-side).
    for node in order:
        if id(node) in values or id(node) not in pending:
            continue
        if os.path.exists(os.path.join(wf_dir, "cancel")):
            _write_meta(wf_dir, status=WorkflowStatus.CANCELED,
                        finished_at=time.time())
            raise WorkflowCancelledError(os.path.basename(wf_dir))
        values[id(node)] = ray_tpu.get(pending.pop(id(node)))

    return values[id(dag)]


class WorkflowCancelledError(RuntimeError):
    pass


def _run_persisted(wf_dir: str) -> Any:
    """Execute (or re-execute) from the persisted DAG + inputs."""
    with open(os.path.join(wf_dir, "dag.pkl"), "rb") as f:
        dag, input_args, input_kwargs = serialization.loads(f.read())
    _write_meta(wf_dir, status=WorkflowStatus.RUNNING, started_at=time.time())
    try:
        result = _execute(wf_dir, dag, input_args, input_kwargs)
    except WorkflowCancelledError:
        raise
    except BaseException as e:  # noqa: BLE001
        _write_meta(wf_dir, status=WorkflowStatus.FAILED, error=repr(e),
                    finished_at=time.time())
        raise
    _atomic_write(os.path.join(wf_dir, "output.pkl"),
                  serialization.dumps(result))
    _write_meta(wf_dir, status=WorkflowStatus.SUCCESSFUL,
                finished_at=time.time())
    return result


# ---------------------------------------------------------------- public API
def run(dag: DAGNode, *args, workflow_id: Optional[str] = None,
        **kwargs) -> Any:
    """Run a DAG durably; blocks until the result (ref: workflow.run)."""
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:12]}"
    _step_ids(dag)  # validate the DAG (rejects actor nodes) before persisting
    wf_dir = _wf_dir(workflow_id)
    os.makedirs(wf_dir, exist_ok=True)
    # Persist the program before executing, so a crashed run is resumable.
    _atomic_write(os.path.join(wf_dir, "dag.pkl"),
                  serialization.dumps((dag, args, kwargs)))
    _write_meta(wf_dir, workflow_id=workflow_id, created_at=time.time(),
                status=WorkflowStatus.RUNNING)
    return _run_persisted(wf_dir)


def run_async(dag: DAGNode, *args, workflow_id: Optional[str] = None,
              **kwargs):
    """Like run() but returns a concurrent.futures.Future."""
    import concurrent.futures

    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:12]}"
    ex = concurrent.futures.ThreadPoolExecutor(max_workers=1)
    fut = ex.submit(run, dag, *args, workflow_id=workflow_id, **kwargs)
    fut.workflow_id = workflow_id  # type: ignore[attr-defined]
    ex.shutdown(wait=False)
    return fut


def resume(workflow_id: str) -> Any:
    """Resume a crashed/failed/canceled workflow: finished steps replay from
    their checkpoints; only unfinished steps execute (ref: workflow.resume)."""
    wf_dir = _wf_dir(workflow_id)
    if not os.path.exists(os.path.join(wf_dir, "dag.pkl")):
        raise ValueError(f"no such workflow: {workflow_id}")
    cancel_marker = os.path.join(wf_dir, "cancel")
    if os.path.exists(cancel_marker):
        os.remove(cancel_marker)
    return _run_persisted(wf_dir)


def get_status(workflow_id: str) -> str:
    return _read_meta(_wf_dir(workflow_id))["status"]


def get_output(workflow_id: str) -> Any:
    """The persisted final result of a successful run."""
    out = os.path.join(_wf_dir(workflow_id), "output.pkl")
    if not os.path.exists(out):
        status = get_status(workflow_id)
        raise ValueError(
            f"workflow {workflow_id} has no output (status={status})")
    with open(out, "rb") as f:
        return serialization.loads(f.read())


def list_all(status_filter: Optional[str] = None) -> List[Tuple[str, str]]:
    """[(workflow_id, status)] (ref: workflow.list_all)."""
    out = []
    root = _root()
    for wf_id in sorted(os.listdir(root)):
        meta_path = os.path.join(root, wf_id, "workflow.json")
        if not os.path.exists(meta_path):
            continue
        with open(meta_path) as f:
            status = json.load(f).get("status", "UNKNOWN")
        if status_filter is None or status == status_filter:
            out.append((wf_id, status))
    return out


def cancel(workflow_id: str) -> None:
    """Request cancellation: the executor stops before its next step and
    marks the workflow CANCELED (running steps finish)."""
    wf_dir = _wf_dir(workflow_id)
    if not os.path.isdir(wf_dir):
        raise ValueError(f"no such workflow: {workflow_id}")
    with open(os.path.join(wf_dir, "cancel"), "w") as f:
        f.write(str(time.time()))


def delete(workflow_id: str) -> None:
    import shutil

    wf_dir = _wf_dir(workflow_id)
    if os.path.isdir(wf_dir):
        shutil.rmtree(wf_dir)
