"""Workflows: durable DAG execution with per-step checkpointing.

Counterpart of the reference's `ray.workflow` (ref: python/ray/workflow/ —
workflow_executor.py, workflow_state_from_dag.py, workflow_storage.py):
`workflow.run(dag, workflow_id=...)` executes a `bind()`-built DAG with
every step's result checkpointed to storage the moment it completes; if the
driver dies mid-flow, `workflow.resume(workflow_id)` replays from the saved
step results instead of recomputing them (exactly-once per successful step).
Step semantics: retries with `max_retries`, exceptions recorded as workflow
failure, steps addressed by a content-derived step id.

Storage layout (filesystem, pluggable root):
  <root>/<workflow_id>/workflow.json       — status + DAG metadata
  <root>/<workflow_id>/steps/<step_id>.pkl — pickled step results
"""

from ray_tpu.workflow.api import (
    WorkflowStatus,
    cancel,
    delete,
    get_output,
    get_status,
    init_storage,
    list_all,
    resume,
    run,
    run_async,
)

__all__ = [
    "WorkflowStatus", "cancel", "delete", "get_output", "get_status",
    "init_storage", "list_all", "resume", "run", "run_async",
]
