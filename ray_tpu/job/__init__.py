from ray_tpu.job.job_manager import JobInfo, JobManager, JobStatus, job_manager

__all__ = ["JobManager", "JobInfo", "JobStatus", "job_manager"]
