"""Job submission: run driver scripts as supervised subprocesses.

Counterpart of the reference's job subsystem (ref: dashboard/modules/job/ —
JobManager:59 in job_manager.py, JobSupervisor:54 in job_supervisor.py, `ray
job` CLI in cli.py): submit an entrypoint, get a job id back immediately,
poll status, stream logs from the per-job log file, stop the job.  The
supervisor role (a detached actor in the reference) is a monitor thread per
job here; drivers are real OS processes so a crashing job can't take the
submitter down, and each job gets the runtime-env treatment (env_vars /
working_dir) via its process environment.
"""

from __future__ import annotations

import os
import signal
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@dataclass
class JobInfo:
    job_id: str
    entrypoint: str
    status: str = JobStatus.PENDING
    message: str = ""
    start_time: float = 0.0
    end_time: Optional[float] = None
    log_path: str = ""
    metadata: Dict[str, str] = field(default_factory=dict)
    return_code: Optional[int] = None

    def to_dict(self) -> dict:
        return dict(self.__dict__)


class JobManager:
    def __init__(self, log_root: Optional[str] = None):
        from ray_tpu._private.config import GLOBAL_CONFIG

        self._jobs: Dict[str, JobInfo] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()
        self._log_root = log_root or default_log_root()
        os.makedirs(self._log_root, exist_ok=True)

    # ---------------------------------------------------------------- submit
    def submit_job(self, entrypoint: str,
                   runtime_env: Optional[dict] = None,
                   metadata: Optional[Dict[str, str]] = None,
                   submission_id: Optional[str] = None) -> str:
        """Start `entrypoint` (a shell command) as a supervised subprocess.

        Returns the job id immediately (ref: JobManager.submit_job)."""
        job_id = submission_id or f"raytpu-job-{uuid.uuid4().hex[:10]}"
        with self._lock:
            if job_id in self._jobs:
                raise ValueError(f"job {job_id} already exists")
            info = JobInfo(
                job_id=job_id, entrypoint=entrypoint,
                log_path=os.path.join(self._log_root, f"{job_id}.log"),
                metadata=dict(metadata or {}))
            self._jobs[job_id] = info

        env = dict(os.environ)
        cwd = None
        if runtime_env:
            from ray_tpu._private.runtime_env import RuntimeEnv

            renv = RuntimeEnv.normalize(runtime_env)
            staged = renv.stage()
            env.update(staged.get("env_vars", {}))
            if staged.get("working_dir"):
                cwd = staged["working_dir"]
            if staged.get("py_modules"):
                extra = os.pathsep.join(staged["py_modules"])
                env["PYTHONPATH"] = extra + os.pathsep + env.get("PYTHONPATH", "")
        env["RAY_TPU_JOB_ID"] = job_id

        log_f = open(info.log_path, "wb")
        try:
            proc = subprocess.Popen(
                entrypoint, shell=True, stdout=log_f, stderr=subprocess.STDOUT,
                env=env, cwd=cwd, start_new_session=True)
        except OSError as e:
            log_f.close()
            with self._lock:
                info.status = JobStatus.FAILED
                info.message = f"failed to start: {e}"
                info.end_time = time.time()
            return job_id
        with self._lock:
            info.status = JobStatus.RUNNING
            info.start_time = time.time()
            self._procs[job_id] = proc
        threading.Thread(target=self._supervise, args=(job_id, proc, log_f),
                         name=f"job-supervisor-{job_id}", daemon=True).start()
        return job_id

    def _supervise(self, job_id: str, proc: subprocess.Popen, log_f) -> None:
        """The JobSupervisor role: wait for exit, record the outcome."""
        rc = proc.wait()
        log_f.close()
        with self._lock:
            info = self._jobs[job_id]
            self._procs.pop(job_id, None)
            info.end_time = time.time()
            info.return_code = rc
            if info.status == JobStatus.STOPPED:
                return
            if rc == 0:
                info.status = JobStatus.SUCCEEDED
            else:
                info.status = JobStatus.FAILED
                info.message = f"exit code {rc}"

    # ----------------------------------------------------------------- query
    def get_job_status(self, job_id: str) -> str:
        return self._get(job_id).status

    def get_job_info(self, job_id: str) -> JobInfo:
        return self._get(job_id)

    def list_jobs(self) -> List[JobInfo]:
        with self._lock:
            return list(self._jobs.values())

    def get_job_logs(self, job_id: str) -> str:
        info = self._get(job_id)
        try:
            with open(info.log_path, "r", errors="replace") as f:
                return f.read()
        except FileNotFoundError:
            return ""

    def tail_job_logs(self, job_id: str, poll_s: float = 0.2):
        """Generator of log chunks until the job reaches a terminal state
        (ref: `ray job logs -f`)."""
        info = self._get(job_id)
        pos = 0
        while True:
            try:
                with open(info.log_path, "r", errors="replace") as f:
                    f.seek(pos)
                    chunk = f.read()
                    pos = f.tell()
            except FileNotFoundError:
                chunk = ""
            if chunk:
                yield chunk
            if self.get_job_status(job_id) in JobStatus.TERMINAL and not chunk:
                return
            time.sleep(poll_s)

    def wait_job(self, job_id: str, timeout: Optional[float] = None) -> str:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            status = self.get_job_status(job_id)
            if status in JobStatus.TERMINAL:
                return status
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"job {job_id} still {status}")
            time.sleep(0.05)

    # ------------------------------------------------------------------ stop
    def stop_job(self, job_id: str, grace_s: float = 3.0) -> bool:
        """SIGTERM the job's process group, SIGKILL after grace
        (ref: JobSupervisor.stop)."""
        with self._lock:
            info = self._jobs.get(job_id)
            proc = self._procs.get(job_id)
            if info is None:
                raise ValueError(f"no such job {job_id}")
            if proc is None or proc.poll() is not None:
                # Already exited — let the supervisor record the real
                # outcome instead of overwriting it with STOPPED.
                return False
            info.status = JobStatus.STOPPED
            info.message = "stopped by user"
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return True
        deadline = time.monotonic() + grace_s
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                return True
            time.sleep(0.05)
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        return True

    def _get(self, job_id: str) -> JobInfo:
        with self._lock:
            info = self._jobs.get(job_id)
        if info is None:
            raise ValueError(f"no such job {job_id}")
        return info


_MANAGER: Optional[JobManager] = None
_MANAGER_LOCK = threading.Lock()


def job_manager() -> JobManager:
    global _MANAGER
    with _MANAGER_LOCK:
        if _MANAGER is None:
            _MANAGER = JobManager()
        return _MANAGER


def default_log_root() -> str:
    """The on-disk job-log directory (shared with the `ray_tpu logs` CLI)."""
    from ray_tpu._private.config import GLOBAL_CONFIG

    return os.path.join(GLOBAL_CONFIG.session_dir, "job_logs")
