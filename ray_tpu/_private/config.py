"""Runtime configuration flag table.

TPU-native analogue of the reference's ``RAY_CONFIG`` macro table
(ref: src/ray/common/ray_config_def.h:22 — 220 C++ flags overridable via
``RAY_<name>`` env vars or a ``_system_config`` dict).  Same contract here:
every flag has a typed default, can be overridden by ``RAY_TPU_<NAME>`` env
vars or the ``_system_config`` dict passed to ``ray_tpu.init``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, fields
from typing import Any, Dict, Optional

_ENV_PREFIX = "RAY_TPU_"


@dataclass
class Config:
    #: Bumped by apply_overrides so config-derived caches invalidate.
    #: (Not an operator knob; skipped by the env-var scan.)
    generation: int = 0

    # --- object store ---
    #: Objects at or below this size are stored inline in the in-process memory
    #: store and copied between workers (ref: max_direct_call_object_size).
    max_inline_object_size: int = 100 * 1024
    #: Cap on shared-memory object store bytes (0 = autodetect 30% of RAM,
    #: matching the reference's plasma default).
    object_store_memory: int = 0
    #: Directory for spilled objects (object spilling under memory pressure,
    #: ref: raylet/local_object_manager.h:41).
    spill_dir: str = "/tmp/ray_tpu_spill"
    #: Start spilling when the store is this full (ref: object_spilling_threshold).
    object_spilling_threshold: float = 0.8
    #: Args/results larger than this ride the native shared-memory arena to
    #: process workers instead of the pipe (zero-copy handoff).
    plasma_handoff_threshold: int = 128 * 1024

    # --- object transfer (node-to-node plane, ref: object_manager.h:117) ---
    #: Start the TCP object server at init so ObjectRefs leaving this process
    #: carry a routable owner address (ownership-based directory).
    enable_object_transfer: bool = False
    #: Interface the object server binds ("127.0.0.1" keeps it host-local;
    #: set to the host's DCN address for multi-host clusters).
    object_transfer_host: str = "127.0.0.1"
    #: Payload slice size for chunked sends (ref: object_manager_chunk_size).
    object_transfer_chunk_bytes: int = 1 << 20
    #: Bound on total in-flight pull payload bytes (ref: pull_manager.h:52
    #: memory-bounded pull requests).
    max_inflight_pull_bytes: int = 256 << 20
    #: Socket/connect timeout per pull request, and the default bound for
    #: fire-and-forget dependency pulls.
    object_transfer_pull_timeout_s: float = 30.0
    #: How long the owner-side server waits for a PENDING object to seal
    #: before answering ST_PENDING (the borrower then retries, so gets with
    #: no deadline wait indefinitely for long-running producers).
    object_transfer_serve_wait_s: float = 1.0
    #: Transient-failure retries for fire-and-forget dependency pulls before
    #: the waiting task is failed with ObjectTransferError.
    object_transfer_pull_retries: int = 3
    #: SO_SNDBUF/SO_RCVBUF on transfer sockets (large windows keep the
    #: zero-copy sendfile pipe full on fast links).
    object_transfer_sockbuf_bytes: int = 4 << 20
    #: Concurrent range streams per large-object pull (ref:
    #: push_manager.h chunked parallel pushes).  1 = single stream — the
    #: right default on a single-core host where extra streams just
    #: timeshare; raise on multi-core hosts.
    parallel_pull_streams: int = 1
    #: Range size per stream request when a pull is split across streams.
    parallel_pull_chunk_bytes: int = 32 << 20
    #: Same-host arena handoff: a puller that can map the owner's tmpfs
    #: arena file copies the payload with ONE memcpy and no socket bytes
    #: (the analogue of the reference's same-node shared plasma — workers
    #: on one host never stream objects through TCP).  Falls back to the
    #: socket path automatically when the peer's arena isn't mappable
    #: (true remote host).
    same_host_handoff: bool = True
    #: Broadcast fan-out tree (ref: the reference's 1 GiB x 50-node broadcast
    #: anchor): when N nodes pull the same large object, the owner serves at
    #: most ``broadcast_tree_fanout`` concurrent direct streams and redirects
    #: later pullers to peers that already hold a complete copy, so owner
    #: egress grows with the fanout, not with N.
    broadcast_tree_enabled: bool = True
    #: Objects below this size skip the tree (the extra negotiation
    #: round-trip isn't worth it; the owner just serves them directly).
    broadcast_tree_min_bytes: int = 32 << 20
    #: Concurrent direct-from-owner streams before redirecting to peers.
    broadcast_tree_fanout: int = 2

    #: Rendezvous bound for in-process collective ops: a lost/wedged rank
    #: fails the other participants after this long instead of holding
    #: them hostage (per-group override via init_collective_group's
    #: timeout_s).
    collective_timeout_s: float = 300.0

    #: Grace window after a borrower's liveness session drops before its
    #: borrows are reaped — a reconnect inside it cancels the reap
    #: (transient TCP resets must not free live data).
    borrow_session_grace_s: float = 5.0

    # --- worker nodes (cross-host execution, ref: node_manager.h:117) ---
    #: Task returns at or below this size travel inline in the completion
    #: frame to the head's store; larger returns stay in the producing
    #: node's store and peers pull them directly
    #: (ref: max_direct_call_object_size split).
    direct_return_max_bytes: int = 256 * 1024
    #: Worker-node heartbeat cadence over the node connection.
    node_heartbeat_interval_s: float = 2.0
    #: How long a worker node keeps retrying to reconnect + re-register
    #: after losing its head connection (a restarted head comes back within
    #: this window and the node rejoins; 0 disables rejoin — drop the node
    #: on first disconnect).  Ref: python/ray/_private/node.py:1407 raylets
    #: tolerating GCS downtime.
    node_reconnect_grace_s: float = 120.0
    #: Bound on a worker node's dispatch-handler threads (task/actor frames
    #: from the head each occupy one handler until their result exports; a
    #: raw thread-per-frame let 10k queued actor calls mean 10k threads —
    #: ref: src/ray/raylet/worker_pool.h:216 bounded worker pools).
    node_dispatch_max_threads: int = 256
    #: Reduce-partition cap for data-exchange stages (shuffle/sort/groupby);
    #: raise on wide clusters where 32-way reduce under-parallelizes.
    data_max_partitions: int = 32
    #: Head declares a node dead after this long without a frame
    #: (ref: gcs_health_check_manager.h:45 health-check timeout).
    node_heartbeat_timeout_s: float = 30.0
    #: Timeout for a worker node's synchronous control-plane requests to
    #: the head (named actors, foreign actor calls, cluster KV).
    node_request_timeout_s: float = 120.0
    #: How long a dispatching node waits for remote actor creation before
    #: reporting it dead.
    actor_create_timeout_s: float = 300.0

    # --- scheduling ---
    #: Pack-then-spread crossover used by the hybrid policy
    #: (ref: hybrid_scheduling_policy.h:50 spread_threshold=0.5).
    scheduler_spread_threshold: float = 0.5
    #: Top-k random tie-break among candidate nodes (ref: scheduler_top_k_fraction).
    scheduler_top_k_fraction: float = 0.2
    #: Max times a task is retried on worker/system failure (per-task override
    #: via options(max_retries=...)).
    task_max_retries: int = 3

    # --- workers ---
    #: Number of pre-started process workers (0 = on demand). Thread workers
    #: (the TPU-native default execution engine) are always available.
    prestart_process_workers: int = 0
    #: Seconds an idle leased process worker is kept before being returned
    #: (ref: worker lease reuse / idle_worker_killing).
    idle_worker_timeout_s: float = 60.0
    #: Hard cap on process workers.
    max_process_workers: int = 16

    # --- OOM defense (ref: memory_monitor.h:52, memory_usage_threshold) ---
    #: Kill a busy process worker when system memory usage crosses this
    #: fraction (1.0 disables the monitor; reference default 0.95).
    memory_monitor_threshold: float = 1.0
    memory_monitor_interval_s: float = 1.0
    #: Absolute floor: also treat free bytes below this as pressure
    #: (0 = disabled; ref: min_memory_free_bytes).
    memory_monitor_min_free_bytes: int = 0

    # --- fault tolerance ---
    #: Period of the control plane's health check of actors/nodes
    #: (ref: gcs_health_check_manager.h:45).
    health_check_period_s: float = 1.0
    #: Actor restart backoff.
    actor_restart_backoff_s: float = 0.1

    # --- testing / chaos (ref: rpc/rpc_chaos.h:22, RAY_testing_rpc_failure) ---
    #: "<method>=<probability>" comma list; matching internal operations fail
    #: with a transient error to exercise retry paths.
    testing_rpc_failure: str = ""
    #: Inject this many microseconds of delay into internal event handling
    #: (ref: RAY_testing_asio_delay_us).
    testing_delay_us: int = 0

    # --- metrics / events ---
    metrics_report_interval_s: float = 5.0
    #: Keep at most this many task events for the state API
    #: (ref: gcs_task_manager.h task event GC).
    max_task_events: int = 100_000
    #: Enable chrome://tracing profile event collection (ref: RAY_PROFILING).
    profiling_enabled: bool = False

    # --- logging ---
    log_dir: str = ""
    log_to_driver: bool = True

    # --- control-plane persistence (ref: gcs_kv_manager.h + redis tier) ---
    #: Persist the internal KV to a WAL under session_dir so control-plane
    #: metadata survives a head restart.
    kv_persist: bool = False

    # --- session ---
    #: Session-scoped scratch dir (runtime-env cache, job logs; the role of
    #: the reference's /tmp/ray/session_* tree).
    session_dir: str = "/tmp/ray_tpu_session"

    def apply_overrides(self, system_config: Optional[Dict[str, Any]] = None) -> None:
        for f in fields(self):
            if f.name == "generation":
                continue
            env = os.environ.get(_ENV_PREFIX + f.name.upper())
            if env is not None:
                setattr(self, f.name, _coerce(env, f.type))
        for key, val in (system_config or {}).items():
            if not hasattr(self, key):
                raise ValueError(f"Unknown system config key: {key}")
            setattr(self, key, val)
        # Bump so caches keyed on config contents (e.g. RemoteFunction's
        # resolved options) invalidate.
        self.generation += 1


def _coerce(value: str, typ: Any) -> Any:
    typ = str(typ)
    if "bool" in typ:
        return value.lower() in ("1", "true", "yes")
    if "int" in typ:
        return int(value)
    if "float" in typ:
        return float(value)
    return value


GLOBAL_CONFIG = Config()


def session_subdir(name: str, env_var: str, *, export: bool = False) -> str:
    """Resolve <session_dir>/<name>, honoring an env override so spawned
    workers (which see only config DEFAULTS, never the driver's
    _system_config) agree with the driver.  ``export=True`` publishes the
    driver's resolved path into the env before spawning children."""
    import os

    env = os.environ.get(env_var)
    if env and not export:
        os.makedirs(env, exist_ok=True)
        return env
    d = os.path.join(GLOBAL_CONFIG.session_dir, name)
    os.makedirs(d, exist_ok=True)
    if export:
        os.environ[env_var] = d
    return d
