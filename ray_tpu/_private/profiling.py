"""Task profiling + chrome-tracing timeline export.

Counterpart of the reference's profile events and `ray.timeline()`
(ref: _private/profiling.py profile():84 and _private/state.py timeline():960,
C++ buffer core_worker/profile_event.h): `profile("name")` emits paired
span events into the runtime's task-event log, and `chrome_trace()` folds
the whole log into chrome://tracing "X" (complete) events — load the JSON at
chrome://tracing or ui.perfetto.dev.
"""

from __future__ import annotations

import json
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional


@contextmanager
def profile(event_name: str, extra_data: Optional[dict] = None):
    """User-defined span, attributed to the current task if inside one
    (ref: _private/profiling.py:84)."""
    from ray_tpu._private import runtime as _rt

    rt = _rt.get_runtime()
    ctx = _rt.current_task_context()
    task_id = ctx.task_id if ctx else _rt_driver_id(rt)
    start = time.time()
    rt._emit_event(task_id, event_name, "PROFILE_BEGIN", **(extra_data or {}))
    try:
        yield
    finally:
        rt._emit_event(task_id, event_name, "PROFILE_END", begin=start)


def _rt_driver_id(rt):
    return rt.job_id


#: Span-name prefixes folded into the shared "train" timeline lane: one
#: Perfetto process row holds training steps, their wait buckets, elastic
#: recoveries, checkpoint phases and ingest transfers TOGETHER, so a
#: shrink -> restore -> resume sequence (with its starved steps) reads as
#: one story instead of thousands of per-trace rows.
_TRAIN_LANE_PREFIXES = ("train.", "checkpoint.", "data.")

#: Serve health-plane spans folded into one shared "serve" lane the same
#: way: SLO burn episodes and preemption recomputes from every request
#: line up on a single row, so a preemption-storm -> SLO-burn -> recovery
#: episode reads as one story next to the per-trace request lanes.
_SERVE_LANE_PREFIXES = ("serve.slo", "serve.preempt_recompute")

#: Device-plane spans (XLA compiles, host<->device transfers, compute
#: burns from util.device_telemetry) folded into one shared "device" lane:
#: a recompile storm, the transfers feeding it, and the burns it starves
#: line up on a single row under the train/serve stories.
_DEVICE_LANE_PREFIXES = ("xla.", "device.")


def spans_to_chrome_events(spans: List[dict]) -> List[dict]:
    """Fold util.tracing spans into chrome-tracing "X" (complete) events.

    Rows group by trace: ``pid`` is the trace id (Perfetto renders one
    process lane per trace — a whole serve request reads top-to-bottom),
    ``tid`` is the span's name so sibling spans of the same kind share a
    track.  Training-plane spans (train./checkpoint./data.) instead share
    the single "train" pid (_TRAIN_LANE_PREFIXES), serve health-plane
    spans (SLO burns, preemption recomputes) the single "serve" pid
    (_SERVE_LANE_PREFIXES), and device-plane spans (xla./device.) the
    single "device" pid (_DEVICE_LANE_PREFIXES).  Unfinished spans
    (end=None) are skipped — an open span has no duration yet."""
    out: List[dict] = []
    for s in spans:
        if s.get("end") is None:
            continue
        args = {"span_id": s.get("span_id"),
                "parent_id": s.get("parent_id"),
                "status": s.get("status", "OK")}
        args.update(s.get("attributes") or {})
        name = s.get("name", "")
        if name.startswith(_TRAIN_LANE_PREFIXES):
            pid = "train"
        elif name.startswith(_SERVE_LANE_PREFIXES):
            pid = "serve"
        elif name.startswith(_DEVICE_LANE_PREFIXES):
            pid = "device"
        else:
            pid = f"trace:{s.get('trace_id', '')[:8]}"
        ev = {
            "ph": "X", "cat": "trace",
            "name": name,
            "pid": pid,
            "tid": s.get("name", ""),
            "ts": s["start"] * 1e6,
            "dur": max(0.0, (s["end"] - s["start"]) * 1e6),
            "args": args,
        }
        if s.get("status", "OK") != "OK":
            ev["cname"] = "terrible"
        out.append(ev)
    return out


def postmortem_chrome_events(bundle: Dict[str, Any]) -> List[dict]:
    """Fold a forensics postmortem bundle (util.forensics.build_bundle)
    into chrome-tracing events: one Perfetto process lane per dumped
    process holding the spans its flight-recorder ring captured, with
    instant markers ("i" events, rendered as flow arrows/flags) at state
    transitions, dump triggers, and stall/death moments — so "replica
    died mid-batch" or "worker wedged in a rendezvous" reads directly off
    the fused timeline."""
    out: List[dict] = []
    for dump in bundle.get("dumps", []):
        pid = f"pid:{dump.get('pid')}"
        for row in dump.get("ring", []):
            kind = row.get("kind", "event")
            name = row.get("name", "")
            status = row.get("status", "OK")
            if kind == "span":
                ev = {
                    "ph": "X", "cat": "forensics", "name": name,
                    "pid": pid, "tid": name,
                    "ts": row.get("start", 0.0) * 1e6,
                    "dur": max(0.0, (row.get("end", 0.0)
                                     - row.get("start", 0.0)) * 1e6),
                    "args": {"status": status, "seq": row.get("seq")},
                }
            else:
                ev = {
                    "ph": "i", "cat": "forensics", "name": f"{kind}:{name}",
                    "pid": pid, "tid": kind,
                    "ts": row.get("start", 0.0) * 1e6, "s": "p",
                    "args": {"status": status, "seq": row.get("seq")},
                }
            if status != "OK" or kind in ("stall", "trigger"):
                ev["cname"] = "terrible"
            out.append(ev)
        # The dump moment itself: the death/breach marker, process-scoped.
        out.append({
            "ph": "i", "cat": "forensics",
            "name": f"dump:{dump.get('reason')}", "pid": pid, "tid": "dump",
            "ts": (dump.get("ts") or 0.0) * 1e6, "s": "p",
            "args": {"reason": dump.get("reason"), "id": dump.get("id")},
            "cname": "terrible",
        })
    return out


def chrome_trace(events: Optional[List[dict]] = None,
                 include_spans: bool = True) -> List[dict]:
    """Fold the task-event log into chrome-tracing events.

    Execution spans: RUNNING→FINISHED/FAILED pairs per task attempt.
    Profile spans: PROFILE_BEGIN/PROFILE_END pairs.  Instant events for
    submits/retries.  When tracing is on (util.tracing), the exported
    distributed-trace spans — serve request timelines included — fold in
    as their own per-trace lanes (``include_spans=False`` to opt out).
    """
    span_events: List[dict] = []
    if include_spans:
        from ray_tpu.util import tracing as _tracing

        span_events = spans_to_chrome_events(_tracing.exported_spans())
    if events is None:
        from ray_tpu._private import runtime as _rt

        rt = _rt.get_runtime()
        events = rt.list_task_events()

    out: List[dict] = []
    running: Dict[str, dict] = {}
    profiling: Dict[tuple, dict] = {}
    for ev in events:
        state = ev.get("state", "")
        tid = ev["task_id"]
        us = ev["time"] * 1e6
        if state == "RUNNING":
            running[tid] = ev
        elif state in ("FINISHED", "FAILED") and tid in running:
            beg = running.pop(tid)
            out.append({
                "ph": "X", "cat": "task", "name": ev.get("name", tid),
                "pid": ev.get("node_id", "node"), "tid": tid,
                "ts": beg["time"] * 1e6, "dur": us - beg["time"] * 1e6,
                "args": {"task_id": tid, "state": state},
                "cname": ("thread_state_running" if state == "FINISHED"
                          else "terrible"),
            })
        elif state == "PROFILE_BEGIN":
            profiling[(tid, ev.get("name"))] = ev
        elif state == "PROFILE_END":
            beg = profiling.pop((tid, ev.get("name")), None)
            beg_ts = (beg["time"] if beg else ev.get("begin", ev["time"])) * 1e6
            out.append({
                "ph": "X", "cat": "profile", "name": ev.get("name", ""),
                "pid": "profile", "tid": tid,
                "ts": beg_ts, "dur": us - beg_ts,
            })
        elif state in ("SUBMITTED_TO_WORKER", "RETRYING", "RESUBMITTED"):
            out.append({
                "ph": "i", "cat": "sched", "name": f"{ev.get('name','')}:{state}",
                "pid": ev.get("node_id", "node"), "tid": tid, "ts": us, "s": "t",
            })
    out.extend(span_events)
    return out


def dump_timeline(filename: str, events: Optional[List[dict]] = None) -> List[dict]:
    trace = chrome_trace(events)
    with open(filename, "w") as f:
        json.dump(trace, f)
    return trace
