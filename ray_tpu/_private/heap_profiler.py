"""On-demand heap profiling via tracemalloc.

TPU-native analogue of the reference's memray integration (ref:
python/ray/dashboard/modules/reporter/profile_manager.py — on-demand heap
profiling of any worker from the dashboard).  memray is not in the image;
tracemalloc gives allocation-site attribution for the driver process (which
hosts every thread-tier worker — the tier that matters for heap pressure
here).  First call starts tracing, so only allocations AFTER that are
attributed; reported via `ray_tpu memory` and /api/memory.
"""

from __future__ import annotations

import tracemalloc
from typing import Dict, List


def ensure_tracing(nframes: int = 16) -> bool:
    """Idempotently start tracemalloc; returns True if it was ALREADY on
    (i.e. the snapshot below covers a real window, not an empty one)."""
    if tracemalloc.is_tracing():
        return True
    tracemalloc.start(nframes)
    return False


def heap_summary(top_n: int = 20, group_by: str = "lineno") -> Dict:
    """Top allocation sites since tracing began (ref: memray table view)."""
    was_tracing = ensure_tracing()
    current, peak = tracemalloc.get_traced_memory()
    stats: List[Dict] = []
    if was_tracing:
        snapshot = tracemalloc.take_snapshot()
        for stat in snapshot.statistics(group_by)[:top_n]:
            frame = stat.traceback[0]
            stats.append({
                "site": f"{frame.filename}:{frame.lineno}",
                "size_bytes": stat.size,
                "count": stat.count,
            })
    return {
        "tracing_window_open": not was_tracing,
        "traced_current_bytes": current,
        "traced_peak_bytes": peak,
        "top_sites": stats,
    }


def format_heap(summary: Dict) -> str:
    lines = [f"traced: {summary['traced_current_bytes']/1e6:.1f} MB current, "
             f"{summary['traced_peak_bytes']/1e6:.1f} MB peak"]
    if summary["tracing_window_open"]:
        lines.append("(tracing just started — run again to see allocations "
                     "made since this call)")
    for s in summary["top_sites"]:
        lines.append(f"{s['size_bytes']/1e6:9.2f} MB  {s['count']:8d} allocs  "
                     f"{s['site']}")
    return "\n".join(lines)
