"""Node-to-node object plane: ownership directory + chunked pull/push transfer.

TPU-native analogue of the reference's object manager (ref:
src/ray/object_manager/object_manager.h:117).  Each runtime ("node") can run
an **object server**: a TCP service that serves the serialized wire form of
objects in its local store, in chunks (the role of the reference's chunked
gRPC transfer, object_manager.proto).  Remote fetches go through a
**PullManager** (ref: src/ray/object_manager/pull_manager.h:52): concurrent
pulls of the same object are deduplicated, total in-flight bytes are bounded,
and completed pulls land in the local store's serialized tier, waking any
task/get/wait blocked on the object.  A **push** path (ref:
src/ray/object_manager/push_manager.h:30) proactively sends an object to a
peer using the same chunk frames in the opposite direction.

The directory is **ownership-based** (ref: src/ray/object_manager/
ownership_based_object_directory.h): there is no central location service.
An ``ObjectRef`` that crosses a process boundary while its owner's object
server is running carries the owner's ``host:port`` in ``owner_addr``; the
owner holds the primary copy (restoring it from spill if needed), so
locating an object is just reading its ref — the same trick the reference
plays by embedding ownership in the object id.

Lifetime note: a pulled copy is a *cache* on the borrowing node, freed by
that node's local refcounter; the owner keeps the primary copy alive for as
long as its own refs (or pins) exist.

Wire protocol (all integers little-endian):

    request  := op:u8  id_len:u16  id:bytes
                [PUSH only: owner_len:u16 owner:bytes size:u64 payload:bytes]
    PULL resp     := status:u8  [ok: size:u64 payload:bytes]
    CONTAINS resp := status:u8   (0 = present)
    PUSH resp     := status:u8
    FREE resp     := status:u8   (drop a cached copy; no-op if absent)

Payloads stream in ``object_transfer_chunk_bytes`` slices; there is no
per-chunk framing because TCP already provides ordered delivery — the size
header tells the receiver exactly how many bytes to expect.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.ids import ObjectID
from ray_tpu.exceptions import ObjectLostError

OP_PULL = 1
OP_CONTAINS = 2
OP_PUSH = 3
OP_FREE = 4
#: Borrowing protocol (ref: reference_count.h borrower registration):
#: request carries borrower_len:u16 + borrower id after the object id.
OP_ADD_BORROW = 5
OP_RELEASE_BORROW = 6
#: Borrower-liveness session (ref: reference_count.h worker-death pubsub —
#: the owner reclaims a dead borrower's borrows): the borrower holds ONE
#: long-lived connection per owner; EOF on it means the borrower process
#: died, and the owner drops every borrow registered under its id.  The
#: object id field carries the borrower id; no reply is sent.
OP_BORROW_SESSION = 7
#: Compiled-DAG channel plane (dag/channel.py RemoteChannel): an element
#: pushed by a producer in ANOTHER runtime lands in this runtime's plasma
#: arena under the channel's ``<name>:<seq>`` key; the local consumer reads
#: and deletes it.  The id field of the frame carries the channel name.
#: (ref: the reference's cross-worker compiled-graph edges —
#: experimental/channel/shared_memory_channel.py + torch NCCL channels; here
#: one transport tier rides the existing object-plane TCP endpoint.)
OP_CHAN_PUSH = 8
OP_CHAN_CLOSE = 9
OP_CHAN_RECLAIM = 10
#: Range pull (ref: object_manager.proto chunked ObjectChunk reads): request
#: carries offset:u64 + len:u64 after the id; the response's size field is
#: the object's TOTAL size, and the payload is the clamped
#: ``[offset, offset+len)`` slice.  A pull of ``offset=0, len=2^63`` is a
#: whole-object pull that tells the client the total up front, so the
#: PullManager always uses this op: small objects land in one round trip and
#: large ones keep this stream for chunk 0 while extra sockets range-pull
#: the rest in parallel.
OP_PULL_RANGE = 11
#: Same-host arena handoff (the analogue of the reference's same-node
#: shared plasma — ref: plasma/client.h mmap'd fd passing): the response
#: carries (arena path, offset, size, content crcs) and the server HOLDS
#: the region pinned until the client sends a done byte (or EOF).  A
#: client that can map the path copies the payload with one memcpy and no
#: socket bytes; anything else falls back to OP_PULL_RANGE.
OP_REGION = 12
#: Cross-language task submission: invoke a DRIVER-REGISTERED function by
#: name with a raw-bytes argument; the reply carries the result ObjectID,
#: which the caller then pulls like any object.  Name-based registration is
#: how the reference's cross-language calls work too — a foreign client
#: cannot produce a Python closure, so the driver publishes the callable
#: (ref: cross_language.java_function / the C++ entry points in
#: cpp/include/ray/api/ — reduced to the registry model our pickle-framed
#: control plane admits).
OP_INVOKE = 13
#: Broadcast fan-out tree (ref: the reference's 1-GiB-to-50-nodes broadcast;
#: object_manager location subscriptions): before pulling a LARGE object, a
#: node asks the owner where to pull FROM, carrying its own object-server
#: address.  The owner serves at most ``broadcast_tree_fanout`` direct
#: streams; once peers complete (they OP_ANNOUNCE), later requesters are
#: redirected to those peers — so an N-node broadcast forms a pull tree and
#: owner egress stays O(fanout), not O(N).  Request: id + alen:u16 + addr.
#: Reply: status:u8 [ok: alen:u16 addr] — an empty/own address means "pull
#: from me"; ST_PENDING means every slot is busy and no holder exists yet
#: (retry shortly).
OP_PULL_LOC = 14
#: Completion report for the tree: "requester at <addr> now holds <id>"
#: (frees its grant slot and registers it as a redirect target).
OP_ANNOUNCE = 15

ST_OK = 0
ST_NOT_FOUND = 1
ST_ERROR = 2
#: Channel backpressure: the element was NOT accepted — the consumer is
#: ``maxsize`` behind; retry after a short sleep.
ST_FULL = 5
#: The channel was closed (sentinel present); writers must stop.
ST_CLOSED = 6
#: The owner knows the object (entry pending / producing task in flight) but
#: it is not ready yet — the borrower should keep waiting, NOT declare loss.
ST_PENDING = 3
#: The producing task FAILED on the owner; payload carries the pickled
#: exception so the borrower re-raises the original error, not ObjectLost.
ST_FAILED = 4

# Address of this process's running object server ("" = not running).  Module
# level so ObjectRef.__reduce__ can stamp refs without importing the runtime.
_LOCAL_ADDR = ""
_LOCAL_ADDR_LOCK = threading.Lock()


def local_server_addr() -> str:
    return _LOCAL_ADDR


def _set_local_addr(addr: str) -> None:
    global _LOCAL_ADDR
    with _LOCAL_ADDR_LOCK:
        _LOCAL_ADDR = addr


class ObjectTransferError(ObjectLostError):
    """A remote pull failed (owner unreachable or object unknown there)."""


class _RemoteTaskFailed(Exception):
    """Internal carrier: the owner reported the producing task FAILED; the
    wrapped original error is landed in the local store and re-raised by
    the getter (never surfaced directly)."""

    def __init__(self, error: BaseException):
        super().__init__(repr(error))
        self.error = error


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed mid-frame")
        got += r
    return bytes(buf)


def _recv_into(sock: socket.socket, total: int) -> bytearray:
    buf = bytearray(total)
    _recv_into_view(sock, memoryview(buf), total)
    return buf


def _recv_into_view(sock: socket.socket, view: memoryview, total: int,
                    offset: int = 0) -> None:
    """Land exactly ``total`` bytes at ``view[offset:]`` — used to receive
    payloads straight into a pre-created plasma arena buffer, making the
    kernel's recv copy the only copy on the receive side."""
    got = 0
    while got < total:
        r = sock.recv_into(view[offset + got:offset + total], total - got)
        if r == 0:
            raise ConnectionError("peer closed mid-payload")
        got += r


def _send_payload(sock: socket.socket, payload) -> None:
    chunk = max(64 * 1024, GLOBAL_CONFIG.object_transfer_chunk_bytes)
    view = memoryview(payload)
    for off in range(0, len(view), chunk):
        sock.sendall(view[off:off + chunk])


def _sendfile_all(sock: socket.socket, fd: int, offset: int, count: int) -> int:
    """Ship an arena-file region with zero user-space copies (tmpfs page →
    socket buffer in the kernel).  On a socket with a timeout (internally
    non-blocking) sendfile raises BlockingIOError once the send buffer
    fills — wait for writability and continue, so a partial send NEVER
    surfaces as an exception mid-stream.  Returns bytes sent; raises only
    with the stream position == offset + return value."""
    import errno
    import os
    import select

    sent_total = 0
    while sent_total < count:
        try:
            sent = os.sendfile(sock.fileno(), fd, offset + sent_total,
                               count - sent_total)
        except (BlockingIOError, InterruptedError):
            timeout = sock.gettimeout()
            r = select.select([], [sock], [], timeout)[1]
            if not r:
                e = socket.timeout(
                    f"sendfile stalled after {sent_total}/{count} bytes")
                e.partial = sent_total  # type: ignore[attr-defined]
                raise e
            continue
        except OSError as e:
            e.partial = sent_total  # type: ignore[attr-defined]
            raise
        if sent == 0:
            raise ConnectionError("peer closed during sendfile")
        sent_total += sent
    return sent_total


def _send_region(sock: socket.socket, store, fd: int, offset: int,
                 count: int) -> None:
    """sendfile an arena region, falling back to a zero-copy sendall from
    the mapped view (the region's plasma refcount is held by the caller).
    The fallback runs ONLY when sendfile failed before sending any bytes
    (unsupported transport) — a mid-stream failure must propagate, never
    restart the payload on the same connection (silent corruption)."""
    import errno

    try:
        _sendfile_all(sock, fd, offset, count)
    except OSError as e:
        if getattr(e, "partial", 0) or e.errno not in (
                errno.EINVAL, errno.ENOSYS, errno.EOPNOTSUPP, errno.ENOTSOCK):
            raise
        plasma = getattr(store, "plasma", None)
        if plasma is None:
            raise
        _send_payload(sock, plasma.view_at(offset, count))


def _tune_sock(sock: socket.socket) -> None:
    buf = GLOBAL_CONFIG.object_transfer_sockbuf_bytes
    if buf > 0:
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, buf)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, buf)
        except OSError:
            pass


class ObjectTransferServer:
    """Per-node TCP object service over the local object store.

    ``store_provider`` returns the live ObjectStore (re-read per request so a
    runtime restart mid-session doesn't serve a stale store); ``on_received``
    is invoked after a PUSH lands so the runtime can wake dependent tasks.
    """

    def __init__(self, store_provider: Callable[[], object],
                 on_received: Optional[Callable[[ObjectID], None]] = None,
                 is_pending: Optional[Callable[[ObjectID], bool]] = None,
                 on_borrow: Optional[Callable[[ObjectID, str], None]] = None,
                 on_borrow_release: Optional[Callable[[ObjectID, str], None]] = None,
                 may_free: Optional[Callable[[ObjectID], bool]] = None,
                 on_borrower_lost: Optional[Callable[[str], None]] = None,
                 on_invoke: Optional[Callable[[str, bytes], str]] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self._store_provider = store_provider
        self._on_invoke = on_invoke
        self._on_received = on_received
        self._is_pending = is_pending
        self._on_borrow = on_borrow
        self._on_borrow_release = on_borrow_release
        self._may_free = may_free
        self._on_borrower_lost = on_borrower_lost
        #: borrower id -> count of live liveness sessions (a reconnect
        #: within the reap grace period cancels the pending reap).
        self._live_sessions: Dict[str, int] = {}
        self._sessions_lock = threading.Lock()
        #: channel name -> consumed floor (lowest seq that may still be
        #: live), advanced by probing — the reader deletes in order.
        self._chan_floors: Dict[str, int] = {}
        #: channel name -> next seq not yet accepted.  A re-push of an
        #: accepted seq (ack lost to a connection reset; the producer
        #: retried) must be answered ST_OK WITHOUT re-sealing — the reader
        #: may have
        #: consumed it already, and a re-sealed dead element would pin the
        #: floor and wedge the channel in ST_FULL forever.
        self._chan_next: Dict[str, int] = {}
        self._chan_lock = threading.Lock()
        #: Broadcast-tree coordination state, per object id:
        #:   grants: requester addr -> (source addr or "" for owner-direct,
        #:           grant timestamp) — outstanding transfers this owner
        #:           handed out; stale grants (requester died mid-pull)
        #:           expire lazily.
        #:   holders: requester addrs that announced a complete copy —
        #:           redirect targets for later pullers.
        self._bcast: Dict[ObjectID, dict] = {}
        self._bcast_lock = threading.Lock()
        #: Egress accounting (proves the tree works: owner egress must grow
        #: sub-linearly in node count).  Socket sends AND same-host region
        #: handoffs both count — a handoff moves the bytes out of this
        #: node's arena just like a send would.
        self.egress = {"pull_bytes": 0, "handoff_bytes": 0,
                       "by_object": {}, "redirects": 0}
        self._egress_lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()
        self.addr = f"{self.host}:{self.port}"
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="objxfer-accept", daemon=True)
        self._accept_thread.start()
        _set_local_addr(self.addr)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                # Transient per-connection errors (ECONNABORTED from a client
                # resetting mid-handshake) must not kill the listener; only a
                # stop() or a closed socket ends the loop.  The short sleep
                # stops persistent errors (EMFILE under fd exhaustion) from
                # busy-spinning a core.
                if self._stop.is_set() or self._sock.fileno() < 0:
                    return
                import time

                time.sleep(0.02)
                continue
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="objxfer-conn", daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _tune_sock(conn)
            while not self._stop.is_set():
                head = conn.recv(1)
                if not head:
                    return
                op = head[0]
                (id_len,) = struct.unpack("<H", _recv_exact(conn, 2))
                oid = ObjectID(_recv_exact(conn, id_len).decode())
                if op == OP_PULL:
                    self._handle_pull(conn, oid)
                elif op == OP_PULL_RANGE:
                    off, ln = struct.unpack("<QQ", _recv_exact(conn, 16))
                    self._handle_pull(conn, oid, rng=(off, ln))
                elif op == OP_REGION:
                    if not self._handle_region(conn, oid):
                        return  # desynced/dead socket: must not be reused
                elif op == OP_INVOKE:
                    (nlen,) = struct.unpack("<H", _recv_exact(conn, 2))
                    name = _recv_exact(conn, nlen).decode()
                    (plen,) = struct.unpack("<Q", _recv_exact(conn, 8))
                    payload = bytes(_recv_into(conn, plen)) if plen else b""
                    self._handle_invoke(conn, name, payload)
                elif op == OP_CONTAINS:
                    store = self._store_provider()
                    ok = store is not None and store.contains(oid)
                    conn.sendall(bytes([ST_OK if ok else ST_NOT_FOUND]))
                elif op in (OP_PULL_LOC, OP_ANNOUNCE):
                    (alen,) = struct.unpack("<H", _recv_exact(conn, 2))
                    requester = _recv_exact(conn, alen).decode() if alen else ""
                    if op == OP_PULL_LOC:
                        self._handle_pull_loc(conn, oid, requester)
                    else:
                        self._handle_announce(conn, oid, requester)
                elif op == OP_PUSH:
                    self._handle_push(conn, oid)
                elif op == OP_FREE:
                    # OP_FREE means "drop a CACHED copy" — it must never
                    # evict a primary copy with live references or borrowers
                    # (ADVICE r2): the node owner decides via may_free.
                    store = self._store_provider()
                    if store is not None and (
                            self._may_free is None or self._may_free(oid)):
                        store.free(oid)
                    conn.sendall(bytes([ST_OK]))
                elif op in (OP_ADD_BORROW, OP_RELEASE_BORROW):
                    (blen,) = struct.unpack("<H", _recv_exact(conn, 2))
                    borrower = _recv_exact(conn, blen).decode() if blen else ""
                    cb = (self._on_borrow if op == OP_ADD_BORROW
                          else self._on_borrow_release)
                    if cb is not None:
                        cb(oid, borrower)
                    conn.sendall(bytes([ST_OK]))
                elif op == OP_CHAN_PUSH:
                    self._handle_chan_push(conn, str(oid))
                elif op == OP_CHAN_CLOSE:
                    arena = self._chan_arena()
                    if arena is None:
                        conn.sendall(bytes([ST_ERROR]))
                    else:
                        key = f"{oid}:__closed__"
                        if not arena.contains(key):
                            arena.put_bytes(key, b"1")
                        conn.sendall(bytes([ST_OK]))
                elif op == OP_CHAN_RECLAIM:
                    drop_sentinel = _recv_exact(conn, 1)[0] != 0
                    (budget,) = struct.unpack("<I", _recv_exact(conn, 4))
                    self._handle_chan_reclaim(conn, str(oid), drop_sentinel,
                                              budget)
                elif op == OP_BORROW_SESSION:
                    # The "object id" field carries the borrower id; this
                    # connection now IS the borrower's liveness signal —
                    # park until EOF, then (after a grace period in which
                    # the borrower may reconnect — a transient TCP reset
                    # must not read as death) reap its borrows.
                    borrower = str(oid)
                    with self._sessions_lock:
                        self._live_sessions[borrower] = \
                            self._live_sessions.get(borrower, 0) + 1
                    try:
                        conn.sendall(bytes([ST_OK]))
                        while conn.recv(1):
                            pass  # borrowers never send; drain defensively
                    except (ConnectionError, OSError):
                        pass
                    finally:
                        # MUST pair with the increment even when the ack
                        # send fails, or this borrower id's reaps are
                        # suppressed forever (count stuck > 0).
                        with self._sessions_lock:
                            self._live_sessions[borrower] -= 1
                            if self._live_sessions[borrower] <= 0:
                                del self._live_sessions[borrower]
                    if self._on_borrower_lost is not None \
                            and not self._stop.is_set():
                        self._reap_after_grace(borrower)
                    return
                else:
                    conn.sendall(bytes([ST_ERROR]))
                    return
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _reap_after_grace(self, borrower: str) -> None:
        """EOF on a borrower's last session: wait out the grace window; if
        no replacement session appeared, declare the borrower dead."""
        def waiter():
            import time as _t

            _t.sleep(GLOBAL_CONFIG.borrow_session_grace_s)
            with self._sessions_lock:
                if self._live_sessions.get(borrower, 0) > 0:
                    return  # reconnected (transient reset, not death)
            if not self._stop.is_set():
                self._on_borrower_lost(borrower)

        threading.Thread(target=waiter, name="objxfer-borrow-reap",
                         daemon=True).start()

    def _resolve_serialized(self, conn: socket.socket, oid: ObjectID):
        """Shared OP_PULL/OP_PULL_RANGE/OP_REGION prologue: resolve the
        object to a pinned arena region (preferred) or a serialized view,
        answering the client directly (NOT_FOUND / PENDING / FAILED) when
        it can't be served.  Returns (store, region, view) — exactly one of
        region/view set — or None when a reply was already sent.

        The PENDING dance: wait a bounded slice for a pending object to
        seal (the owner may still be computing it); the borrower retries on
        ST_PENDING, so a long-running producer never turns into a false
        NOT_FOUND.  get_serialized also serializes thread-tier values into
        the arena, so the region is retried after it."""
        store = self._store_provider()
        if store is None:
            conn.sendall(bytes([ST_NOT_FOUND]))
            return None
        state = store.state_of(oid)
        known = state is not None or (
            self._is_pending is not None and self._is_pending(oid))
        if not known:
            # The owner has never seen this object and nothing is producing
            # it: answer immediately — this is genuine loss, and waiting
            # would just stall the borrower.
            conn.sendall(bytes([ST_NOT_FOUND]))
            return None
        if state == "FAILED":
            self._send_failed(conn, store, oid)
            return None
        region = store.serialized_region(oid) \
            if hasattr(store, "serialized_region") else None
        view = None
        if region is None:
            try:
                view = store.get_serialized(
                    oid, timeout=GLOBAL_CONFIG.object_transfer_serve_wait_s)
            except Exception:
                state_now = store.state_of(oid)
                if state_now == "FAILED":
                    # The producer failed while we were waiting for it.
                    self._send_failed(conn, store, oid)
                    return None
                still_coming = state_now in (None, "PENDING") and known
                conn.sendall(
                    bytes([ST_PENDING if still_coming else ST_NOT_FOUND]))
                return None
            region = store.serialized_region(oid) \
                if hasattr(store, "serialized_region") else None
            if region is not None:
                view.release()
                view = None
        return store, region, view

    def _handle_pull(self, conn: socket.socket, oid: ObjectID,
                     rng: Optional[Tuple[int, int]] = None) -> None:
        if rng is not None:
            # Spilled objects: seek-read just the requested range — the
            # generic path below would re-read the entire spill file for
            # every parallel chunk stream.
            store = self._store_provider()
            sr = store.spilled_range(oid, *rng) \
                if store is not None and hasattr(store, "spilled_range") \
                else None
            if sr is not None:
                total, chunk = sr
                conn.sendall(bytes([ST_OK]) + struct.pack("<Q", total))
                _send_payload(conn, chunk)
                self._account_egress(oid, len(chunk), handoff=False)
                return
        resolved = self._resolve_serialized(conn, oid)
        if resolved is None:
            return
        store, region, view = resolved
        if region is not None:
            # Fast path: arena-resident — sendfile the pinned region
            # straight out of the tmpfs arena file, no user-space copy
            # (ref: object_buffer_pool.h chunk reads, minus the copy).
            fd, roff, size, release = region
            try:
                off, ln = rng if rng is not None else (0, size)
                n = min(ln, max(0, size - off))
                conn.sendall(bytes([ST_OK]) + struct.pack("<Q", size))
                if n:
                    _send_region(conn, store, fd, roff + off, n)
                    self._account_egress(oid, n, handoff=False)
            finally:
                release()
            return
        # Fallback (shm tier / spilled): copy before sending — serialized
        # views are only stable until the next store operation that may
        # spill (see ObjectStore docstring).
        total = len(view)
        off, ln = rng if rng is not None else (0, total)
        n = min(ln, max(0, total - off))
        payload = bytes(view[off:off + n])
        conn.sendall(bytes([ST_OK]) + struct.pack("<Q", total))
        _send_payload(conn, payload)
        self._account_egress(oid, n, handoff=False)

    def _handle_region(self, conn: socket.socket, oid: ObjectID) -> bool:
        """Same-host handoff: answer with the pinned arena region's
        coordinates and hold the pin until the client is done copying.

        Returns False when the connection must be dropped: if the done-byte
        wait times out while the client is still alive (stalled in its
        budget gate or a long memcpy), its eventual done byte would be
        parsed as the next request's opcode — a desynced pooled socket
        poisons every later pull on it."""
        import zlib

        resolved = self._resolve_serialized(conn, oid)
        if resolved is None:
            return True
        store, region, view = resolved
        plasma = getattr(store, "plasma", None)
        if region is None or plasma is None:
            # Not arena-resident (shm tier / spilled): socket pull instead.
            if region is not None:
                region[3]()
            conn.sendall(bytes([ST_ERROR]))
            return True
        fd, roff, size, release = region
        ok = True
        try:
            n = min(4096, size)
            crc_head = zlib.crc32(plasma.view_at(roff, n)) if n else 0
            crc_tail = zlib.crc32(
                plasma.view_at(roff + max(0, size - n), n)) if n else 0
            pathb = plasma.path.encode()
            conn.sendall(bytes([ST_OK])
                         + struct.pack("<QQH", roff, size, len(pathb))
                         + pathb + struct.pack("<II", crc_head, crc_tail))
            # The pin lives as long as this wait: done byte or EOF releases.
            prev = conn.gettimeout()
            conn.settimeout(GLOBAL_CONFIG.object_transfer_pull_timeout_s)
            try:
                conn.recv(1)
            except (socket.timeout, ConnectionError, OSError):
                ok = False
            finally:
                conn.settimeout(prev)
        finally:
            release()
        if ok:
            self._account_egress(oid, size, handoff=True)
        return ok

    # ------------------------------------------------- broadcast tree
    def _account_egress(self, oid: ObjectID, n: int, handoff: bool) -> None:
        if n <= 0:
            return
        with self._egress_lock:
            key = "handoff_bytes" if handoff else "pull_bytes"
            self.egress[key] += n
            per = self.egress["by_object"]
            if str(oid) in per or len(per) < 1024:
                per[str(oid)] = per.get(str(oid), 0) + n

    def stats(self) -> dict:
        """Egress snapshot (bench/observability; see BENCH_ENVELOPE)."""
        with self._egress_lock:
            out = dict(self.egress)
            out["by_object"] = dict(out["by_object"])
            return out

    def _bcast_state(self, oid: ObjectID) -> dict:
        st = self._bcast.get(oid)
        if st is None:
            if len(self._bcast) >= 1024:
                # Best-effort state: evicting just means the evicted
                # object's later pullers go owner-direct again.
                self._bcast.pop(next(iter(self._bcast)))
            st = self._bcast[oid] = {"grants": {}, "holders": []}
        else:
            # Lazily expire grants whose requester died mid-pull (no
            # announce ever comes) so their owner slots aren't leaked.
            ttl = 2 * GLOBAL_CONFIG.object_transfer_pull_timeout_s
            now = time.monotonic()
            stale = [a for a, (_, t0) in st["grants"].items()
                     if now - t0 > ttl]
            for a in stale:
                del st["grants"][a]
        return st

    def _handle_pull_loc(self, conn: socket.socket, oid: ObjectID,
                         requester: str) -> None:
        """Tree negotiation: tell the requester where to pull ``oid`` from.

        Owner-direct grants are capped at ``broadcast_tree_fanout``
        concurrent streams; beyond that, requesters are redirected to the
        least-loaded peer that already announced a complete copy, or told
        ST_PENDING to retry when no such peer exists yet.  Small or
        not-yet-serialized objects short-circuit to owner-direct — the
        tree only pays off on large payloads.

        Reply: ST_OK + tree:u8 + alen:u16 + addr.  ``tree=0`` means the
        tree is not engaged (small object): pull directly and do NOT
        announce; ``tree=1`` means the requester holds a grant and must
        OP_ANNOUNCE when its copy lands.  An empty addr = "pull from me"."""
        def reply(addr: str, tree: bool) -> None:
            ab = addr.encode()
            conn.sendall(bytes([ST_OK, 1 if tree else 0])
                         + struct.pack("<H", len(ab)) + ab)

        store = self._store_provider()
        if store is None or not store.contains(oid):
            pending = self._is_pending is not None and self._is_pending(oid)
            conn.sendall(bytes([ST_PENDING if pending else ST_NOT_FOUND]))
            return
        size = store.size_hint(oid) if hasattr(store, "size_hint") else 0
        if (not GLOBAL_CONFIG.broadcast_tree_enabled or not requester
                or size < GLOBAL_CONFIG.broadcast_tree_min_bytes):
            # size == 0 means "not yet serialized" — the first direct pull
            # serializes into the arena, after which later negotiations see
            # the real size and the tree engages.
            reply("", False)
            return
        with self._bcast_lock:
            st = self._bcast_state(oid)
            grants = st["grants"]
            if requester in grants:
                # Re-negotiation (retry after a failed pull): re-issue as
                # owner-direct so one bad peer can't wedge the requester.
                grants[requester] = ("", time.monotonic())
                reply("", True)
                return
            holders = [h for h in st["holders"] if h != requester]
            if holders:
                load = {h: 0 for h in holders}
                for src, _ in grants.values():
                    if src in load:
                        load[src] += 1
                pick = min(holders, key=lambda h: load[h])
                grants[requester] = (pick, time.monotonic())
                with self._egress_lock:
                    self.egress["redirects"] += 1
                reply(pick, True)
                return
            active = sum(1 for src, _ in grants.values() if not src)
            if active < max(1, GLOBAL_CONFIG.broadcast_tree_fanout):
                grants[requester] = ("", time.monotonic())
                reply("", True)
                return
        # Every owner slot busy and nobody complete yet: retry shortly —
        # by then either a slot freed or a holder announced.
        conn.sendall(bytes([ST_PENDING]))

    def _handle_announce(self, conn: socket.socket, oid: ObjectID,
                         requester: str) -> None:
        """A granted puller completed: free its slot, register it as a
        redirect target for later pullers."""
        with self._bcast_lock:
            st = self._bcast.get(oid)
            if st is not None:
                st["grants"].pop(requester, None)
                if requester and requester not in st["holders"]:
                    st["holders"].append(requester)
            elif requester and len(self._bcast) < 1024:
                self._bcast[oid] = {"grants": {}, "holders": [requester]}
        conn.sendall(bytes([ST_OK]))

    def _handle_invoke(self, conn: socket.socket, name: str,
                       payload: bytes) -> None:
        """Cross-language task submission (OP_INVOKE): run the registered
        function as a normal task and answer with the result's ObjectID —
        the caller pulls it with OP_PULL like any other object."""
        if self._on_invoke is None:
            conn.sendall(bytes([ST_ERROR]))
            return
        try:
            result_id = self._on_invoke(name, payload)
        except KeyError:
            conn.sendall(bytes([ST_NOT_FOUND]))
            return
        except Exception:  # noqa: BLE001 — submission (not task) failure
            conn.sendall(bytes([ST_ERROR]))
            return
        idb = str(result_id).encode()
        conn.sendall(bytes([ST_OK]) + struct.pack("<H", len(idb)) + idb)

    @staticmethod
    def _send_failed(conn: socket.socket, store, oid: ObjectID) -> None:
        from ray_tpu._private import serialization

        err = store.get_error(oid) or RuntimeError(f"object {oid} failed")
        try:
            payload = serialization.dumps(err)
        except Exception:
            payload = serialization.dumps(RuntimeError(repr(err)))
        conn.sendall(bytes([ST_FAILED]) + struct.pack("<Q", len(payload)))
        _send_payload(conn, payload)

    # ------------------------------------------------- channel plane
    def _chan_arena(self):
        store = self._store_provider()
        return getattr(store, "plasma", None) if store is not None else None

    def _handle_chan_push(self, conn: socket.socket, name: str) -> None:
        seq, maxsize, flags = struct.unpack("<IIB", _recv_exact(conn, 9))
        probe = bool(flags & 1)
        payload = b""
        if not probe:
            (size,) = struct.unpack("<Q", _recv_exact(conn, 8))
            payload = _recv_into(conn, size)
        arena = self._chan_arena()
        if arena is None:
            conn.sendall(bytes([ST_ERROR]))
            return
        if arena.contains(f"{name}:__closed__"):
            conn.sendall(bytes([ST_CLOSED]))
            return
        with self._chan_lock:
            # Duplicate of an already-accepted element (the ack was lost to
            # a reset and the producer retried): acknowledge, never re-seal
            # — the reader may have consumed it already.
            duplicate = seq < self._chan_next.get(name, 0)
            admissible = False
            if not duplicate:
                floor = self._chan_floors.get(name, 0)
                while floor < seq and not arena.contains(f"{name}:{floor}"):
                    floor += 1
                self._chan_floors[name] = floor
                admissible = seq - floor < max(1, maxsize)
        # All socket I/O happens OUTSIDE the lock (a stalled peer must not
        # head-of-line block every other channel through this node).
        if duplicate:
            conn.sendall(bytes([ST_OK]))
            return
        if not admissible:
            conn.sendall(bytes([ST_FULL]))
            return
        if not probe:
            # The payload memcpy runs OUTSIDE the lock (a multi-MB copy
            # under the global lock would head-of-line block every other
            # channel through this node); contains() guards the race with a
            # duplicate re-push of the same seq.  chan_next advances only
            # AFTER the element is sealed, and before the ack — so a
            # retried seq is dup-acked only once it really exists.
            try:
                if not arena.contains(f"{name}:{seq}"):
                    arena.put_bytes(f"{name}:{seq}", bytes(payload))
            except Exception:
                conn.sendall(bytes([ST_ERROR]))
                return
            with self._chan_lock:
                self._chan_next[name] = max(
                    self._chan_next.get(name, 0), seq + 1)
        conn.sendall(bytes([ST_OK]))

    def _handle_chan_reclaim(self, conn: socket.socket, name: str,
                             drop_sentinel: bool, budget: int) -> None:
        """Delete a torn-down channel's arena objects (same probe-forward
        scheme as SharedMemoryChannel.reclaim, run where the arena lives;
        the caller sizes ``budget`` to its maxsize so deep channels don't
        out-run the miss tolerance)."""
        arena = self._chan_arena()
        if arena is None:
            conn.sendall(bytes([ST_ERROR]))
            return

        def drop(key: str) -> bool:
            try:
                if not arena.contains(key):
                    return False
                arena.release(key)
                arena.delete(key)
                return True
            except Exception:
                return False

        with self._chan_lock:
            start = self._chan_floors.pop(name, 0)
            self._chan_next.pop(name, None)
        misses, k = 0, start
        budget = max(256, min(budget, 1 << 20))
        while misses < budget:
            if drop(f"{name}:{k}"):
                misses = 0
            else:
                misses += 1
            k += 1
        if drop_sentinel:
            drop(f"{name}:__closed__")
        conn.sendall(bytes([ST_OK]))

    def _handle_push(self, conn: socket.socket, oid: ObjectID) -> None:
        (owner_len,) = struct.unpack("<H", _recv_exact(conn, 2))
        owner = _recv_exact(conn, owner_len).decode() if owner_len else ""
        (size,) = struct.unpack("<Q", _recv_exact(conn, 8))
        store = self._store_provider()
        created = store.create_for_receive(oid, size, owner=owner) \
            if store is not None and hasattr(store, "create_for_receive") \
            else None
        if created is not None:
            # Zero-copy landing: the pushed payload goes straight from the
            # socket into a pre-created arena buffer.
            buf, commit, abort = created
            try:
                _recv_into_view(conn, buf, size)
            except BaseException:
                abort()
                raise
            commit()
            if self._on_received is not None:
                self._on_received(oid)
            conn.sendall(bytes([ST_OK]))
            return
        payload = _recv_into(conn, size)
        if store is None:
            conn.sendall(bytes([ST_ERROR]))
            return
        if not store.contains(oid):
            store.put_serialized(oid, bytes(payload), owner=owner)
            if self._on_received is not None:
                self._on_received(oid)
        conn.sendall(bytes([ST_OK]))

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        if local_server_addr() == self.addr:
            _set_local_addr("")


# Same-host handoff: cache of read-only mappings of peer arena files
# (one per peer node process; page-table cost only).  Insertion-ordered for
# LRU eviction — a dead peer's multi-GB (unlinked) arena must not stay
# resident just because we once pulled from it.
# path -> [mmap, view, size, refs, doomed].  refs counts in-flight handoff
# copies holding slices of the view; doomed marks an evicted/refreshed entry
# whose unmap must wait for the last ref (releasing the parent memoryview
# invalidates every live slice — a concurrent LRU eviction would otherwise
# kill a copy mid-flight).
_ARENA_MAPS: Dict[str, list] = {}
_ARENA_MAPS_LOCK = threading.Lock()
_ARENA_MAPS_MAX = 32


def _unmap_arena_entry(ent) -> None:
    try:
        ent[1].release()
        ent[0].close()
    except (BufferError, OSError):
        pass


def _drop_arena_map_locked(path: str) -> None:
    old = _ARENA_MAPS.pop(path, None)
    if old is None:
        return
    if old[3] > 0:
        old[4] = True  # last _arena_map_unref unmaps
    else:
        _unmap_arena_entry(old)


def _arena_map_unref(ent) -> None:
    with _ARENA_MAPS_LOCK:
        ent[3] -= 1
        if ent[4] and ent[3] <= 0:
            _unmap_arena_entry(ent)


def _map_peer_arena(path: str, refresh: bool = False):
    """Read-only view over a peer node's arena file, or None when the path
    isn't mappable here (true remote host).  Returns (view, size, unref);
    the caller MUST call unref() once done copying — the mapping is only
    unmapped when evicted AND unreferenced."""
    import mmap as _mmap
    import os as _os
    from functools import partial as _partial

    with _ARENA_MAPS_LOCK:
        if refresh or (path in _ARENA_MAPS and not _os.path.exists(path)):
            # Explicit refresh, or the peer died and its file was swept:
            # drop the stale mapping so the kernel can reclaim the pages.
            _drop_arena_map_locked(path)
        ent = _ARENA_MAPS.get(path)
        if ent is not None:
            _ARENA_MAPS[path] = _ARENA_MAPS.pop(path)  # LRU touch
            ent[3] += 1
            return ent[1], ent[2], _partial(_arena_map_unref, ent)
        try:
            fd = _os.open(path, _os.O_RDONLY)
        except OSError:
            return None
        try:
            size = _os.fstat(fd).st_size
            m = _mmap.mmap(fd, size, prot=_mmap.PROT_READ)
        except (OSError, ValueError):
            return None
        finally:
            _os.close(fd)
        ent = [m, memoryview(m), size, 1, False]
        _ARENA_MAPS[path] = ent
        while len(_ARENA_MAPS) > _ARENA_MAPS_MAX:
            _drop_arena_map_locked(next(iter(_ARENA_MAPS)))
        return ent[1], ent[2], _partial(_arena_map_unref, ent)


def _request_sock(addr: str, timeout: float) -> socket.socket:
    host, port = addr.rsplit(":", 1)
    sock = socket.create_connection((host, int(port)), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    _tune_sock(sock)
    return sock


def _req_header(op: int, oid: ObjectID) -> bytes:
    idb = str(oid).encode()
    return bytes([op]) + struct.pack("<H", len(idb)) + idb


class PullManager:
    """Client side of the transfer plane (ref: pull_manager.h:52).

    Deduplicates concurrent pulls of the same object, bounds total in-flight
    payload bytes (`max_inflight_pull_bytes`), and lands completed pulls in
    the local store's serialized tier via ``on_complete``.
    """

    def __init__(self, store, on_complete: Optional[Callable[[ObjectID], None]] = None,
                 on_failure: Optional[Callable[[ObjectID, str], None]] = None,
                 is_live: Optional[Callable[[ObjectID], bool]] = None):
        self._store = store
        self._on_complete = on_complete
        self._on_failure = on_failure
        self._is_live = is_live
        self._lock = threading.Lock()
        self._inflight: Dict[ObjectID, threading.Event] = {}
        self._errors: Dict[ObjectID, str] = {}
        self._inflight_bytes = 0
        self._bytes_cv = threading.Condition(self._lock)
        #: peers whose arena file we could not map (true remote hosts) —
        #: skip the handoff round trip for them from then on.
        self._no_handoff: set = set()
        #: addr -> pooled idle connections to that peer's object server.
        self._socks: Dict[str, list] = {}
        self.stats = {"pulls": 0, "pull_bytes": 0, "dedup_hits": 0,
                      "failures": 0, "handoffs": 0, "handoff_bytes": 0,
                      #: source addr -> bytes pulled from it (broadcast-tree
                      #: evidence: followers' bytes spread across peers).
                      "sources": {}}

    # ------------------------------------------------------------------ async
    def request(self, oid: ObjectID, addr: str) -> None:
        """Fire-and-forget pull; completion wakes store waiters, terminal
        failure (after retries) reports through ``on_failure`` so tasks
        blocked on the dependency fail instead of hanging forever."""
        with self._lock:
            if self._store.contains(oid) or oid in self._inflight:
                self.stats["dedup_hits"] += 1
                return
            ev = threading.Event()
            self._inflight[oid] = ev
        threading.Thread(target=self._pull_into_store, args=(oid, addr, ev),
                         kwargs={"retries": GLOBAL_CONFIG.object_transfer_pull_retries,
                                 "report_failure": True},
                         name="objxfer-pull", daemon=True).start()

    # --------------------------------------------------------------- blocking
    def pull_blocking(self, oid: ObjectID, addr: str,
                      timeout: Optional[float] = None) -> None:
        """Pull (or join an in-flight pull) and wait for it to land.

        ``timeout=None`` waits indefinitely (matching local get semantics —
        the owner answers ST_PENDING while a producer is still running, and
        we keep retrying); ``timeout<=0`` is an immediate-deadline probe.
        """
        if timeout is not None and timeout <= 0:
            if self._store.contains(oid):
                return
            from ray_tpu.exceptions import GetTimeoutError

            raise GetTimeoutError(f"object {oid} not local and timeout<=0")
        wait_s = timeout
        with self._lock:
            if self._store.contains(oid):
                return
            ev = self._inflight.get(oid)
            if ev is None:
                ev = threading.Event()
                self._inflight[oid] = ev
                starter = True
            else:
                self.stats["dedup_hits"] += 1
                starter = False
        if starter:
            self._pull_into_store(oid, addr, ev, timeout=wait_s)
        else:
            if not ev.wait(wait_s):
                from ray_tpu.exceptions import GetTimeoutError

                raise GetTimeoutError(
                    f"timed out waiting for in-flight pull of {oid}")
        if not self._store.contains(oid):
            # Read without popping: several callers may be joined on the same
            # failed pull and each must observe the error.  A mere timeout is
            # GetTimeoutError (retryable, matching local get semantics), not
            # object loss.
            with self._lock:
                entry = self._errors.get(oid)
            timed_out, err = entry if entry else (False, None)
            if timed_out:
                from ray_tpu.exceptions import GetTimeoutError

                raise GetTimeoutError(err)
            raise ObjectTransferError(
                err or f"pull of {oid} from {addr} did not land")

    def _pull_into_store(self, oid: ObjectID, addr: str, ev: threading.Event,
                         timeout: Optional[float] = None, retries: int = 0,
                         report_failure: bool = False) -> None:
        try:
            attempt = 0
            while True:
                try:
                    tag, payload = self._fetch(oid, addr, timeout)
                    break
                except _RemoteTaskFailed as rf:
                    # The producing task failed on the owner: land the
                    # ORIGINAL error locally so getters re-raise it (parity
                    # with local task-failure semantics).
                    if not self._store.contains(oid):
                        self._store.put_error(oid, rf.error)
                    if self._on_complete is not None:
                        self._on_complete(oid)
                    return
                except Exception:
                    attempt += 1
                    if attempt > retries:
                        raise
                    import time

                    time.sleep(min(1.0, 0.1 * (2 ** attempt)))
            size = payload if tag == "landed" else len(payload)
            if self._is_live is not None and not self._is_live(oid):
                # Every local ref died while the pull was in flight: landing
                # the payload now would park unreclaimable bytes in the store
                # (the zero-refcount callback already fired).  Drop it — a
                # direct-landed payload is already sealed, so free it.
                if tag == "landed":
                    self._store.free(oid)
                return
            if tag != "landed" and not self._store.contains(oid):
                self._store.put_serialized(oid, bytes(payload))
            with self._lock:
                self.stats["pulls"] += 1
                self.stats["pull_bytes"] += size
                self._errors.pop(oid, None)
            if self._on_complete is not None:
                self._on_complete(oid)
        except Exception as e:  # noqa: BLE001 — recorded, surfaced to waiters
            timed_out = isinstance(e, (socket.timeout, TimeoutError))
            msg = f"pull of {oid} from {addr} failed: {e!r}"
            with self._lock:
                self.stats["failures"] += 1
                if len(self._errors) > 4096:  # bounded error memory
                    self._errors.pop(next(iter(self._errors)))
                self._errors[oid] = (timed_out, msg)
            if report_failure and self._on_failure is not None:
                # Dependency pulls already retried; even a timeout is
                # terminal for the parked task at this point.
                self._on_failure(oid, msg)
        finally:
            with self._lock:
                self._inflight.pop(oid, None)
            ev.set()

    def _fetch(self, oid: ObjectID, addr: str,
               timeout: Optional[float] = None) -> Tuple[str, object]:
        """Tree-aware pull (ref: the reference's location-directed pulls):
        ask the owner where to pull from first, so an N-node broadcast of a
        large object forms a fan-out tree instead of N direct streams.  A
        failed peer pull falls back to the owner; per-source byte counts
        land in ``stats["sources"]`` (the bench's sub-linearity evidence).
        """
        src, engaged = addr, False
        me = local_server_addr()
        if (GLOBAL_CONFIG.broadcast_tree_enabled and addr and me
                and me != addr):
            got = self._negotiate_source(oid, addr, timeout)
            if got is not None:
                peer, engaged = got
                if peer:
                    src = peer
        try:
            result = self._fetch_direct(oid, src, timeout)
        except _RemoteTaskFailed:
            raise
        except Exception:
            if src == addr:
                raise
            # The peer we were redirected to failed us: the owner still
            # holds the primary copy — pull it directly.
            src = addr
            result = self._fetch_direct(oid, addr, timeout)
        if engaged:
            self._announce(oid, addr)
        size = result[1] if result[0] == "landed" else len(result[1])
        with self._lock:
            srcs = self.stats.setdefault("sources", {})
            srcs[src] = srcs.get(src, 0) + size
        return result

    def _negotiate_source(self, oid: ObjectID, owner: str,
                          timeout: Optional[float]):
        """OP_PULL_LOC round-trips with the owner until it names a source.

        Returns ``(source_addr, tree_engaged)`` — empty source means pull
        from the owner itself — or ``None`` when negotiation can't be used
        (owner unreachable / predates the op / object unknown there) and
        the caller should just pull directly without announcing."""
        me = local_server_addr().encode()
        req = _req_header(OP_PULL_LOC, oid) \
            + struct.pack("<H", len(me)) + me
        deadline = None if timeout is None else time.monotonic() + timeout
        stale = 0
        while True:
            sock_timeout = GLOBAL_CONFIG.object_transfer_pull_timeout_s
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"pull of {oid} from {owner} timed out (negotiation)")
                sock_timeout = min(sock_timeout, max(remaining, 0.05))
            try:
                sock, reused = self._borrow_sock(owner, sock_timeout)
            except OSError:
                return None
            ok = False
            try:
                sock.sendall(req)
                status = _recv_exact(sock, 1)[0]
                if status == ST_OK:
                    tree = _recv_exact(sock, 1)[0] != 0
                    (alen,) = struct.unpack("<H", _recv_exact(sock, 2))
                    srcb = _recv_exact(sock, alen) if alen else b""
                    ok = True
                    return (srcb.decode(), tree)
                if status != ST_PENDING:
                    return None
                ok = True
            except (ConnectionError, OSError):
                if reused and stale < 4:
                    stale += 1
                    continue
                return None
            finally:
                if ok:
                    self._return_sock(owner, sock)
                else:
                    try:
                        sock.close()
                    except OSError:
                        pass
            time.sleep(0.05)

    def _announce(self, oid: ObjectID, owner: str) -> None:
        """Fire-and-forget completion report: frees our grant slot on the
        owner and registers us as a redirect target for later pullers."""
        me = local_server_addr().encode()
        try:
            sock, _ = self._borrow_sock(
                owner, GLOBAL_CONFIG.object_transfer_pull_timeout_s)
        except OSError:
            return
        ok = False
        try:
            sock.sendall(_req_header(OP_ANNOUNCE, oid)
                         + struct.pack("<H", len(me)) + me)
            ok = _recv_exact(sock, 1)[0] == ST_OK
        except (ConnectionError, OSError):
            pass
        finally:
            if ok:
                self._return_sock(owner, sock)
            else:
                try:
                    sock.close()
                except OSError:
                    pass

    def _fetch_direct(self, oid: ObjectID, addr: str,
                      timeout: Optional[float] = None) -> Tuple[str, object]:
        """One logical pull; retries while the owner answers ST_PENDING.

        Returns ``("landed", size)`` when the payload was received straight
        into a pre-created arena buffer (already sealed in the store — the
        kernel's recv copy was the only copy), or ``("bytes", payload)``
        when the arena couldn't take it and the caller should
        ``put_serialized`` the payload.

        ``timeout=None`` = no deadline (the per-request socket timeout still
        bounds each round trip, so a dead owner raises promptly).
        """
        import time

        streams = max(1, GLOBAL_CONFIG.parallel_pull_streams)
        chunk = max(1 << 20, GLOBAL_CONFIG.parallel_pull_chunk_bytes)
        first_len = (1 << 63) if streams <= 1 else chunk
        sock_timeout = GLOBAL_CONFIG.object_transfer_pull_timeout_s
        deadline = None if timeout is None else time.monotonic() + timeout
        handoff = GLOBAL_CONFIG.same_host_handoff and addr not in self._no_handoff
        sock: Optional[socket.socket] = None
        reused = False
        stale = 0
        ok = False
        try:
            while True:
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"pull of {oid} from {addr} timed out")
                    sock_timeout = min(
                        GLOBAL_CONFIG.object_transfer_pull_timeout_s,
                        max(remaining, 0.05))
                if sock is None:
                    sock, reused = self._borrow_sock(addr, sock_timeout)
                else:
                    sock.settimeout(sock_timeout)
                try:
                    if handoff:
                        outcome = self._region_attempt(sock, oid, addr,
                                                       sock_timeout)
                        if outcome == "pending":
                            time.sleep(0.05)
                            continue
                        if outcome == "no-map":
                            # Peer's arena isn't mappable here: a real
                            # remote host.  Remember and use the socket path.
                            self._no_handoff.add(addr)
                            handoff = False
                            continue
                        if outcome == "socket":
                            # This object isn't arena-resident on the owner
                            # right now; socket-pull it (peer stays
                            # eligible).
                            handoff = False
                            continue
                        ok = True
                        return outcome
                    sock.sendall(_req_header(OP_PULL_RANGE, oid)
                                 + struct.pack("<QQ", 0, first_len))
                    status = _recv_exact(sock, 1)[0]
                except (ConnectionError, OSError):
                    # A pooled socket may have gone stale (peer restarted or
                    # idle-closed); retry on a fresh connection before
                    # declaring the pull failed.
                    try:
                        sock.close()
                    except OSError:
                        pass
                    sock = None
                    if reused and stale < 4:
                        stale += 1
                        reused = False
                        continue
                    raise
                if status == ST_PENDING:
                    # Producer still running on the owner — keep waiting.
                    time.sleep(0.05)
                    continue
                if status == ST_FAILED:
                    (size,) = struct.unpack("<Q", _recv_exact(sock, 8))
                    from ray_tpu._private import serialization

                    err = serialization.loads(bytes(_recv_into(sock, size)))
                    ok = True
                    raise _RemoteTaskFailed(err)
                if status != ST_OK:
                    ok = True
                    raise ObjectTransferError(
                        f"owner at {addr} has no object {oid} (status={status})")
                (total,) = struct.unpack("<Q", _recv_exact(sock, 8))
                self._acquire_budget(total, sock_timeout)
                try:
                    created = self._store.create_for_receive(oid, total) \
                        if hasattr(self._store, "create_for_receive") else None
                    if created is not None:
                        buf, commit, abort = created
                    else:
                        fallback = bytearray(total)
                        buf, commit, abort = memoryview(fallback), None, None
                    try:
                        n0 = min(first_len, total)
                        _recv_into_view(sock, buf, n0)
                        if total > n0:
                            self._fetch_ranges(oid, addr, sock, buf, n0,
                                               total, chunk, streams,
                                               sock_timeout)
                    except BaseException:
                        if abort is not None:
                            abort()
                        raise
                    if commit is not None:
                        commit()
                        ok = True
                        return ("landed", total)
                    ok = True
                    return ("bytes", fallback)
                finally:
                    self._release_budget(total)
        finally:
            if sock is not None:
                if ok:
                    self._return_sock(addr, sock)
                else:
                    try:
                        sock.close()
                    except OSError:
                        pass

    def _borrow_sock(self, addr: str,
                     timeout: float) -> Tuple[socket.socket, bool]:
        """Pooled connection to a peer's object server (ref: the reference's
        per-remote-node rpc client cache) — saves the connect + accept +
        server-thread spawn per pull."""
        with self._lock:
            pool = self._socks.get(addr)
            if pool:
                s = pool.pop()
                try:
                    s.settimeout(timeout)
                    return s, True
                except OSError:
                    pass
        return _request_sock(addr, timeout), False

    def _return_sock(self, addr: str, sock: socket.socket) -> None:
        with self._lock:
            pool = self._socks.setdefault(addr, [])
            if len(pool) < 4:
                pool.append(sock)
                return
        try:
            sock.close()
        except OSError:
            pass

    def _region_attempt(self, sock: socket.socket, oid: ObjectID, addr: str,
                        sock_timeout: float):
        """One same-host handoff attempt.  Returns a ``("landed"|"bytes",
        ...)`` result, or "pending" / "no-map" / "socket" control strings
        (see _fetch).  The server holds the region pinned until we send the
        done byte (or the socket closes)."""
        import zlib

        sock.sendall(_req_header(OP_REGION, oid))
        status = _recv_exact(sock, 1)[0]
        if status == ST_PENDING:
            return "pending"
        if status == ST_FAILED:
            (size,) = struct.unpack("<Q", _recv_exact(sock, 8))
            from ray_tpu._private import serialization

            err = serialization.loads(bytes(_recv_into(sock, size)))
            raise _RemoteTaskFailed(err)
        if status == ST_ERROR:
            return "socket"
        if status != ST_OK:
            raise ObjectTransferError(
                f"owner at {addr} has no object {oid} (status={status})")
        roff, size, plen = struct.unpack("<QQH", _recv_exact(sock, 18))
        path = _recv_exact(sock, plen).decode()
        crc_head, crc_tail = struct.unpack("<II", _recv_exact(sock, 8))

        def src_ok(view: memoryview, mapped: int) -> bool:
            if roff + size > mapped:
                return False
            n = min(4096, size)
            if n == 0:
                return True
            if zlib.crc32(view[roff:roff + n]) != crc_head:
                return False
            return zlib.crc32(
                view[roff + max(0, size - n):roff + size]) == crc_tail

        ent = _map_peer_arena(path)
        if ent is not None and not src_ok(ent[0], ent[1]):
            ent[2]()
            ent = _map_peer_arena(path, refresh=True)  # stale map (path reuse)
        if ent is None or not src_ok(ent[0], ent[1]):
            # Unmappable (remote host) vs mapped-but-mismatched: only the
            # former disqualifies the peer.  Either way release the server's
            # pin NOW — this connection is pooled and the server is parked
            # in its done-byte wait until we answer.
            if ent is not None:
                ent[2]()
            try:
                sock.sendall(b"\x01")
            except OSError:
                pass
            return "no-map" if ent is None else "socket"
        view, _, unref = ent
        try:
            src = view[roff:roff + size]
            self._acquire_budget(size, sock_timeout)
            try:
                created = self._store.create_for_receive(oid, size) \
                    if hasattr(self._store, "create_for_receive") else None
                if created is not None:
                    buf, commit, abort = created
                    try:
                        buf[:size] = src
                    except BaseException:
                        abort()
                        raise
                    commit()
                    result = ("landed", size)
                else:
                    result = ("bytes", bytearray(src))
            finally:
                self._release_budget(size)
        finally:
            unref()
        with self._lock:
            self.stats["handoffs"] += 1
            self.stats["handoff_bytes"] += size
        try:
            sock.sendall(b"\x01")  # release the server-side pin promptly
        except OSError:
            pass  # close() releases it anyway
        return result

    def _fetch_ranges(self, oid: ObjectID, addr: str, sock0: socket.socket,
                      buf: memoryview, start: int, total: int, chunk: int,
                      streams: int, sock_timeout: float) -> None:
        """Pull the remainder of a large object as parallel range streams
        (ref: push_manager.h chunked parallel transfer): the already-open
        socket keeps pulling ranges while up to ``streams - 1`` extra
        connections work the same offset queue into disjoint slices of the
        destination buffer."""
        offsets = list(range(start, total, chunk))
        offsets.reverse()  # pop() from the low end first
        qlock = threading.Lock()
        errors: list = []

        def pull_range(s: socket.socket, off: int) -> None:
            ln = min(chunk, total - off)
            s.sendall(_req_header(OP_PULL_RANGE, oid)
                      + struct.pack("<QQ", off, ln))
            status = _recv_exact(s, 1)[0]
            if status != ST_OK:
                raise ObjectTransferError(
                    f"range pull of {oid} from {addr} failed (status={status})")
            (tot,) = struct.unpack("<Q", _recv_exact(s, 8))
            if tot != total:
                raise ObjectTransferError(
                    f"object {oid} changed size mid-pull ({tot} != {total})")
            _recv_into_view(s, buf, ln, offset=off)

        def worker(s: socket.socket) -> None:
            while True:
                with qlock:
                    if errors or not offsets:
                        return
                    off = offsets.pop()
                try:
                    pull_range(s, off)
                except BaseException as e:  # noqa: BLE001 — joined below
                    with qlock:
                        errors.append(e)
                    return

        extra = min(streams - 1, len(offsets) - 1)
        socks, threads = [], []
        try:
            for _ in range(max(0, extra)):
                try:
                    socks.append(self._borrow_sock(addr, sock_timeout)[0])
                except OSError:
                    break  # fewer streams, not failure
            for s in socks:
                t = threading.Thread(target=worker, args=(s,),
                                     name="objxfer-range", daemon=True)
                t.start()
                threads.append(t)
            worker(sock0)
            for t in threads:
                t.join()
        finally:
            for s in socks:
                if not errors:
                    self._return_sock(addr, s)
                else:
                    try:
                        s.close()
                    except OSError:
                        pass
        if errors:
            raise errors[0]

    def _acquire_budget(self, size: int, timeout: float) -> None:
        cap = GLOBAL_CONFIG.max_inflight_pull_bytes
        with self._bytes_cv:
            # A single object larger than the cap is admitted alone rather
            # than deadlocking (the reference's pull manager makes the same
            # at-least-one-request progress guarantee).
            while self._inflight_bytes > 0 and self._inflight_bytes + size > cap:
                if not self._bytes_cv.wait(timeout):
                    raise ObjectTransferError(
                        f"pull budget ({cap} bytes) not available within {timeout}s")
            self._inflight_bytes += size

    def _release_budget(self, size: int) -> None:
        with self._bytes_cv:
            self._inflight_bytes -= size
            self._bytes_cv.notify_all()


# ------------------------------------------------------------------- one-shots
def contains(addr: str, oid: ObjectID, timeout: float = 5.0) -> bool:
    sock = _request_sock(addr, timeout)
    try:
        sock.sendall(_req_header(OP_CONTAINS, oid))
        return _recv_exact(sock, 1)[0] == ST_OK
    finally:
        sock.close()


def push(store, oid: ObjectID, addr: str, owner: str = "",
         timeout: Optional[float] = None) -> None:
    """Proactively send a local object to a peer (ref: push_manager.h:30).

    Arena-resident objects ship via sendfile straight from the tmpfs arena
    (no user-space copy); anything else falls back to a view copy."""
    timeout = timeout if timeout is not None \
        else GLOBAL_CONFIG.object_transfer_pull_timeout_s
    sock = _request_sock(addr, timeout)  # connect BEFORE pinning the region
    try:
        region = store.serialized_region(oid) \
            if hasattr(store, "serialized_region") else None
        payload = None
        if region is None:
            payload = bytes(store.get_serialized(oid, timeout=timeout))
            region = store.serialized_region(oid) \
                if hasattr(store, "serialized_region") else None
        ob = owner.encode()
        if region is not None:
            fd, roff, size, release = region
            try:
                sock.sendall(_req_header(OP_PUSH, oid)
                             + struct.pack("<H", len(ob)) + ob
                             + struct.pack("<Q", size))
                _send_region(sock, store, fd, roff, size)
            finally:
                release()
        else:
            sock.sendall(_req_header(OP_PUSH, oid)
                         + struct.pack("<H", len(ob)) + ob
                         + struct.pack("<Q", len(payload)))
            _send_payload(sock, payload)
        status = _recv_exact(sock, 1)[0]
        if status != ST_OK:
            raise ObjectTransferError(f"push of {oid} to {addr} rejected ({status})")
    finally:
        sock.close()


def free_remote(addr: str, oid: ObjectID, timeout: float = 5.0) -> None:
    """Ask a peer to drop its copy of an object (cache invalidation)."""
    sock = _request_sock(addr, timeout)
    try:
        sock.sendall(_req_header(OP_FREE, oid))
        _recv_exact(sock, 1)
    finally:
        sock.close()


# ----------------------------------------------------------- channel plane
def chan_connect(addr: str, timeout: float = 30.0) -> socket.socket:
    """Persistent producer-side connection for a channel's pushes."""
    return _request_sock(addr, timeout)


def chan_push_sock(sock: socket.socket, name: str, seq: int, maxsize: int,
                   payload: bytes, probe: bool = False) -> int:
    """Push one element (or, with ``probe``, just ask whether seq would be
    admitted — no payload travels) over an open channel connection;
    returns ST_*.  Backpressured writers poll with probes so a full channel
    costs 9 header bytes per retry, not a payload retransmit."""
    frame = _req_header(OP_CHAN_PUSH, name) + struct.pack(
        "<IIB", seq, maxsize, 1 if probe else 0)
    if probe:
        sock.sendall(frame)
    else:
        sock.sendall(frame + struct.pack("<Q", len(payload)))
        _send_payload(sock, payload)
    return _recv_exact(sock, 1)[0]


def chan_close_remote(addr: str, name: str, timeout: float = 10.0) -> None:
    sock = _request_sock(addr, timeout)
    try:
        sock.sendall(_req_header(OP_CHAN_CLOSE, name))
        _recv_exact(sock, 1)
    finally:
        sock.close()


def chan_reclaim_remote(addr: str, name: str, drop_sentinel: bool,
                        budget: int = 256, timeout: float = 30.0) -> None:
    sock = _request_sock(addr, timeout)
    try:
        sock.sendall(_req_header(OP_CHAN_RECLAIM, name)
                     + bytes([1 if drop_sentinel else 0])
                     + struct.pack("<I", budget))
        _recv_exact(sock, 1)
    finally:
        sock.close()
