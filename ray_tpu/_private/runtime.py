"""The core runtime: task manager, actor manager, dispatcher, object plane.

This is the TPU-native equivalent of the reference's CoreWorker + raylet pair
(ref: src/ray/core_worker/core_worker.h:166, src/ray/raylet/node_manager.h:117),
collapsed into one in-process control plane:

* TaskManager — pending task bookkeeping, retries, lineage-based object
  reconstruction (ref: task_manager.h:212, object_recovery_manager.h:38).
* Dispatcher — dependency wait (ref: dependency_manager.h:49) then resource
  acquisition via the ClusterScheduler, then execution on the thread tier or
  a leased process worker (ref: local_task_manager.h:58, worker_pool.h:216).
* ActorManager — actor FSM with restarts (ref: gcs_actor_manager.h:312),
  ordered mailboxes, async actors, named actor registry.
* Driver API — get/put/wait/cancel/kill with in-task resource release during
  blocking get (the reference's "worker blocked in ray.get" CPU release).

Why one process: on a TPU host, exactly one JAX client owns the chips
(multi-controller SPMD), so the natural worker model is threads sharing that
client for anything touching the TPU, with process isolation as an opt-in for
CPU-bound Python.  Multi-host is reached through jax.distributed + the
collective layer, not by forking per-device workers.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import inspect
import os
import queue
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private import serialization
from ray_tpu._private.config import GLOBAL_CONFIG, Config
from ray_tpu._private.ids import (
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    TaskID,
    WorkerID,
    put_counter,
)
from ray_tpu._private.object_ref import ObjectRef, global_refcounter
from ray_tpu._private.object_store import ObjectStore
from ray_tpu._private.process_pool import ProcessPool
from ray_tpu._private.scheduling import (
    ClusterScheduler,
    DefaultStrategy,
    PlacementGroupSchedulingStrategy,
    SchedulingStrategy,
    SpreadStrategy,
)
from ray_tpu._private.task_spec import (ActorSpec, TaskSpec,
                                        EXEC_FN_METHOD)
from ray_tpu._private import metrics_agent
from ray_tpu.util import tracing
from ray_tpu.exceptions import (
    ActorDiedError,
    ActorUnavailableError,
    GetTimeoutError,
    ObjectLostError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)

_runtime_lock = threading.Lock()
_runtime: Optional["Runtime"] = None

#: Dispatcher wake token: retry the blocked list (see _notify_resources_freed).
_RETRY_BLOCKED = object()


def _noop() -> None:
    """Stand-in release for dispatches that hold no per-task lease
    (actor calls ride the actor's standing lease)."""

_task_ctx = threading.local()


class TaskContext:
    """Per-execution context (ref: runtime_context.py RuntimeContext)."""

    __slots__ = ("task_id", "actor_id", "lease_release", "lease_reacquire", "cancelled")

    def __init__(self, task_id: TaskID, actor_id: Optional[ActorID] = None,
                 lease_release=None, lease_reacquire=None):
        self.task_id = task_id
        self.actor_id = actor_id
        self.lease_release = lease_release
        self.lease_reacquire = lease_reacquire
        self.cancelled = threading.Event()


def current_task_context() -> Optional[TaskContext]:
    return getattr(_task_ctx, "ctx", None)


class ObjectRefGenerator:
    """Streaming generator returns (ref: _raylet.pyx streaming generator
    protocol :1097/:1348): yields ObjectRefs as the remote generator yields."""

    def __init__(self, task_id: TaskID):
        self._task_id = task_id
        self._queue: "queue.Queue" = queue.Queue()
        self._done = False

    def _push(self, ref: ObjectRef) -> None:
        self._queue.put(ref)

    def _finish(self, error: Optional[BaseException] = None) -> None:
        self._queue.put(StopIteration if error is None else error)

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        item = self._queue.get()
        if item is StopIteration:
            self._queue.put(StopIteration)
            raise StopIteration
        if isinstance(item, BaseException):
            self._queue.put(item)
            raise item
        return item

    def __aiter__(self):
        return self

    async def __anext__(self):
        loop = asyncio.get_event_loop()
        try:
            return await loop.run_in_executor(None, self.__next__)
        except StopIteration:
            raise StopAsyncIteration from None


class _ActorState:
    PENDING = "PENDING_CREATION"
    ALIVE = "ALIVE"
    RESTARTING = "RESTARTING"
    DEAD = "DEAD"

    def __init__(self, spec: ActorSpec):
        self.spec = spec
        self.state = _ActorState.PENDING
        self.instance: Any = None
        # SimpleQueue: C-implemented put/get — roughly half the wakeup cost
        # of queue.Queue's pure-Python Condition dance on the actor-call
        # hot path (same FIFO + blocking semantics; we never need join()).
        self.mailbox: "queue.SimpleQueue" = queue.SimpleQueue()
        self.threads: List[threading.Thread] = []
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self.node_id: Optional[NodeID] = None
        self.release = None
        self.num_restarts = 0
        self.death_cause: Optional[BaseException] = None
        self.ready_event = threading.Event()
        self.lock = threading.Lock()
        self.is_async = any(
            inspect.iscoroutinefunction(getattr(spec.cls, m, None))
            for m in dir(spec.cls)
            if not m.startswith("__") or m == "__call__"
        )
        #: Dedicated process worker hosting the instance when
        #: isolation="process" or a runtime_env is set (see _start_actor).
        self.proc_worker = None
        #: Worker node hosting the instance when placement landed on a
        #: joined remote node (None = this process hosts it).
        self.remote_node: Optional[NodeID] = None


class _LeanExecPool:
    """Futures-free task executor: SimpleQueue dispatch to daemon threads,
    spawning a new thread only when none is idle (bounded).  Replaces
    ThreadPoolExecutor on the task hot path — its per-submit Future +
    semaphore + thread-adjust machinery cost ~75us/task (bench_core
    single_client_tasks_async); every call site ignores the result anyway."""

    def __init__(self, max_threads: int = 512, name: str = "worker"):
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._max = max_threads
        self._name = name
        #: Workers parked in q.get() whose NEXT wake-up has not been claimed
        #: by a submit.  Every queued item holds exactly one claim (an idle
        #: permit or a freshly spawned thread), so no item can be stranded —
        #: a plain "is anyone idle" read could leave one behind when two
        #: submits race, deadlocking nested tasks.
        self._idle = 0
        self._nthreads = 0
        self._threads: List[threading.Thread] = []
        self._stopped = False
        self._lock = threading.Lock()

    def submit(self, fn, *args, **kwargs) -> None:
        with self._lock:
            if self._stopped:
                # Loud, like ThreadPoolExecutor: silently dropping would leak
                # the caller's already-acquired lease and hang its waiters.
                raise RuntimeError("cannot submit after shutdown")
            if self._idle > 0:
                self._idle -= 1  # claim a parked worker's next wake
            elif self._nthreads < self._max:
                self._nthreads += 1
                t = threading.Thread(
                    target=self._run,
                    name=f"{self._name}-{self._nthreads}",
                    daemon=True,
                )
                self._threads.append(t)
                t.start()
            # else: at capacity — an active worker will claim it via the
            # idle+1 it posts after finishing its current item.
        self._q.put((fn, args, kwargs))

    def _run(self) -> None:
        # A new thread's first wake is pre-claimed by the submit that
        # spawned it, so it parks WITHOUT posting an idle permit.
        while True:
            item = self._q.get()
            if item is None:
                with self._lock:
                    self._nthreads -= 1
                return
            fn, args, kwargs = item
            try:
                fn(*args, **kwargs)
            except BaseException:  # noqa: BLE001 — never kill the pool thread
                import traceback

                traceback.print_exc()
            with self._lock:
                if self._stopped:
                    self._nthreads -= 1
                    return
                self._idle += 1

    def shutdown(self, wait: bool = False, cancel_futures: bool = False) -> None:
        with self._lock:
            self._stopped = True
            n = self._nthreads
            self._idle = 0
            threads = list(self._threads)
        if cancel_futures:
            # Drop queued-but-undispatched work so nothing runs against a
            # torn-down runtime after this returns.
            try:
                while True:
                    self._q.get_nowait()
            except queue.Empty:
                pass
        for _ in range(n):
            self._q.put(None)
        if wait:
            for t in threads:
                t.join(timeout=5)


class Runtime:
    """Singleton per process; created by ray_tpu.init()."""

    def __init__(
        self,
        num_cpus: Optional[float] = None,
        num_tpus: Optional[float] = None,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        _system_config: Optional[dict] = None,
        namespace: str = "default",
    ):
        GLOBAL_CONFIG.apply_overrides(_system_config)
        self.config: Config = GLOBAL_CONFIG
        # Chaos layer (ref: rpc_chaos.h RpcFailure): rebuild from the fresh
        # config; hot paths skip the hooks entirely when disabled.
        from ray_tpu._private import fault_injection

        fault_injection.reset_injector()
        self._chaos = fault_injection.get_injector().enabled
        # Black-box bootstrap: the flight recorder's span tap only exists
        # once the singleton does — building it here (not lazily at the
        # first dump) is what makes the ring *always-on*: spans emitted
        # before any failure seam fires must already be in it.  The
        # watchdog ticker starts here too: its tick is what samples metric
        # deltas into the ring, so without it a process with tracing off
        # (the default) would crash with an empty black box.
        from ray_tpu.util import flight_recorder, watchdog

        flight_recorder.get_recorder()
        flight_recorder.record_event(
            "runtime.start", {"pid": os.getpid()}, kind="state")
        watchdog.get_watchdog().ensure_started()
        self.job_id = JobID.from_random()
        self.worker_id = WorkerID.from_random()
        self.namespace = namespace

        self.store = ObjectStore(self.config.object_store_memory)
        self.scheduler = ClusterScheduler()
        self.process_pool = ProcessPool(self.store.arena_path, self.store.plasma)
        self.refcounter = global_refcounter()
        self.refcounter.set_zero_callback(self._on_zero_refs)

        # Node-to-node object plane (ref: object_manager.h:117) — opt-in: the
        # server makes refs leaving this process carry a routable owner
        # address; the pull manager fetches remote-owned refs on demand.
        self.object_server = None
        self._pull_mgr = None
        # Owner-side BorrowLedger — built eagerly: three threads (object
        # server ADD/RELEASE/FREE handlers) race to touch it, and a lazy
        # check-then-create could lose a concurrent borrow registration.
        from ray_tpu._private.borrowing import BorrowLedger

        self._borrows = BorrowLedger()
        #: Cross-language registry + a bounded pin window for results the
        #: foreign caller hasn't pulled yet (see register_cross_lang).
        self._cross_lang_fns: Dict[str, Any] = {}
        self._cross_lang_results: deque = deque(maxlen=256)

        # OOM defense over busy process workers (ref: memory_monitor.h:52).
        self._leased_workers: Dict[int, "_LeasedWorker"] = {}
        self._leased_lock = threading.Lock()
        self._memory_monitor = None
        if self.config.enable_object_transfer:
            self.start_object_server()

        # Cross-host worker nodes (ref: node_manager.h:117): joined nodes,
        # their in-flight dispatches, and the location table for results
        # that STAYED in a producing node's store (direct-call split).
        self.node_server = None
        self._remote_nodes: Dict[NodeID, Any] = {}
        self._remote_nodes_lock = threading.Lock()
        self._remote_inflight: Dict[TaskID, Tuple] = {}
        self._remote_lock = threading.Lock()
        self._object_locations: Dict[ObjectID, str] = {}
        self._locations_lock = threading.Lock()
        #: Waiters blocked until an object resolves EITHER locally or as a
        #: remote location (_wait_value_or_location); fired by
        #: _on_object_ready so the wake is event-driven, not polled.
        self._ready_events: Dict[ObjectID, threading.Event] = {}
        self._export_release_q: Optional["queue.SimpleQueue"] = None

        # Head node resources.
        from ray_tpu._private.accelerators import detect_accelerators

        base: Dict[str, float] = {"CPU": float(num_cpus if num_cpus is not None else (os.cpu_count() or 1))}
        accel_res, accel_labels = detect_accelerators()
        if num_tpus is not None:
            accel_res["TPU"] = float(num_tpus)
        base.update(accel_res)
        base.update(resources or {})
        base.setdefault("memory", float(self.store.capacity_bytes))
        node_labels = dict(accel_labels)
        node_labels.update(labels or {})
        self.head_node_id = self.scheduler.add_node(base, node_labels)

        # Task bookkeeping.
        self._lineage: Dict[ObjectID, TaskSpec] = {}
        # RLock: a lineage pop can GC an ObjectRef whose zero-callback
        # re-enters _on_zero_refs on this same thread.
        self._lineage_lock = threading.RLock()
        self._pending_deps: Dict[TaskID, Tuple[TaskSpec, set]] = {}
        self._obj_waiters: Dict[ObjectID, List[TaskID]] = {}
        self._deps_lock = threading.Lock()
        self._ready: "queue.Queue" = queue.Queue()
        self._running: Dict[TaskID, TaskContext] = {}
        self._cancelled: set = set()
        self._generators: Dict[TaskID, ObjectRefGenerator] = {}
        #: Tasks submitted but not yet finished/failed — lets get() tell
        #: "still computing" apart from "object lost, reconstruct from lineage".
        self._inflight: set = set()

        # Actors.
        self._actors: Dict[ActorID, _ActorState] = {}
        self._named_actors: Dict[Tuple[str, str], ActorID] = {}
        self._actors_lock = threading.Lock()

        # Task events for the state API (ref: gcs_task_manager.h:86).
        self.task_events: deque = deque(maxlen=self.config.max_task_events)

        # Execution pool for the thread tier; resource accounting does the
        # real concurrency limiting, this is just a thread cache.
        self._exec_pool = _LeanExecPool(
            max_threads=512, name="ray_tpu_worker"
        )
        self._dispatcher_stop = threading.Event()
        self._blocked_count = 0
        self._retry_pending = False
        self.scheduler.on_release = self._notify_resources_freed
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="ray_tpu_dispatcher", daemon=True
        )
        self._dispatcher.start()
        self.start_time = time.time()

    # ------------------------------------------------------------------ events
    def _emit_event(self, task_id: TaskID, name: str, state: str, **extra) -> None:
        # deque.append is GIL-atomic — no lock on the hot path (3 events per
        # task at task-throughput rates); list_task_events' list(deque) is
        # likewise safe against concurrent appends.
        self.task_events.append(
            {"task_id": str(task_id), "name": name, "state": state,
             "time": time.time(), **extra}
        )
        if state in ("FINISHED", "FAILED"):
            metrics_agent.record_task_finished(state == "FINISHED")

    # ------------------------------------------------------------------- puts
    def put(self, value: Any, _owner: str = "driver") -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("Calling put() on an ObjectRef is not allowed.")
        object_id = ObjectID.from_put(put_counter.next(), self.worker_id[:8])
        self.store.put(object_id, value, owner=_owner)
        return ObjectRef(object_id, owner=_owner)

    # ----------------------------------------------------------- OOM defense
    def _track_leased_worker(self, worker, retriable: bool) -> None:
        """Register a busy process worker as an OOM-kill candidate
        (ref: raylet worker_killing_policy — the monitor picks victims among
        running workers, retriable-first/newest-first)."""
        entry = _LeasedWorker(worker, retriable)
        with self._leased_lock:
            self._leased_workers[id(worker)] = entry
        self._maybe_start_memory_monitor()

    def _untrack_leased_worker(self, worker) -> None:
        with self._leased_lock:
            self._leased_workers.pop(id(worker), None)

    def _maybe_start_memory_monitor(self) -> None:
        if self._memory_monitor is not None \
                or self.config.memory_monitor_threshold >= 1.0:
            return
        from ray_tpu._private.memory_monitor import MemoryMonitor

        def victims():
            with self._leased_lock:
                return list(self._leased_workers.values())

        def kill(lw):
            # Re-check ENTRY IDENTITY under the lock: the task may have
            # finished and the worker been re-leased to a new (possibly
            # non-retriable) task between the monitor's snapshot and this
            # kill — a same-id fresh entry means the victim is gone.
            with self._leased_lock:
                if self._leased_workers.get(id(lw.worker)) is not lw:
                    return
                lw.worker.kill()

        self._memory_monitor = MemoryMonitor(
            victims_fn=victims, kill_fn=kill,
            threshold=self.config.memory_monitor_threshold,
            check_interval_s=self.config.memory_monitor_interval_s,
            min_memory_free_bytes=(
                self.config.memory_monitor_min_free_bytes or None))
        self._memory_monitor.start()

    # --------------------------------------------------- cluster introspection
    # Uniform surface shared with ClientRuntime so the public API never has
    # to reach into `.scheduler` / private state (ray:// proxies these).
    def cluster_resources(self) -> Dict[str, float]:
        return self.scheduler.cluster_resources()

    def available_resources(self) -> Dict[str, float]:
        return self.scheduler.available_resources()

    def nodes(self) -> List[dict]:
        return [n.snapshot() for n in self.scheduler.nodes()]

    def list_task_events(self) -> List[dict]:
        # Appends are lock-free (see _emit_event); list(deque) can raise if
        # a GC-triggered thread switch lands an append mid-copy — retry,
        # backing off so the appenders drain.  Never fabricate emptiness:
        # an operator debugging an overload must not see zero tasks.
        for attempt in range(64):
            try:
                return list(self.task_events)
            except RuntimeError:
                if attempt > 8:
                    time.sleep(0.001)
        raise RuntimeError(
            "task-event snapshot kept colliding with concurrent appends")

    # --------------------------------------------------------- object plane
    def start_object_server(self) -> str:
        """Start (idempotently) the node object server; returns host:port."""
        from ray_tpu._private import object_transfer

        if self.object_server is None:
            self.object_server = object_transfer.ObjectTransferServer(
                lambda: self.store, on_received=self._on_object_ready,
                is_pending=self._object_is_pending,
                on_borrow=self._on_remote_borrow,
                on_borrow_release=self._on_remote_borrow_release,
                on_invoke=self._cross_lang_invoke,
                may_free=lambda oid: (
                    self.refcounter.count(oid) == 0
                    and not self._borrow_ledger().is_borrowed(oid)),
                on_borrower_lost=self._on_borrower_lost,
                host=self.config.object_transfer_host)
        self._pull_manager()  # pulls and serves share a lifetime
        return self.object_server.addr

    # ---------------------------------------------------- cross-language
    def register_cross_lang(self, name: str, fn) -> None:
        """Publish `fn` for name-based invocation by non-Python clients
        over the object plane (OP_INVOKE; the registry model of the
        reference's cross-language calls — a C++ caller cannot produce a
        Python closure, so the driver registers the callable).  `fn`
        receives the caller's raw bytes payload and should return bytes
        (the shape the C++ client's pickle codec speaks)."""
        self._cross_lang_fns[name] = fn

    def _cross_lang_invoke(self, name: str, payload: bytes) -> str:
        fn = self._cross_lang_fns.get(name)
        if fn is None:
            raise KeyError(name)
        import ray_tpu

        ref = ray_tpu.remote(fn).remote(payload)
        # Pin: the driver drops its reference immediately, but the foreign
        # caller still has to pull the result — keep a bounded window of
        # recent results alive (the caller cannot participate in the
        # borrower protocol).
        self._cross_lang_results.append(ref)
        return str(ref.id)

    # Borrowing protocol (owner side) — a borrowed object survives the local
    # refcount hitting zero until every borrower releases
    # (ref: reference_count.h:66 borrower bookkeeping).
    def _borrow_ledger(self):
        return self._borrows

    def _on_remote_borrow(self, object_id: ObjectID, borrower: str) -> None:
        self._borrow_ledger().add(object_id, borrower)

    def _on_remote_borrow_release(self, object_id: ObjectID, borrower: str) -> None:
        if self._borrow_ledger().release(object_id, borrower) \
                and self.refcounter.count(object_id) == 0:
            # Last borrower gone and no local handles: free now (the local
            # zero-callback already fired and deferred to the borrow).
            self._on_zero_refs(object_id)

    def _on_borrower_lost(self, borrower_id: str) -> None:
        """A borrower process died without releasing (its liveness session
        hit EOF): reap every borrow it held; objects whose LAST holder it
        was — and with no local handles — free now (ref:
        reference_count.h worker-death reclamation)."""
        for object_id in self._borrow_ledger().drop_borrower(borrower_id):
            if self.refcounter.count(object_id) == 0:
                self._on_zero_refs(object_id)

    def _object_is_pending(self, object_id: ObjectID) -> bool:
        """Owner-side directory answer: is something still producing this
        object (so a remote pull should wait instead of declaring loss)?"""
        task_id = object_id.task_id()
        if task_id in self._inflight:
            return True
        with self._lineage_lock:
            return object_id in self._lineage

    def owns_object(self, object_id: ObjectID) -> bool:
        """Is this process the object's owner (holder or producer)?  Used to
        decide whether refs leaving here may claim our server address —
        forwarding someone else's ref must not claim ownership."""
        return self.store.state_of(object_id) is not None \
            or self._object_is_pending(object_id)

    def _pull_manager(self):
        from ray_tpu._private import object_transfer

        if self._pull_mgr is None:
            self._pull_mgr = object_transfer.PullManager(
                self.store, on_complete=self._on_object_ready,
                on_failure=self._on_pull_failed,
                is_live=lambda oid: self.refcounter.count(oid) > 0)
        return self._pull_mgr

    def _on_pull_failed(self, object_id: ObjectID, msg: str) -> None:
        """Terminal failure of a dependency pull: poison the store entry so
        tasks parked on it dispatch, observe the error while resolving args,
        and fail instead of hanging (the object may still be re-created by
        lineage or a later successful pull overwriting nothing — the entry is
        already FAILED and get() raises)."""
        from ray_tpu._private.object_transfer import ObjectTransferError

        if not self.store.contains(object_id):
            self.store.put_error(object_id, ObjectTransferError(msg))
            self._on_object_ready(object_id)

    def _remote_owner_addr(self, ref: ObjectRef) -> str:
        """The address to pull a ref from, or "" if it is locally owned.

        The location table wins over the ref's stamped owner address: it is
        head-authoritative and survives reconstruction onto a different
        node, whereas the stamp is frozen at serialization time."""
        addr = self.location_of(ref.id) or getattr(ref, "owner_addr", "")
        if not addr:
            return ""
        if self.object_server is not None and addr == self.object_server.addr:
            return ""
        return addr

    # ------------------------------------------------------- worker nodes
    # Head side of cross-host execution (ref: node_manager.h:117,
    # cluster_task_manager.h:42 spillback, gcs_node_manager.h registration).
    def start_node_server(self, host: str = "127.0.0.1", port: int = 0) -> str:
        """Start (idempotently) the head's node-manager service; worker
        nodes join it via ``ray_tpu worker --address=<returned addr>``."""
        from ray_tpu._private.node_manager import NodeManagerServer

        if self.node_server is None:
            self.start_object_server()  # results/args ride the object plane
            self.node_server = NodeManagerServer(self, host=host, port=port)
        return self.node_server.address

    def location_of(self, object_id: ObjectID) -> str:
        """Object-plane address of the node holding a result that stayed
        remote ("" if unknown/local)."""
        with self._locations_lock:
            return self._object_locations.get(object_id, "")

    def _register_remote_node(self, node, info: dict) -> bool:
        """Returns True when this is a FRESH registration — the head holds
        no state for the node (first join, or loss recovery already ran and
        dropped it).  A re-register of a still-known node (transient
        reconnect that beat the loss handler) keeps the head's scheduler
        ledger so in-flight leases aren't double-counted."""
        resources = dict(info.get("resources") or {})
        labels = dict(info.get("labels") or {})
        labels.setdefault("node-ip", node.conn._sock.getpeername()[0]
                          if hasattr(node.conn, "_sock") else "")
        with self._remote_nodes_lock:
            fresh = node.node_id not in self._remote_nodes
            self._remote_nodes[node.node_id] = node
        existing = self.scheduler.get_node(node.node_id)
        if fresh or existing is None or not existing.alive:
            self.scheduler.add_node(resources, labels, node_id=node.node_id)
            fresh = True
        return fresh

    def _remote_nodes_snapshot(self) -> List:
        with self._remote_nodes_lock:
            return list(self._remote_nodes.values())

    def _remote_node(self, node_id: NodeID):
        with self._remote_nodes_lock:
            return self._remote_nodes.get(node_id)

    def _dispatch_remote(self, spec: TaskSpec, node_id: NodeID, release) -> None:
        """Ship a leased task to its node; completion frames finish it."""
        node = self._remote_node(node_id)
        if node is None or not node.alive:
            release()
            self._handle_task_failure(
                spec, WorkerCrashedError(f"node {node_id} vanished before dispatch"))
            return
        self._emit_event(spec.task_id, spec.name, "SUBMITTED_TO_WORKER",
                         node_id=str(node_id))
        with self._remote_lock:
            self._remote_inflight[spec.task_id] = (spec, release, node_id)
        try:
            node.conn.send(("task", serialization.dumps_inband(spec)))
        except (OSError, ConnectionError):
            with self._remote_lock:
                self._remote_inflight.pop(spec.task_id, None)
            release()
            # The node is gone: run loss recovery NOW so the retry below
            # (and every other blocked task) stops leasing its resources.
            self._declare_node_lost(node)
            self._handle_task_failure(
                spec, WorkerCrashedError(f"node {node_id} unreachable"))
        except BaseException as e:  # noqa: BLE001 — e.g. unpicklable func
            with self._remote_lock:
                self._remote_inflight.pop(spec.task_id, None)
            release()
            self._fail_task(spec, e, retry=False)

    def _land_remote_result(self, object_id: ObjectID, item: Tuple, node) -> None:
        kind, payload = item
        if kind == "inline":
            if not self.store.contains(object_id):
                self.store.put_serialized(object_id, payload,
                                          owner=str(node.node_id))
        else:  # "stored": primary copy stays on the producer
            with self._locations_lock:
                self._object_locations[object_id] = payload
        self._on_object_ready(object_id)

    def _on_remote_task_done(self, node, task_id: TaskID, results: List[Tuple]) -> None:
        with self._remote_lock:
            entry = self._remote_inflight.pop(task_id, None)
        if entry is None:
            return  # node-loss handling or cancel already settled it
        spec, release, _ = entry
        release()
        if spec.generator:
            gen = self._generators.pop(task_id, None)
            if results and results[0][0] == "error":
                err = serialization.loads(results[0][1])
                self._generators[task_id] = gen  # _fail_task pops + finishes
                self._handle_task_failure(spec, err)
                return
            if gen is not None:
                gen._finish()
            self._inflight.discard(task_id)
            self._emit_event(task_id, spec.name, "FINISHED")
            return
        errors = [r for r in results if r[0] == "error"]
        if errors:
            err = serialization.loads(errors[0][1])
            self._handle_task_failure(spec, err)
            return
        for i, item in enumerate(results):
            self._land_remote_result(
                ObjectID.for_task_return(spec.task_id, i), item, node)
        self._inflight.discard(task_id)
        self._emit_event(task_id, spec.name, "FINISHED")

    def _on_remote_task_yield(self, node, task_id: TaskID, index: int,
                              item: Tuple) -> None:
        object_id = ObjectID.for_task_return(task_id, index)
        if item[0] == "error":
            err = serialization.loads(item[1])
            if not isinstance(err, (TaskError, ObjectLostError)):
                err = TaskError(err, task_repr=str(task_id))
            self.store.put_error(object_id, err)
            self._on_object_ready(object_id)
        else:
            self._land_remote_result(object_id, item, node)
        gen = self._generators.get(task_id)
        if gen is not None:
            gen._push(ObjectRef(object_id, owner=str(node.node_id)))

    def _on_remote_actor_ready(self, node, actor_id: ActorID) -> None:
        state = self._actors.get(actor_id)
        if state is None:
            return
        state.state = _ActorState.ALIVE
        state.ready_event.set()
        if not state.threads:
            self._start_actor_executors(state)

    def _on_remote_actor_dead(self, node, actor_id: ActorID,
                              err: BaseException) -> None:
        """The node reports the actor terminally dead (creation failure or
        its local FSM exhausted restarts) — mirror local death handling."""
        state = self._actors.get(actor_id)
        if state is None:
            return
        with state.lock:
            state.remote_node = None
            if state.release is not None:
                state.release()
                state.release = None
            if not isinstance(err, ActorDiedError):
                err = ActorDiedError(cause=err)
            state.death_cause = err
            state.state = _ActorState.DEAD
            with self._actors_lock:
                key = (state.spec.namespace, state.spec.name)
                if state.spec.name and self._named_actors.get(key) == actor_id:
                    del self._named_actors[key]
            for _ in state.threads:
                state.mailbox.put(None)
        state.ready_event.set()
        self._drain_mailbox(state)

    def _declare_node_lost(self, node) -> None:
        """Idempotent entry to node-death recovery: a failed send, the
        reader's EOF and the heartbeat monitor all race to report it, but
        recovery — and especially removing the node from the scheduler so
        retries stop re-leasing it — must run exactly once, and EARLY (a
        retry burning its whole budget on a dead-but-still-registered node
        is the failure mode this guards)."""
        with self._remote_nodes_lock:
            if node.lost_handled:
                return
            node.lost_handled = True
        node.alive = False
        try:
            node.conn.close()
        except Exception:
            pass
        self._on_node_lost(node)

    def _on_node_lost(self, node) -> None:
        """Connection loss / missed heartbeats: remove the node, retry its
        tasks, restart its actors, reconstruct its objects (ref:
        gcs_health_check_manager.h:45, object_recovery_manager.h:38)."""
        node_id = node.node_id
        with self._remote_nodes_lock:
            superseded = self._remote_nodes.get(node_id) is not node
            if not superseded:
                self._remote_nodes.pop(node_id, None)
                # Inside the lock: a rejoin that re-registers between the
                # pop and this removal would have its fresh scheduler entry
                # deleted out from under it (register takes this lock too).
                self.scheduler.remove_node(node_id)
        if superseded:
            # The node already RE-REGISTERED over a fresh connection (rejoin
            # races this loss handler): the process is alive, its dispatched
            # work keeps running and reports over the NEW connection —
            # removing it from the registry/scheduler or restarting its
            # actors here would silently wreck a live, rejoined node.
            return

        with self._remote_lock:
            lost = [(tid, e) for tid, e in self._remote_inflight.items()
                    if e[2] == node_id]
            for tid, _ in lost:
                del self._remote_inflight[tid]
        for _tid, (spec, release, _) in lost:
            release()
            if spec.actor_id is not None:
                self._fail_task(spec, ActorDiedError(
                    f"node {node_id} died mid-call"), retry=False)
            else:
                self._handle_task_failure(
                    spec, WorkerCrashedError(f"node {node_id} died"))

        with self._locations_lock:
            lost_oids = [oid for oid, addr in self._object_locations.items()
                         if addr == node.object_addr]
            for oid in lost_oids:
                del self._object_locations[oid]
        for oid in lost_oids:
            if self.store.contains(oid):
                continue
            spec = self._lineage_for(oid)
            if spec is not None and oid.task_id() not in self._inflight:
                self._resubmit(spec)
            elif spec is None:
                self.store.put_error(oid, ObjectLostError(
                    f"object {oid} lost with node {node_id}"))
                self._on_object_ready(oid)

        with self._actors_lock:
            states = list(self._actors.values())
        for state in states:
            if state.remote_node == node_id:
                state.remote_node = None  # node gone; no kill frame to send
                self._kill_actor_state(state, ActorDiedError(
                    f"node {node_id} died"), no_restart=False)

    def _release_export(self, object_id: ObjectID, addr: str) -> None:
        """Async-release a producer's export pin (we were the last holder).
        Runs off-thread: this is reached from GC (`__del__`), which must
        never block on TCP."""
        if self._export_release_q is None:
            q: "queue.SimpleQueue" = queue.SimpleQueue()

            def _drain():
                from ray_tpu._private.borrowing import _send_borrow_op
                from ray_tpu._private.node_manager import EXPORT_BORROWER

                while True:
                    oid, a = q.get()
                    _send_borrow_op("release", oid, a, EXPORT_BORROWER)

            self._export_release_q = q
            threading.Thread(target=_drain, name="ray_tpu_export_release",
                             daemon=True).start()
        self._export_release_q.put((object_id, addr))

    # ------------------------------------------------------------------- gets
    def get(self, refs: Any, timeout: Optional[float] = None) -> Any:
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        for r in ref_list:
            if not isinstance(r, ObjectRef):
                raise TypeError(f"get() expects ObjectRef(s), got {type(r)}")
        # Vectorized fast path: one store pass resolves every ref whose
        # value is already local (the 10k-object get anchor); only the
        # stragglers take the per-ref slow path (pulls, reconstruction,
        # inflight waits).
        values, missing = self.store.try_get_many([r.id for r in ref_list])
        if not missing:
            return values[0] if single else values
        ctx = current_task_context()
        released = False
        if ctx is not None and ctx.lease_release is not None:
            # Release this task's resources while blocked (the reference
            # releases CPU while a worker blocks in ray.get).
            ctx.lease_release()
            released = True
        try:
            for i in missing:
                values[i] = self._get_one(ref_list[i], timeout)
        finally:
            if released:
                ctx.lease_reacquire()
        return values[0] if single else values

    def _get_one(self, ref: ObjectRef, timeout: Optional[float]) -> Any:
        # One deadline governs the whole get: remote pulls, inflight waits
        # and the store materialization all share it, so get(timeout=T)
        # blocks at most ~T, not a multiple (ADVICE r2).
        deadline = None if timeout is None else time.monotonic() + timeout

        def _remaining() -> Optional[float]:
            return None if deadline is None \
                else max(0.0, deadline - time.monotonic())

        reconstructs = 0
        while True:
            if self.store.contains(ref.id):
                try:
                    return self.store.get(ref.id, _remaining())
                except ObjectLostError:
                    spec = self._lineage_for(ref.id)
                    reconstructs += 1
                    if spec is None or reconstructs > 3:
                        raise
                    # Drop the poisoned/freed entry so the loop waits for
                    # the reconstruction instead of re-reading the error.
                    self.store.free(ref.id)
                    self._resubmit(spec)
                    continue
            addr = self._remote_owner_addr(ref)
            if addr:
                # Remote copy exists (owner-stamped or location table):
                # pull it (ref: pull_manager.h:52).  A lost holder falls
                # back to lineage reconstruction.
                try:
                    self._pull_manager().pull_blocking(ref.id, addr, _remaining())
                except GetTimeoutError:
                    raise
                except ObjectLostError:
                    with self._locations_lock:  # the holder lied or died
                        self._object_locations.pop(ref.id, None)
                    if ref.id.task_id() in self._inflight:
                        # A reconstruction is already running; wait for it.
                        self._wait_value_or_location(ref.id, _remaining())
                        continue
                    spec = self._lineage_for(ref.id)
                    reconstructs += 1
                    if spec is None or reconstructs > 3:
                        raise
                    self._resubmit(spec)
                continue
            task_id = ref.id.task_id()
            if task_id in self._inflight:
                # Still computing (here or on a worker node): wait for a
                # local value/error OR a remote location to appear.
                self._wait_value_or_location(ref.id, _remaining())
                continue
            # Not in flight, no local value, no known copy: lost — try
            # lineage (ref: object_recovery_manager.h:38).
            spec = self._lineage_for(ref.id)
            if spec is not None:
                self._resubmit(spec)
                continue
            return self.store.get(ref.id, _remaining())

    def _wait_value_or_location(self, object_id: ObjectID,
                                timeout: Optional[float]) -> None:
        """Block until the object resolves locally (value/error) or a
        worker node reports it produced-and-stored (location table).
        Event-driven: every completion path funnels through
        _on_object_ready, which fires the registered event."""
        if self.store.contains(object_id) or self.location_of(object_id):
            return
        with self._locations_lock:
            ev = self._ready_events.get(object_id)
            if ev is None:
                ev = self._ready_events[object_id] = threading.Event()
        try:
            # Re-check AFTER registering: a completion between the first
            # check and the registration would otherwise be missed.
            if self.store.contains(object_id) or self.location_of(object_id):
                return
            if not ev.wait(timeout):
                raise GetTimeoutError(
                    f"Timed out waiting for object {object_id}")
        finally:
            with self._locations_lock:
                if self._ready_events.get(object_id) is ev and ev.is_set():
                    del self._ready_events[object_id]

    async def get_async(self, ref: ObjectRef) -> Any:
        loop = asyncio.get_event_loop()
        return await loop.run_in_executor(None, self._get_one, ref, None)

    def as_future(self, ref: ObjectRef) -> concurrent.futures.Future:
        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _resolve():
            try:
                fut.set_result(self._get_one(ref, None))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=_resolve, daemon=True).start()
        return fut

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None, fetch_local: bool = True):
        if not refs:
            return [], []
        refs = list(refs)
        if num_returns > len(refs):
            raise ValueError("num_returns exceeds number of refs")
        if fetch_local:
            for r in refs:
                addr = self._remote_owner_addr(r)
                if addr and not self.store.contains(r.id):
                    self._pull_manager().request(r.id, addr)
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: List[ObjectRef] = []
        pending = list(refs)
        requested: set = set()
        while len(ready) < num_returns:
            progressed = False
            for r in list(pending):
                is_ready = self.store.contains(r.id)
                if not is_ready:
                    loc = self.location_of(r.id)
                    if loc:
                        if fetch_local:
                            # Produced on a worker node mid-wait: start the
                            # pull; ready once it lands.
                            if r.id not in requested:
                                requested.add(r.id)
                                self._pull_manager().request(r.id, loc)
                        else:
                            # fetch_local=False: existing anywhere counts.
                            is_ready = True
                if is_ready:
                    ready.append(r)
                    pending.remove(r)
                    progressed = True
                    if len(ready) >= num_returns:
                        break
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            if not progressed:
                remaining = 0.01 if deadline is None else min(0.01, deadline - time.monotonic())
                if pending and remaining > 0:
                    self.store.wait_ready(pending[0].id, remaining)
                elif remaining <= 0:
                    break
        return ready, pending

    # ---------------------------------------------------------------- submits
    def submit_task(self, spec: TaskSpec) -> Any:
        if tracing.is_tracing_enabled():
            with tracing.span(f"submit::{spec.name}",
                              attributes={"task_id": spec.task_id}):
                tracing.inject_task_spec(spec)
                return self._submit_task_inner(spec)
        return self._submit_task_inner(spec)

    def _submit_task_inner(self, spec: TaskSpec) -> Any:
        # Batched ownership bookkeeping: one refcounter pass for all return
        # handles instead of one lock round-trip per ref.
        oids = [ObjectID.for_task_return(spec.task_id, i)
                for i in range(spec.num_returns)]
        self.refcounter.add_many(oids)
        refs = [ObjectRef(oid, owner=self.worker_id, _add_ref=False)
                for oid in oids]
        with self._lineage_lock:
            for ref in refs:
                self._lineage[ref.id] = spec
        gen = None
        if spec.generator:
            gen = ObjectRefGenerator(spec.task_id)
            self._generators[spec.task_id] = gen
        self._emit_event(spec.task_id, spec.name, "PENDING_ARGS_AVAIL")
        self._inflight.add(spec.task_id)
        self._enqueue_after_deps(spec)
        if spec.generator:
            return gen
        return refs[0] if spec.num_returns == 1 else refs

    def _enqueue_after_deps(self, spec: TaskSpec) -> None:
        ref_args = [a for a in list(spec.args) + list(spec.kwargs.values())
                    if isinstance(a, ObjectRef)]
        if not ref_args:
            self._ready.put(spec)
            return
        deps = set()
        present = self.store.contains_many([a.id for a in ref_args])
        for a, here in zip(ref_args, present):
            if not here:
                if self.location_of(a.id):
                    # Produced, held by a worker node: the EXECUTING side
                    # pulls it on demand (it may be dispatched right back
                    # to the holder — prefetching here would drag every
                    # block through the head).
                    continue
                deps.add(a.id)
                addr = self._remote_owner_addr(a)
                if addr:
                    # Remote-owned dependency: start pulling now so the task
                    # unblocks when the transfer lands (the reference's
                    # DependencyManager subscribes+pulls args the same way).
                    self._pull_manager().request(a.id, addr)
        if not deps:
            self._ready.put(spec)
            return
        with self._deps_lock:
            dep_list = list(deps)
            landed = self.store.contains_many(dep_list)
            still = {d for d, here in zip(dep_list, landed) if not here}
            if not still:
                self._ready.put(spec)
                return
            self._pending_deps[spec.task_id] = (spec, still)
            for d in still:
                self._obj_waiters.setdefault(d, []).append(spec.task_id)

    def _on_object_ready(self, object_id: ObjectID) -> None:
        with self._locations_lock:
            ev = self._ready_events.pop(object_id, None)
        if ev is not None:
            ev.set()
        to_ready = []
        with self._deps_lock:
            for task_id in self._obj_waiters.pop(object_id, []):
                entry = self._pending_deps.get(task_id)
                if entry is None:
                    continue
                spec, deps = entry
                deps.discard(object_id)
                if not deps:
                    del self._pending_deps[task_id]
                    to_ready.append(spec)
        for spec in to_ready:
            self._ready.put(spec)

    def _resubmit(self, spec: TaskSpec) -> None:
        spec.attempt += 1
        self._emit_event(spec.task_id, spec.name, "RESUBMITTED", attempt=spec.attempt)
        self._inflight.add(spec.task_id)
        if spec.actor_id is not None:
            state = self._actors.get(spec.actor_id)
            if state is not None and state.state != _ActorState.DEAD:
                state.mailbox.put(spec)
                return
            self._fail_task(spec, ActorDiedError("actor gone; cannot reconstruct"), retry=False)
            return
        self._enqueue_after_deps(spec)

    # -------------------------------------------------------------- dispatch
    def _notify_resources_freed(self) -> None:
        """Scheduler release hook: wake the dispatcher to retry blocked tasks.

        Coalesced — at most one retry token is in the queue at a time, so a
        burst of releases costs one blocked-list scan, not one per release
        (the old retry-on-every-queue-event design degraded O(blocked x
        events): 16.7 _try_dispatch calls per task in bench_core)."""
        if self._blocked_count and not self._retry_pending:
            self._retry_pending = True
            self._ready.put(_RETRY_BLOCKED)

    @staticmethod
    def _placement_shape(spec: TaskSpec) -> tuple:
        """Bucket key under which blocked specs are interchangeable for
        placement feasibility: same resource demand + same strategy
        semantics.  Stateless strategies collapse into one bucket per
        demand shape; parameterized strategies (affinity, labels, PGs)
        bucket per instance — correct, and they are never the 1M-task
        storm case."""
        res = tuple(sorted(spec.resources.items())) if spec.resources else ()
        strat = spec.strategy
        if strat is None or type(strat) is DefaultStrategy:
            return (res, "DEFAULT")
        if type(strat) is SpreadStrategy:
            return (res, "SPREAD")
        return (res, id(strat))

    def _dispatch_loop(self) -> None:
        # Blocked tasks live in per-placement-shape FIFO queues: a capacity
        # event probes one head per shape instead of rescanning every
        # blocked spec.  The old flat list retried O(blocked) specs per
        # release and removed with O(blocked) list scans — quadratic once
        # a 1M-task backlog forms behind a busy cluster; this is
        # O(shapes + dispatched) per release.
        blocked: Dict[tuple, deque] = {}
        blocked_n = 0

        def retry_blocked() -> None:
            nonlocal blocked_n
            for key in list(blocked):
                q = blocked.get(key)
                while q:
                    if self._try_dispatch(q[0]):
                        q.popleft()
                        blocked_n -= 1
                    else:
                        break  # shape doesn't fit now; next bucket
                if not q:
                    blocked.pop(key, None)
            self._blocked_count = blocked_n

        while not self._dispatcher_stop.is_set():
            try:
                spec = self._ready.get(timeout=0.2)
            except queue.Empty:
                # Safety net for release notifications racing the flag.
                if blocked:
                    retry_blocked()
                continue
            if spec is None:
                break
            if spec is _RETRY_BLOCKED:
                self._retry_pending = False
                retry_blocked()
                continue
            key = self._placement_shape(spec)
            q = blocked.get(key)
            if q:
                # FIFO fairness: same-shape work already waits; dispatching
                # around it would starve the backlog's head forever.  Still
                # report demand — the autoscaler sizes off the full backlog,
                # not one probe per shape.
                self.scheduler.report_task_demand(spec.task_id, spec.resources)
                q.append(spec)
                blocked_n += 1
                self._blocked_count = blocked_n
            elif not self._try_dispatch(spec):
                blocked.setdefault(key, deque()).append(spec)
                blocked_n += 1
                self._blocked_count = blocked_n

    def _try_dispatch(self, spec: TaskSpec) -> bool:
        if spec.task_id in self._cancelled:
            self.scheduler.clear_task_demand(spec.task_id)
            self._fail_task(spec, TaskCancelledError(str(spec.task_id)), retry=False)
            return True
        lease = self.scheduler.try_acquire(spec.resources, spec.strategy)
        if lease is None:
            # Infeasible requests fail fast instead of hanging forever —
            # unless an autoscaler is running, which may add capacity.
            from ray_tpu._private.scheduling import DefaultStrategy

            strategy = spec.strategy or DefaultStrategy()
            with self.scheduler._lock:
                feasible = self.scheduler._feasible_anywhere_locked(spec.resources, strategy)
            # (feasibility counts launchable autoscaler node types, so this
            # is a genuine never-fits even with autoscaling on.)
            if not feasible and not isinstance(strategy, PlacementGroupSchedulingStrategy):
                from ray_tpu._private.scheduling import InfeasibleError

                # Drop any demand reported on an earlier blocked pass, or a
                # running autoscaler keeps launching nodes for a dead task.
                self.scheduler.clear_task_demand(spec.task_id)
                self._fail_task(
                    spec,
                    InfeasibleError(
                        f"Task {spec.name} requests {spec.resources} which no node can "
                        f"ever satisfy (cluster total: {self.scheduler.cluster_resources()})"
                    ),
                    retry=False,
                )
                return True
            # Blocked: visible to the autoscaler as unmet demand.
            self.scheduler.report_task_demand(spec.task_id, spec.resources)
            return False
        self.scheduler.clear_task_demand(spec.task_id)
        node_id, release = lease
        if node_id in self._remote_nodes:
            # Placed on a joined worker node: ship the spec over its
            # connection (ref: cluster_task_manager.h spillback — here the
            # grant itself lands on the remote node's resources).
            self._dispatch_remote(spec, node_id, release)
            return True
        self._emit_event(spec.task_id, spec.name, "SUBMITTED_TO_WORKER", node_id=str(node_id))
        try:
            self._exec_pool.submit(self._execute_task, spec, node_id, release)
        except RuntimeError:
            release()
            self._fail_task(spec, WorkerCrashedError("runtime is shutting down"),
                            retry=False)
        return True

    # -------------------------------------------------------------- execution
    def _execute_task(self, spec: TaskSpec, node_id: NodeID, release) -> None:
        reacquire_box = {"release": release}

        def lease_release():
            reacquire_box["release"]()

        def lease_reacquire():
            _, new_release = self.scheduler.acquire(spec.resources, spec.strategy)
            reacquire_box["release"] = new_release

        ctx = TaskContext(spec.task_id, spec.actor_id, lease_release, lease_reacquire)
        self._running[spec.task_id] = ctx
        _task_ctx.ctx = ctx
        self._emit_event(spec.task_id, spec.name, "RUNNING")
        try:
            with tracing.task_execute_span(spec):
                if self._chaos:
                    from ray_tpu._private import fault_injection

                    fault_injection.check("execute")
                args, kwargs = self._resolve_args(spec)
                if spec.isolation == "process" or spec.runtime_env:
                    # A runtime env implies the process tier: envs are
                    # per-worker-process state (ref: worker_pool.h env-keyed
                    # workers); thread-tier tasks share the driver process.
                    if spec.generator:
                        self._run_generator_in_process(spec, args, kwargs)
                        result = None
                    else:
                        result = self._run_in_process(spec, args, kwargs)
                elif spec.generator:
                    self._run_generator(spec, args, kwargs)
                    result = None
                else:
                    result = spec.func(*args, **kwargs)
            if spec.task_id in self._cancelled:
                raise TaskCancelledError(str(spec.task_id))
            if not spec.generator:
                self._store_results(spec, result)
            self._emit_event(spec.task_id, spec.name, "FINISHED")
        except BaseException as e:  # noqa: BLE001
            self._handle_task_failure(spec, e)
        finally:
            _task_ctx.ctx = None
            self._running.pop(spec.task_id, None)
            reacquire_box["release"]()

    def _resolve_ref(self, v: Any) -> Any:
        """Arg materialization shared by task and actor paths: local store
        hit, else _get_one (object-plane pull + lineage reconstruction)."""
        if not isinstance(v, ObjectRef):
            return v
        if self.store.contains(v.id):
            return self.store.get(v.id)
        return self._get_one(v, None)

    def _resolve_args(self, spec: TaskSpec):
        args = spec.args
        kwargs = spec.kwargs
        ref_idx = [i for i, a in enumerate(args) if isinstance(a, ObjectRef)]
        if ref_idx:
            # One store pass for every ref arg (a 10k-arg call would
            # otherwise pay two lock round-trips per ref); stragglers take
            # the pull/reconstruction slow path individually.
            vals, missing = self.store.try_get_many(
                [args[i].id for i in ref_idx])
            resolved = dict(zip(ref_idx, vals))
            for j in missing:
                i = ref_idx[j]
                resolved[i] = self._resolve_ref(args[i])
            args = tuple(resolved.get(i, a) if isinstance(a, ObjectRef) else a
                         for i, a in enumerate(args))
        else:
            args = tuple(args)
        kwargs = {k: self._resolve_ref(v) for k, v in kwargs.items()}
        return args, kwargs

    def _lease_env_worker(self, spec: TaskSpec):
        """Stage the spec's runtime env (if any) and lease a matching
        process worker; returns (worker, fn_id, fn_bytes)."""
        fn = spec.func
        fn_id = getattr(fn, "__qualname__", "fn") + ":" + str(id(fn))
        fn_bytes = serialization.dumps(fn)
        env_key, env_payload = "", None
        if spec.runtime_env:
            from ray_tpu._private.runtime_env import RuntimeEnv, payload_key

            env = RuntimeEnv.normalize(spec.runtime_env)
            env_payload = env.stage()
            env_key = payload_key(env_payload)
        return self.process_pool.lease(env_key, env_payload), fn_id, fn_bytes

    def _run_in_process(self, spec: TaskSpec, args, kwargs):
        if self._chaos:
            from ray_tpu._private import fault_injection

            fault_injection.check("process_exec")
        worker, fn_id, fn_bytes = self._lease_env_worker(spec)
        self._track_leased_worker(worker, retriable=spec.max_retries > 0)
        try:
            result = worker.execute(fn_id, fn_bytes, args, kwargs)
        except (TaskError, WorkerCrashedError):
            self.process_pool.discard(worker)
            raise
        finally:
            self._untrack_leased_worker(worker)
        self.process_pool.release(worker)
        return result

    def _run_generator_in_process(self, spec: TaskSpec, args, kwargs) -> None:
        """Streaming-generator task on a leased process worker: items
        arrive over the multiplexed pipe and feed the ordinary generator
        machinery (VERDICT r2 item 8 — the process tier streams now)."""
        worker, fn_id, fn_bytes = self._lease_env_worker(spec)
        self._track_leased_worker(worker, retriable=False)
        ok = False
        try:
            self._run_generator(
                spec, args, kwargs,
                iterator=worker.execute_gen(fn_id, fn_bytes, args, kwargs))
            ok = True
        finally:
            self._untrack_leased_worker(worker)
            if ok:
                self.process_pool.release(worker)
            else:
                self.process_pool.discard(worker)

    def _run_generator(self, spec: TaskSpec, args, kwargs,
                       iterator=None) -> None:
        gen_handle = self._generators.get(spec.task_id)
        index = 0
        if iterator is None:
            iterator = spec.func(*args, **kwargs)
        try:
            for value in iterator:
                if spec.task_id in self._cancelled:
                    raise TaskCancelledError(str(spec.task_id))
                object_id = ObjectID.for_task_return(spec.task_id, index)
                self.store.put(object_id, value, owner=self.worker_id)
                self._on_object_ready(object_id)
                if gen_handle is not None:
                    gen_handle._push(ObjectRef(object_id, owner=self.worker_id))
                index += 1
            if gen_handle is not None:
                gen_handle._finish()
            self._inflight.discard(spec.task_id)
        except BaseException as e:  # noqa: BLE001
            if gen_handle is not None:
                gen_handle._finish(TaskError(e, task_repr=spec.name))
            raise
        finally:
            self._generators.pop(spec.task_id, None)

    def _store_results(self, spec: TaskSpec, result: Any) -> None:
        if spec.num_returns == 1:
            outputs = [result]
        else:
            if not isinstance(result, (tuple, list)) or len(result) != spec.num_returns:
                raise ValueError(
                    f"Task {spec.name} declared num_returns={spec.num_returns} but "
                    f"returned {type(result)}")
            outputs = list(result)
        for i, value in enumerate(outputs):
            object_id = ObjectID.for_task_return(spec.task_id, i)
            self.store.put(object_id, value, owner=self.worker_id)
            self._on_object_ready(object_id)
        self._inflight.discard(spec.task_id)

    def _handle_task_failure(self, spec: TaskSpec, error: BaseException) -> None:
        # ObjectLostError counts as a system error: a dependency's holder
        # died; the retry re-waits deps while lineage reconstructs them.
        is_app_error = not isinstance(
            error, (WorkerCrashedError, SystemError, MemoryError, ObjectLostError))
        if spec.generator:
            # Streaming tasks never retry mid-stream: the consumer's
            # generator already delivered items (and the error) — a rerun
            # would overwrite per-index returns behind refs the consumer
            # holds (the reference restarts streaming generators only
            # before any item is consumed; terminal failure is the honest
            # single-semantics here).
            self._fail_task(spec, error, retry=False)
            return
        retryable = (not is_app_error) or spec.retry_exceptions
        if isinstance(error, (TaskCancelledError,)):
            retryable = False
        if retryable and spec.attempt < spec.max_retries:
            spec.attempt += 1
            self._emit_event(spec.task_id, spec.name, "RETRYING", attempt=spec.attempt)
            self._enqueue_after_deps(spec)
            return
        self._fail_task(spec, error, retry=False)

    def _fail_task(self, spec: TaskSpec, error: BaseException, retry: bool) -> None:
        if not isinstance(error, (TaskError, TaskCancelledError, ActorDiedError)):
            error = TaskError(error, task_repr=spec.name)
        for i in range(max(spec.num_returns, 1)):
            object_id = ObjectID.for_task_return(spec.task_id, i)
            self.store.put_error(object_id, error)
            self._on_object_ready(object_id)
        gen_handle = self._generators.pop(spec.task_id, None)
        if gen_handle is not None:
            gen_handle._finish(error)
        self._inflight.discard(spec.task_id)
        self._emit_event(spec.task_id, spec.name, "FAILED", error=repr(error))

    # ---------------------------------------------------------------- cancel
    def cancel(self, ref: ObjectRef, force: bool = False) -> None:
        task_id = ref.id.task_id()
        self._cancelled.add(task_id)
        with self._remote_lock:
            remote = self._remote_inflight.get(task_id)
        if remote is not None:
            node = self._remote_node(remote[2])
            if node is not None and node.alive:
                try:
                    node.conn.send(("cancel", str(task_id), force))
                except (OSError, ConnectionError):
                    pass
            return
        ctx = self._running.get(task_id)
        if ctx is not None:
            ctx.cancelled.set()
        else:
            with self._deps_lock:
                entry = self._pending_deps.pop(task_id, None)
            if entry is not None:
                self._fail_task(entry[0], TaskCancelledError(str(task_id)), retry=False)

    # ---------------------------------------------------------------- lineage
    def _lineage_for(self, object_id: ObjectID) -> Optional[TaskSpec]:
        with self._lineage_lock:
            return self._lineage.get(object_id)

    def _on_zero_refs(self, object_id: ObjectID) -> None:
        if self._borrows is not None and self._borrows.is_borrowed(object_id):
            # Remote borrowers still hold handles: the owner keeps the
            # primary copy until the last RELEASE_BORROW arrives
            # (ref: reference_count.h — borrows keep the object pinned).
            return
        with self._locations_lock:
            loc = self._object_locations.pop(object_id, None)
        if loc:
            # The last head-side handle died: release the producing node's
            # export pin so it can free its copy (off-thread — GC path).
            self._release_export(object_id, loc)
        self.store.free(object_id)
        with self._lineage_lock:
            self._lineage.pop(object_id, None)

    # ----------------------------------------------------------------- actors
    def create_actor(self, spec: ActorSpec) -> None:
        state = _ActorState(spec)
        with self._actors_lock:
            if spec.name:
                key = (spec.namespace, spec.name)
                if key in self._named_actors:
                    existing = self._actors.get(self._named_actors[key])
                    if existing is not None and existing.state != _ActorState.DEAD:
                        raise ValueError(f"Actor name '{spec.name}' already taken")
                self._named_actors[key] = spec.actor_id
            self._actors[spec.actor_id] = state
        try:
            self._exec_pool.submit(self._start_actor, state, first=True)
        except RuntimeError:
            state.death_cause = ActorDiedError("runtime is shutting down")
            state.state = _ActorState.DEAD
            state.ready_event.set()

    def _start_actor(self, state: _ActorState, first: bool) -> None:
        spec = state.spec
        try:
            node_id, release = self.scheduler.acquire(spec.resources, spec.strategy)
        except BaseException as e:  # noqa: BLE001
            state.death_cause = e
            state.state = _ActorState.DEAD
            state.ready_event.set()
            return
        state.node_id, state.release = node_id, release
        if node_id in self._remote_nodes:
            self._start_remote_actor(state, node_id)
            return
        use_process = spec.isolation == "process" or bool(
            getattr(spec, "runtime_env", None))
        try:
            args, kwargs = self._resolve_values(spec.args, spec.kwargs)
            if use_process:
                if state.is_async:
                    raise ValueError(
                        "async actors cannot use isolation='process'")
                # Dedicated worker process hosting the instance (the
                # reference's default: one worker process per actor —
                # gcs_actor_scheduler.h leases a worker for creation).
                env_key, env_payload = "", None
                if spec.runtime_env:
                    from ray_tpu._private.runtime_env import (
                        RuntimeEnv, payload_key)

                    env = RuntimeEnv.normalize(spec.runtime_env)
                    env_payload = env.stage()
                    env_key = payload_key(env_payload)
                worker = self.process_pool.lease(env_key, env_payload)
                try:
                    worker.actor_new(serialization.dumps(spec.cls),
                                     spec.actor_id, args, kwargs)
                except BaseException:
                    self.process_pool.discard(worker)
                    raise
                state.proc_worker = worker
            else:
                # __init__ runs with an actor-scoped context so code inside
                # it (e.g. collective rank binding) can see the actor
                # identity.
                _task_ctx.ctx = TaskContext(TaskID.from_random(), spec.actor_id)
                try:
                    state.instance = spec.cls(*args, **kwargs)
                finally:
                    _task_ctx.ctx = None
        except BaseException as e:  # noqa: BLE001
            release()
            state.death_cause = TaskError(e, task_repr=f"{spec.cls.__name__}.__init__")
            state.state = _ActorState.DEAD
            state.ready_event.set()
            self._drain_mailbox(state)
            return
        state.state = _ActorState.ALIVE
        state.ready_event.set()
        if first or not state.threads:
            self._start_actor_executors(state)

    def _start_remote_actor(self, state: _ActorState, node_id: NodeID) -> None:
        """Ship actor creation to a worker node; readiness arrives as an
        actor_ready/actor_dead frame (ref: gcs_actor_scheduler.h — the GCS
        leases a remote worker for creation the same way)."""
        node = self._remote_node(node_id)
        spec = state.spec
        if node is None or not node.alive:
            # Vanished between lease and dispatch: retry the FSM.
            if state.release is not None:
                state.release()
                state.release = None
            self._kill_actor_state(state, ActorDiedError(
                f"node {node_id} vanished before actor creation"),
                no_restart=False)
            return
        state.remote_node = node_id
        try:
            node.conn.send(("actor_create", serialization.dumps_inband(spec)))
        except (OSError, ConnectionError):
            state.remote_node = None
            if state.release is not None:
                state.release()
                state.release = None
            self._kill_actor_state(state, ActorDiedError(
                f"node {node_id} unreachable for actor creation"),
                no_restart=False)
            return
        except BaseException as e:  # noqa: BLE001 — unpicklable class/args
            state.remote_node = None
            if state.release is not None:
                state.release()
                state.release = None
            state.death_cause = TaskError(e, task_repr=f"{spec.cls.__name__}.__init__")
            state.state = _ActorState.DEAD
            state.ready_event.set()
            self._drain_mailbox(state)
        # state stays PENDING (or RESTARTING) until the node answers; the
        # executor loops wait on ready_event before touching the mailbox.

    def _forward_actor_task(self, state: _ActorState, spec: TaskSpec) -> None:
        """Mailbox consumer path for remotely-hosted actors: ship the call;
        its completion frame lands the results."""
        node = self._remote_node(state.remote_node) \
            if state.remote_node is not None else None
        if node is None or not node.alive:
            self._fail_task(spec, ActorDiedError(
                f"actor node {state.remote_node} died"), retry=False)
            return
        self._emit_event(spec.task_id, spec.name, "SUBMITTED_TO_WORKER",
                         node_id=str(node.node_id))
        with self._remote_lock:
            self._remote_inflight[spec.task_id] = (spec, _noop, node.node_id)
        try:
            node.conn.send(("actor_task", str(spec.actor_id),
                            serialization.dumps_inband(spec)))
        except (OSError, ConnectionError):
            with self._remote_lock:
                self._remote_inflight.pop(spec.task_id, None)
            self._declare_node_lost(node)
            self._fail_task(spec, ActorDiedError(
                f"actor node {node.node_id} unreachable"), retry=False)
        except BaseException as e:  # noqa: BLE001
            with self._remote_lock:
                self._remote_inflight.pop(spec.task_id, None)
            self._fail_task(spec, e, retry=False)

    def _resolve_values(self, args, kwargs):
        return (tuple(self._resolve_ref(a) for a in args),
                {k: self._resolve_ref(v) for k, v in kwargs.items()})

    def _start_actor_executors(self, state: _ActorState) -> None:
        if state.remote_node is not None:
            # Remote host: ONE ordered forwarding thread (concurrency is
            # enforced by the hosting node's own executors).
            t = threading.Thread(target=self._actor_sync_loop, args=(state,), daemon=True)
            t.start()
            state.threads = [t]
            return
        if state.is_async:
            t = threading.Thread(target=self._actor_async_loop, args=(state,), daemon=True)
            t.start()
            state.threads = [t]
        else:
            n = max(1, state.spec.max_concurrency)
            state.threads = []
            for _ in range(n):
                t = threading.Thread(target=self._actor_sync_loop, args=(state,), daemon=True)
                t.start()
                state.threads.append(t)

    def _actor_sync_loop(self, state: _ActorState) -> None:
        while True:
            item = state.mailbox.get()
            if item is None:
                return
            spec: TaskSpec = item
            if state.state in (_ActorState.RESTARTING, _ActorState.PENDING):
                # Wait out a restart / a remote creation still in flight
                # instead of calling into a torn-down or not-yet-built
                # instance (ready_event is set on ALIVE or DEAD).
                state.ready_event.wait(timeout=300)
            if state.state != _ActorState.ALIVE:
                self._fail_task(spec, ActorDiedError(cause=state.death_cause), retry=False)
                continue
            if state.remote_node is not None:
                self._forward_actor_task(state, spec)
            else:
                self._execute_actor_task(state, spec)

    def _actor_async_loop(self, state: _ActorState) -> None:
        loop = asyncio.new_event_loop()
        state.loop = loop
        sem = asyncio.Semaphore(max(1, state.spec.max_concurrency))

        async def run_one(spec: TaskSpec):
            try:
                async with sem:
                    await self._execute_actor_task_async(state, spec)
            except asyncio.CancelledError:
                # Cancelled while still queued on the concurrency
                # semaphore — the executor never saw this call, so its
                # refs must be resolved here or the caller hangs.
                self._fail_task(spec, ActorDiedError(cause=state.death_cause),
                                retry=False)
                raise

        async def pump():
            while True:
                item = await loop.run_in_executor(None, state.mailbox.get)
                if item is None:
                    return
                if state.state in (_ActorState.RESTARTING, _ActorState.PENDING):
                    await loop.run_in_executor(
                        None, state.ready_event.wait, 300)
                if state.state != _ActorState.ALIVE:
                    self._fail_task(item, ActorDiedError(cause=state.death_cause), retry=False)
                    continue
                if state.remote_node is not None:
                    # Restart landed on a worker node: forward instead of
                    # executing against the (gone) local instance.
                    self._forward_actor_task(state, item)
                    continue
                # detached_ok: reaped by the all_tasks cancel sweep after pump()
                loop.create_task(run_one(item))

        try:
            loop.run_until_complete(pump())
            # The actor is dead (pump only returns on the death sentinel):
            # calls still executing on this loop would otherwise be
            # abandoned with their refs forever unresolved — every caller
            # blocked in get()/get_async() on them would hang.  Cancel
            # them and run the cancellations to completion so each call
            # fails over to ActorDiedError (see _execute_actor_task_async).
            pending = [t for t in asyncio.all_tasks(loop) if not t.done()]
            for task in pending:
                task.cancel()
            if pending:
                loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True))
        finally:
            loop.close()

    def _execute_actor_task(self, state: _ActorState, spec: TaskSpec) -> None:
        ctx = TaskContext(spec.task_id, spec.actor_id)
        self._running[spec.task_id] = ctx
        _task_ctx.ctx = ctx
        self._emit_event(spec.task_id, spec.name, "RUNNING")
        worker = state.proc_worker
        try:
            with tracing.task_execute_span(spec):
                args, kwargs = self._resolve_args(spec)
                if spec.method_name == EXEC_FN_METHOD and spec.func is not None:
                    # Shipped-function actor task (compiled-DAG resident
                    # loops): run spec.func against the instance — the
                    # instance has no such method to look up.
                    if worker is not None:
                        result = worker.actor_exec(
                            serialization.dumps(spec.func), args, kwargs)
                    else:
                        result = spec.func(state.instance, *args, **kwargs)
                elif worker is not None:
                    if spec.generator:
                        # Stream the method's items over the multiplexed
                        # worker pipe into the generator machinery.
                        self._run_generator(
                            spec, args, kwargs,
                            iterator=worker.actor_call_gen(
                                spec.method_name, args, kwargs))
                        result = None
                    else:
                        result = worker.actor_call(
                            spec.method_name, args, kwargs)
                elif spec.generator:
                    method = getattr(state.instance, spec.method_name)
                    saved, spec.func = spec.func, method
                    try:
                        self._run_generator(spec, args, kwargs)
                    finally:
                        spec.func = saved
                    result = None
                else:
                    method = getattr(state.instance, spec.method_name)
                    result = method(*args, **kwargs)
            if not spec.generator:
                self._store_results(spec, result)
            self._emit_event(spec.task_id, spec.name, "FINISHED")
        except _ActorExit as e:
            self._store_results(spec, None)
            self._kill_actor_state(state, ActorDiedError("exit_actor() was called"), no_restart=True)
        except WorkerCrashedError as e:
            # The actor's host process died: fail this call and run the
            # restart FSM (ref: gcs_actor_manager.h actor restart on worker
            # death; max_restarts honored by _kill_actor_state).  Only the
            # thread whose crash matches the CURRENT worker triggers the
            # restart — with max_concurrency > 1, later threads observing the
            # same crash must not discard the freshly restarted worker and
            # burn an extra restart.
            self._fail_task(spec, ActorDiedError(cause=e), retry=False)
            if state.proc_worker is worker:
                self._kill_actor_state(
                    state, ActorDiedError(f"actor worker process died: {e}"),
                    no_restart=False)
        except BaseException as e:  # noqa: BLE001
            self._fail_task(spec, TaskError(e, task_repr=spec.name), retry=False)
        finally:
            _task_ctx.ctx = None
            self._running.pop(spec.task_id, None)

    async def _execute_actor_task_async(self, state: _ActorState, spec: TaskSpec) -> None:
        self._emit_event(spec.task_id, spec.name, "RUNNING")
        try:
            with tracing.task_execute_span(spec):
                args, kwargs = self._resolve_args(spec)
                method = getattr(state.instance, spec.method_name)
                result = method(*args, **kwargs)
                if inspect.isawaitable(result):
                    result = await result
            self._store_results(spec, result)
            self._emit_event(spec.task_id, spec.name, "FINISHED")
        except _ActorExit:
            self._store_results(spec, None)
            self._kill_actor_state(state, ActorDiedError("exit_actor() was called"), no_restart=True)
        except asyncio.CancelledError:
            # The actor died with this call in flight (kill/preemption
            # cancels the loop's tasks on the way down): resolve the refs
            # with the death cause — callers classify ActorDiedError as
            # retryable, a bare TaskError they would surface to the user.
            self._fail_task(spec, ActorDiedError(cause=state.death_cause),
                            retry=False)
        except BaseException as e:  # noqa: BLE001
            self._fail_task(spec, TaskError(e, task_repr=spec.name), retry=False)

    def submit_actor_task(self, actor_id: ActorID, spec: TaskSpec) -> Any:
        if tracing.is_tracing_enabled():
            with tracing.span(f"submit::{spec.name}",
                              attributes={"task_id": spec.task_id,
                                          "actor_id": actor_id}):
                tracing.inject_task_spec(spec)
                return self._submit_actor_task_inner(actor_id, spec)
        return self._submit_actor_task_inner(actor_id, spec)

    def _submit_actor_task_inner(self, actor_id: ActorID, spec: TaskSpec) -> Any:
        state = self._actors.get(actor_id)
        if state is None:
            raise ActorDiedError(f"Unknown actor {actor_id}")
        if state.state == _ActorState.DEAD:
            ref = ObjectRef(ObjectID.for_task_return(spec.task_id, 0), owner=self.worker_id)
            self._fail_task(spec, ActorDiedError(cause=state.death_cause), retry=False)
            return ref
        refs = [
            ObjectRef(ObjectID.for_task_return(spec.task_id, i), owner=self.worker_id)
            for i in range(spec.num_returns)
        ]
        gen = None
        if spec.generator:
            gen = ObjectRefGenerator(spec.task_id)
            self._generators[spec.task_id] = gen
        self._emit_event(spec.task_id, spec.name, "PENDING_ACTOR_TASK")
        self._inflight.add(spec.task_id)
        state.mailbox.put(spec)
        if spec.generator:
            return gen
        return refs[0] if spec.num_returns == 1 else refs

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        state = self._actors.get(actor_id)
        if state is None:
            return
        self._kill_actor_state(state, ActorDiedError("ray_tpu.kill() was called"), no_restart)

    def _kill_actor_state(self, state: _ActorState, cause: ActorDiedError, no_restart: bool) -> None:
        died_terminally = False
        with state.lock:
            spec = state.spec
            can_restart = (not no_restart) and (
                spec.max_restarts == -1 or state.num_restarts < spec.max_restarts
            )
            if state.release is not None:
                state.release()
                state.release = None
            state.instance = None
            if state.proc_worker is not None:
                self.process_pool.discard(state.proc_worker)
                state.proc_worker = None
            if state.remote_node is not None:
                # Tell the hosting node to tear down its instance (it must
                # not run its own restart FSM after an explicit head kill);
                # on node death remote_node was already cleared.
                node = self._remote_node(state.remote_node)
                state.remote_node = None
                if node is not None and node.alive:
                    try:
                        node.conn.send(("kill_actor", str(spec.actor_id), True))
                    except (OSError, ConnectionError):
                        pass
            if can_restart:
                state.state = _ActorState.RESTARTING
                state.num_restarts += 1
                state.ready_event.clear()
                try:
                    self._exec_pool.submit(self._start_actor, state, first=False)
                except RuntimeError:
                    state.death_cause = ActorDiedError("runtime is shutting down")
                    state.state = _ActorState.DEAD
                    state.ready_event.set()
            else:
                state.state = _ActorState.DEAD
                state.death_cause = cause
                with self._actors_lock:
                    if spec.name and self._named_actors.get((spec.namespace, spec.name)) == spec.actor_id:
                        del self._named_actors[(spec.namespace, spec.name)]
                for _ in state.threads:
                    state.mailbox.put(None)
                died_terminally = True
        if died_terminally and not self._dispatcher_stop.is_set():
            # Actor-death sentinel: snapshot the black box while the spans
            # that explain the death are still in the ring (best-effort,
            # flood-controlled; skipped during runtime shutdown where mass
            # actor teardown is expected, not a failure).
            from ray_tpu.util import flight_recorder

            flight_recorder.trigger_dump("actor_death", {
                "actor_id": str(spec.actor_id),
                "name": spec.name or "",
                "class": getattr(spec, "class_name", "") or "",
                "cause": str(cause),
                # Node attribution: the cluster autoscaler's health gate
                # keys postmortems on the node that produced them.
                "node": str(state.node_id) if state.node_id else "",
            })

    def _drain_mailbox(self, state: _ActorState) -> None:
        while True:
            try:
                spec = state.mailbox.get_nowait()
            except queue.Empty:
                return
            if spec is not None:
                self._fail_task(spec, ActorDiedError(cause=state.death_cause), retry=False)

    def get_actor_state(self, actor_id: ActorID) -> Optional[_ActorState]:
        return self._actors.get(actor_id)

    def get_named_actor(self, name: str, namespace: Optional[str] = None) -> ActorID:
        key = (namespace or self.namespace, name)
        with self._actors_lock:
            actor_id = self._named_actors.get(key)
        if actor_id is None:
            raise ValueError(f"Failed to look up actor '{name}' in namespace '{key[0]}'")
        return actor_id

    def list_actor_states(self) -> List[dict]:
        with self._actors_lock:
            return [
                {
                    "actor_id": str(aid),
                    "class_name": st.spec.cls.__name__,
                    "state": st.state,
                    "name": st.spec.name or "",
                    "num_restarts": st.num_restarts,
                    "node_id": str(st.node_id) if st.node_id else "",
                }
                for aid, st in self._actors.items()
            ]

    # --------------------------------------------------------------- shutdown
    def shutdown(self) -> None:
        self._dispatcher_stop.set()
        self._ready.put(None)
        if self.node_server is not None:
            for node in self._remote_nodes_snapshot():
                node.alive = False  # suppress node-lost recovery on EOF
                try:
                    node.conn.send(("shutdown",))
                except (OSError, ConnectionError):
                    pass
            self.node_server.stop()
            self.node_server = None
        with self._actors_lock:
            actors = list(self._actors.values())
        for state in actors:
            state.state = _ActorState.DEAD
            if state.proc_worker is not None:
                state.proc_worker.kill()
                state.proc_worker = None
            for _ in state.threads or [None]:
                state.mailbox.put(None)
        if self._memory_monitor is not None:
            self._memory_monitor.stop()
            self._memory_monitor = None
        self.process_pool.shutdown()
        from ray_tpu._private.process_pool import stop_log_monitor

        stop_log_monitor()
        self._exec_pool.shutdown(wait=False, cancel_futures=True)
        from ray_tpu._private import borrowing

        borrowing.release_all()  # return outstanding borrows to their owners
        if self.object_server is not None:
            self.object_server.stop()
            self.object_server = None
        self.store.shutdown()
        self.refcounter.clear()


class _LeasedWorker:
    """Kill-candidate record for the memory monitor."""

    __slots__ = ("worker", "retriable", "started_at")

    def __init__(self, worker, retriable: bool):
        self.worker = worker
        self.retriable = retriable
        self.started_at = time.monotonic()


class _ActorExit(BaseException):
    """Raised by exit_actor() to terminate the current actor."""


def get_runtime() -> Runtime:
    if _runtime is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return _runtime


def runtime_or_none() -> Optional[Runtime]:
    return _runtime


def install_runtime(rt) -> None:
    """Install a runtime implementation (process workers install their
    ClientRuntime proxy here so the full API works in the child)."""
    global _runtime
    with _runtime_lock:
        _runtime = rt


def init_runtime(**kwargs) -> Runtime:
    global _runtime
    with _runtime_lock:
        if _runtime is None:
            _runtime = Runtime(**kwargs)
        return _runtime


def shutdown_runtime() -> None:
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            _runtime.shutdown()
            _runtime = None
