"""Task/actor specifications (ref: src/ray/common/task/task_spec.h, TaskSpecification).

A TaskSpec carries everything needed to execute (and re-execute, for lineage
reconstruction) a task: the function, resolved-or-pending args, resource
request, scheduling strategy, retry budget.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.ids import ActorID, TaskID
from ray_tpu._private.scheduling import SchedulingStrategy

#: Actor-task escape hatch: a spec with this method_name runs ``spec.func``
#: with the actor INSTANCE prepended to its args instead of looking the
#: method up on the instance — how a compiled DAG installs its resident
#: executor loop on an actor hosted in another runtime (ref: the reference
#: submits do_exec_tasks to each actor the same way,
#: compiled_dag_node.py:711).
EXEC_FN_METHOD = "__ray_tpu_exec_fn__"


class TaskSpec:
    # Kept lean on purpose: a spec is built on every .remote() call, so
    # anything not needed to execute (wall-clock stamps, derived display
    # strings) is materialized lazily by whoever needs it, not here.
    __slots__ = (
        "task_id", "name", "func", "args", "kwargs", "num_returns",
        "resources", "strategy", "max_retries", "retry_exceptions",
        "actor_id", "method_name", "isolation", "attempt",
        "generator", "parent_task_id", "runtime_env", "trace_ctx",
    )

    def __init__(
        self,
        task_id: TaskID,
        name: str,
        func: Any,
        args: Tuple[Any, ...],
        kwargs: Dict[str, Any],
        num_returns: int,
        resources: Dict[str, float],
        strategy: Optional[SchedulingStrategy],
        max_retries: int,
        retry_exceptions: bool = False,
        actor_id: Optional[ActorID] = None,
        method_name: str = "",
        isolation: str = "thread",
        generator: bool = False,
        parent_task_id: Optional[TaskID] = None,
        runtime_env: Optional[dict] = None,
    ):
        self.task_id = task_id
        self.name = name
        self.func = func
        self.args = args
        self.kwargs = kwargs
        self.num_returns = num_returns
        self.resources = resources
        self.strategy = strategy
        self.max_retries = max_retries
        self.retry_exceptions = retry_exceptions
        self.actor_id = actor_id
        self.method_name = method_name
        self.isolation = isolation
        self.attempt = 0
        self.generator = generator
        self.parent_task_id = parent_task_id
        self.runtime_env = runtime_env
        #: Submitter's tracing context (util/tracing.py), propagated to the
        #: execute-side span like the reference's TaskSpec-carried OTel ctx.
        self.trace_ctx: Optional[dict] = None

    @property
    def is_actor_task(self) -> bool:
        return self.actor_id is not None and self.method_name != "__init__"

    def __repr__(self) -> str:
        return f"TaskSpec({self.name}, id={self.task_id})"


class ActorSpec:
    __slots__ = (
        "actor_id", "name", "namespace", "cls", "args", "kwargs", "resources",
        "strategy", "max_restarts", "max_task_retries", "max_concurrency",
        "isolation", "lifetime", "concurrency_groups", "runtime_env",
    )

    def __init__(
        self,
        actor_id: ActorID,
        name: Optional[str],
        namespace: str,
        cls: type,
        args: Tuple[Any, ...],
        kwargs: Dict[str, Any],
        resources: Dict[str, float],
        strategy: Optional[SchedulingStrategy],
        max_restarts: int,
        max_task_retries: int,
        max_concurrency: int,
        isolation: str,
        lifetime: Optional[str],
        concurrency_groups: Optional[Dict[str, int]] = None,
        runtime_env: Optional[dict] = None,
    ):
        self.actor_id = actor_id
        self.name = name
        self.namespace = namespace
        self.cls = cls
        self.args = args
        self.kwargs = kwargs
        self.resources = resources
        self.strategy = strategy
        self.max_restarts = max_restarts
        self.max_task_retries = max_task_retries
        self.max_concurrency = max_concurrency
        self.isolation = isolation
        self.lifetime = lifetime
        self.concurrency_groups = concurrency_groups or {}
        self.runtime_env = runtime_env
