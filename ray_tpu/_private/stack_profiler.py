"""On-demand stack dumps of the driver and every worker process.

TPU-native analogue of the reference's on-demand profiling (ref:
python/ray/dashboard/modules/reporter/profile_manager.py:78 — py-spy stack
dumps/flamegraphs of any worker from the dashboard; `ray stack` CLI).
py-spy is not in the image, so:

- driver/thread-tier workers: sampled in-process via
  ``sys._current_frames`` (every thread, no interruption);
- process-tier workers: each worker registers a SIGUSR1 faulthandler at
  startup writing to a per-pid file under the session dir; the driver
  signals the pid and collects the file (signal-based dumping works even
  mid-task, the property py-spy provides externally).
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional


def dump_dir(export: bool = False) -> str:
    """Driver side resolves from the live config (export=True publishes to
    env for spawned children); workers prefer the exported env value."""
    from ray_tpu._private.config import session_subdir

    return session_subdir("stack_dumps", "RAY_TPU_STACK_DUMP_DIR",
                          export=export)


# ---------------------------------------------------------------- worker side
def install_worker_dump_handler() -> None:
    """Called in every process worker's main: SIGUSR1 → dump all thread
    stacks to <session>/stack_dumps/<pid>.txt (faulthandler is async-signal
    -safe, unlike a Python-level handler formatting frames)."""
    import faulthandler

    try:
        path = os.path.join(dump_dir(), f"{os.getpid()}.txt")
        f = open(path, "w")
        faulthandler.register(signal.SIGUSR1, file=f, all_threads=True)
        # Keep the handle alive for the process lifetime.
        globals().setdefault("_dump_files", []).append(f)
    except Exception:
        pass  # profiling is best-effort; workers must start regardless


# ---------------------------------------------------------------- driver side
def current_process_stacks() -> Dict[str, List[str]]:
    """Thread-name → formatted stack for THIS process (driver + thread-tier
    workers; ref: `ray stack` output shape)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for ident, frame in sys._current_frames().items():
        name = names.get(ident, f"thread-{ident}")
        out[name] = traceback.format_stack(frame)
    return out


#: Sentinel prefix for pids whose dump never arrived before the deadline —
#: callers (postmortem bundles, /api/stacks) can tell a missing worker from
#: a collected dump without parsing prose.
MISSING_DUMP_PREFIX = "<no dump before deadline"


def dump_worker_stacks(pids: List[int], timeout_s: float = 2.0) -> Dict[int, str]:
    """Signal each worker pid; collect its faulthandler dump file.

    A worker is only signaled once its dump file exists — the file is
    created when the handler registers, so its absence means the worker is
    still booting and SIGUSR1 would hit the DEFAULT disposition and kill it.
    (A stale same-pid file from an older session could defeat this gate;
    sessions share /tmp dirs rarely enough that we accept the window rather
    than plumb worker start-times through.)

    The driver-side wait is bounded by ``timeout_s`` TOTAL (not per pid):
    a dead or wedged worker — SIGUSR1 masked, stuck in native code, killed
    between the signal and the write — is reported in the result under
    :data:`MISSING_DUMP_PREFIX` instead of blocking the collector, so a
    postmortem dump of a dying cluster always returns.
    """
    d = dump_dir()
    results: Dict[int, str] = {}
    marks: Dict[int, int] = {}
    for pid in pids:
        path = os.path.join(d, f"{pid}.txt")
        if not os.path.exists(path):
            results[pid] = "<worker still starting; dump handler not ready>"
            continue
        try:
            marks[pid] = os.path.getsize(path)
            os.kill(pid, signal.SIGUSR1)
        except (ProcessLookupError, PermissionError, OSError) as e:
            results[pid] = f"<unreachable: {e}>"
    deadline = time.monotonic() + timeout_s
    pending = [p for p in pids if p not in results]
    last_size: Dict[int, int] = {}
    while pending and time.monotonic() < deadline:
        time.sleep(min(0.05, timeout_s))
        for pid in list(pending):
            path = os.path.join(d, f"{pid}.txt")
            try:
                size = os.path.getsize(path)
                # Collect only once the dump is QUIESCENT (grew past the
                # mark, then unchanged across a poll) — faulthandler writes
                # incrementally and a partial read would drop thread stacks.
                if size > marks.get(pid, 0) and last_size.get(pid) == size:
                    with open(path) as f:
                        f.seek(marks.get(pid, 0))
                        results[pid] = f.read()
                    pending.remove(pid)
                else:
                    last_size[pid] = size
            except OSError:
                pass  # file vanished/unreadable this poll; deadline bounds us
    for pid in pending:
        results[pid] = (f"{MISSING_DUMP_PREFIX} ({timeout_s:.1f}s): worker "
                        "dead, signal masked, or busy in native code>")
    return results


def collect_all_stacks() -> Dict[str, object]:
    """Full cluster view: driver threads + every live process worker."""
    out: Dict[str, object] = {"driver": current_process_stacks()}
    pids = worker_pids()
    if pids:
        out["process_workers"] = dump_worker_stacks(pids)
    return out


def worker_pids() -> List[int]:
    """All live process-tier worker pids known to the runtime."""
    from ray_tpu._private.runtime import runtime_or_none

    rt = runtime_or_none()
    if rt is None or not hasattr(rt, "process_pool"):
        return []
    pids = set()
    pool = rt.process_pool
    with pool._lock:
        for workers in pool._idle.values():
            for w in workers:
                if w.alive():
                    pids.add(w.proc.pid)
    with rt._leased_lock:
        for lw in rt._leased_workers.values():
            if lw.worker.alive():
                pids.add(lw.worker.proc.pid)
    with rt._actors_lock:
        for state in rt._actors.values():
            w = state.proc_worker
            if w is not None and w.alive():
                pids.add(w.proc.pid)
    return sorted(pids)


def format_stacks(stacks: Dict[str, object]) -> str:
    lines: List[str] = []
    for name, stack in sorted(stacks.get("driver", {}).items()):
        lines.append(f"=== driver thread: {name} ===")
        lines.extend(s.rstrip("\n") for s in stack)
    for pid, text in sorted(stacks.get("process_workers", {}).items()):
        lines.append(f"=== process worker pid={pid} ===")
        lines.append(str(text).rstrip("\n"))
    return "\n".join(lines)
