"""Runtime environments: per-task/actor execution environments.

Counterpart of the reference's runtime-env subsystem (ref:
_private/runtime_env/ — plugin.py, working_dir.py, py_modules.py, pip.py;
raylet AgentManager asks a Python agent to materialize envs, worker_pool.h
caches workers keyed by the env).  Single-host model: envs are materialized
into a session-local cache directory (the URI-cache role, uri_cache.py) and
applied inside *process-tier* workers — a task carrying a runtime_env is
automatically routed to the process pool, whose leases are keyed by the env
hash exactly like the reference's runtime-env-keyed worker caching.

Supported fields:
  env_vars:    {str: str} exported in the worker
  working_dir: local directory staged into the cache and chdir'd into
  py_modules:  list of local module/package paths prepended to sys.path
  pip / uv:    list of requirements, materialized OFFLINE into a real
               content-keyed virtualenv from a local wheel cache
               (``pip install --no-index --find-links``; ref: pip.py:122
               _install_pip_packages + uri_cache.py).  The wheel source is
               runtime_env["config"]["pip_find_links"] or
               $RAY_TPU_WHEEL_CACHE; TRUE network installs (no local
               wheel source) remain gated with a clear error.  Workers
               activate the venv by site-dir injection (packages shadow
               the host's), not interpreter re-exec — the process pool
               spawns via multiprocessing, whose executable is global.
  conda:       rejected (no conda toolchain in this image)
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import sys
import threading
from typing import Any, Dict, List, Optional

_CACHE_LOCK = threading.Lock()


def _cache_root() -> str:
    from ray_tpu._private.config import GLOBAL_CONFIG

    root = os.path.join(GLOBAL_CONFIG.session_dir, "runtime_envs")
    os.makedirs(root, exist_ok=True)
    return root


class RuntimeEnv(dict):
    """Validated runtime-env dict (ref: ray.runtime_env.RuntimeEnv)."""

    _ALLOWED = {"env_vars", "working_dir", "py_modules", "pip", "conda",
                "uv", "config"}
    _GATED = ("conda",)

    def __init__(self, **kwargs):
        super().__init__()
        for k, v in kwargs.items():
            if v is not None:
                self[k] = v
        self.validate()

    @classmethod
    def normalize(cls, obj) -> Optional["RuntimeEnv"]:
        if obj is None:
            return None
        if isinstance(obj, RuntimeEnv):
            obj.validate()
            return obj
        if isinstance(obj, dict):
            return cls(**obj)
        raise TypeError(f"runtime_env must be a dict, got {type(obj)}")

    def validate(self) -> None:
        unknown = set(self) - self._ALLOWED
        if unknown:
            raise ValueError(f"unknown runtime_env fields: {sorted(unknown)}")
        for gated in self._GATED:
            if self.get(gated):
                raise RuntimeError(
                    f"runtime_env[{gated!r}] needs package installation, "
                    "which is unavailable in this offline image; pre-bake "
                    "dependencies or use py_modules/working_dir")
        if self.get("pip") and self.get("uv"):
            raise ValueError("runtime_env: specify pip OR uv, not both")
        for field in ("pip", "uv"):
            spec = self.get(field)
            if spec is None:
                continue
            pkgs = spec.get("packages") if isinstance(spec, dict) else spec
            if not (isinstance(pkgs, list)
                    and all(isinstance(p, str) for p in pkgs)):
                raise ValueError(
                    f"runtime_env[{field!r}] must be a list of requirement "
                    "strings (or {'packages': [...]})")
        ev = self.get("env_vars", {})
        if not isinstance(ev, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in ev.items()):
            raise ValueError("env_vars must be Dict[str, str]")
        wd = self.get("working_dir")
        if wd is not None and not os.path.isdir(wd):
            raise ValueError(f"working_dir {wd!r} is not a directory")
        for p in self.get("py_modules", ()):
            if not os.path.exists(p):
                raise ValueError(f"py_modules path {p!r} does not exist")

    def env_key(self) -> str:
        """Stable hash of the declared env (worker-pool lease key; prefer
        payload_key(stage()) which also captures working_dir content)."""
        return hashlib.sha1(
            json.dumps(self, sort_keys=True).encode()).hexdigest()[:16]

    # ------------------------------------------------------------- staging
    def stage(self) -> dict:
        """Materialize (driver side): copy working_dir into the session cache
        once per content key; return the payload shipped to workers.
        Memoized per instance with a 5 s TTL: stage() sits on the
        task-submission hot path (the pip/uv content key re-walks the wheel
        cache), while the TTL keeps the content-fingerprint freshness that
        lets an edited working_dir produce a new lease key mid-session."""
        import time as _time

        cached = getattr(self, "_staged", None)
        if cached is not None and _time.monotonic() < cached[0]:
            return cached[1]
        payload: Dict[str, Any] = {"env_vars": dict(self.get("env_vars", {}))}
        wd = self.get("working_dir")
        if wd:
            payload["working_dir"] = _stage_dir(os.path.abspath(wd))
        mods = [os.path.abspath(p) for p in self.get("py_modules", ())]
        if mods:
            payload["py_modules"] = mods
            # Content-fingerprint each module like working_dir, so editing a
            # module produces a new lease key instead of silently reusing a
            # cached worker that already imported the stale code.
            payload["py_modules_fingerprint"] = [
                _dir_fingerprint(p) if os.path.isdir(p) else _file_fingerprint(p)
                for p in mods
            ]
        for installer in ("pip", "uv"):
            if self.get(installer):
                payload.update(
                    _materialize_venv(self[installer], installer,
                                      self.get("config") or {}))
                break
        self._staged = (_time.monotonic() + 5.0, payload)
        return payload


def _find_links_dir(config: dict) -> Optional[str]:
    d = config.get("pip_find_links") or os.environ.get("RAY_TPU_WHEEL_CACHE")
    return os.path.abspath(d) if d else None


def _materialize_venv(spec, installer: str, config: dict) -> dict:
    """Build (once) a real virtualenv holding `spec`'s requirements from a
    LOCAL wheel cache, content-keyed by (installer, requirements, wheel-dir
    fingerprint) — the uri_cache.py role.  Returns payload fields; workers
    activate via site-dir injection (apply_in_worker)."""
    import subprocess
    import venv as venv_mod

    pkgs = sorted(spec.get("packages") if isinstance(spec, dict) else spec)
    find_links = _find_links_dir(config)
    if find_links is None or not os.path.isdir(find_links):
        raise RuntimeError(
            f"runtime_env[{installer!r}] would need a NETWORK package "
            "install, which is unavailable in this offline image.  Provide "
            "a local wheel cache via runtime_env['config']"
            "['pip_find_links'] or $RAY_TPU_WHEEL_CACHE "
            f"(got {find_links!r}), or pre-bake dependencies.")
    key = hashlib.sha1(
        f"{installer}:{json.dumps(pkgs)}:{_dir_fingerprint(find_links)}"
        .encode()).hexdigest()[:16]
    venv_dir = os.path.join(_cache_root(), "venvs", key)
    py = os.path.join(venv_dir, "bin", "python")
    with _CACHE_LOCK:
        if not os.path.isdir(venv_dir):
            tmp = venv_dir + ".tmp"
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(os.path.dirname(venv_dir), exist_ok=True)
            uv_bin = shutil.which("uv") if installer == "uv" else None
            try:
                if uv_bin:
                    # blocking_ok: build-once cache; the lock exists to serialize concurrent builders
                    subprocess.run([uv_bin, "venv", "--python",
                                    sys.executable,
                                    "--system-site-packages", tmp],
                                   check=True, capture_output=True,
                                   text=True, timeout=120)
                    cmd = [uv_bin, "pip", "install", "--offline",
                           "--no-index", "--find-links", find_links,
                           "--python", os.path.join(tmp, "bin", "python"),
                           *pkgs]
                else:
                    # venv without pip (ensurepip is slow); drive the HOST
                    # pip against the venv interpreter (pip >= 22.3).
                    venv_mod.create(tmp, system_site_packages=True,
                                    with_pip=False, symlinks=True)
                    cmd = [sys.executable, "-m", "pip", "--python",
                           os.path.join(tmp, "bin", "python"), "install",
                           "--no-index", "--find-links", find_links, *pkgs]
                subprocess.run(cmd, check=True, capture_output=True,  # blocking_ok: build-once cache, see above
                               text=True, timeout=300)
            except (subprocess.CalledProcessError,
                    subprocess.TimeoutExpired, OSError) as e:
                shutil.rmtree(tmp, ignore_errors=True)
                detail = (getattr(e, "stderr", "") or str(e))[-800:]
                raise RuntimeError(
                    f"runtime_env[{installer!r}] install failed for {pkgs} "
                    f"from {find_links}: {detail}") from e
            os.replace(tmp, venv_dir)
    site_dirs = [
        os.path.join(venv_dir, "lib", d, "site-packages")
        for d in os.listdir(os.path.join(venv_dir, "lib"))
        if d.startswith("python")
    ] if os.path.isdir(os.path.join(venv_dir, "lib")) else []
    return {"venv_dir": venv_dir, "venv_python": py,
            "venv_site": site_dirs[0] if site_dirs else None,
            "venv_key": key}


def _file_fingerprint(path: str) -> str:
    try:
        stat = os.stat(path)
        tail = f"{stat.st_mtime_ns}:{stat.st_size}"
    except OSError:
        tail = "missing"
    return hashlib.sha1(f"{path}:{tail}".encode()).hexdigest()[:16]


def _dir_fingerprint(src: str) -> str:
    """Content fingerprint: every file's relpath+mtime+size.  (Directory
    mtime alone misses in-place edits to contained files.)"""
    h = hashlib.sha1(src.encode())
    for root, dirs, files in os.walk(src):
        dirs[:] = sorted(d for d in dirs if d not in (".git", "__pycache__"))
        for name in sorted(files):
            p = os.path.join(root, name)
            try:
                stat = os.stat(p)
            except OSError:
                continue
            h.update(f"{os.path.relpath(p, src)}:{stat.st_mtime_ns}:"
                     f"{stat.st_size};".encode())
    return h.hexdigest()[:16]


def _stage_dir(src: str) -> str:
    """Copy `src` into the cache keyed by content (URI cache equivalent —
    repeated leases reuse the staged copy; edits re-stage)."""
    stamp = _dir_fingerprint(src)
    dst = os.path.join(_cache_root(), stamp)
    with _CACHE_LOCK:
        if not os.path.isdir(dst):
            tmp = dst + ".tmp"
            shutil.copytree(src, tmp,
                            ignore=shutil.ignore_patterns(".git", "__pycache__"))
            os.replace(tmp, dst)
    return dst


def payload_key(payload: dict) -> str:
    """Lease key from the *staged* payload: the working_dir path in it is
    content-stamped, so editing files yields a fresh key (and worker)."""
    return hashlib.sha1(
        json.dumps(payload, sort_keys=True).encode()).hexdigest()[:16]


def apply_in_worker(payload: dict) -> None:
    """Apply a staged env inside a (process-tier) worker."""
    for k, v in payload.get("env_vars", {}).items():
        os.environ[k] = v
    vs = payload.get("venv_site")
    if vs:
        import site

        prev = set(sys.path)
        site.addsitedir(vs)  # honors .pth files, unlike a bare insert
        fresh = [p for p in sys.path if p not in prev]
        # Venv packages must SHADOW same-named host packages.
        sys.path[:] = fresh + [p for p in sys.path if p not in fresh]
        os.environ["VIRTUAL_ENV"] = payload.get("venv_dir", "")
    for p in reversed(payload.get("py_modules", [])):
        if p not in sys.path:
            sys.path.insert(0, p)
    wd = payload.get("working_dir")
    if wd:
        if wd not in sys.path:
            sys.path.insert(0, wd)
        os.chdir(wd)
