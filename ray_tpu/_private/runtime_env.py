"""Runtime environments: per-task/actor execution environments.

Counterpart of the reference's runtime-env subsystem (ref:
_private/runtime_env/ — plugin.py, working_dir.py, py_modules.py, pip.py;
raylet AgentManager asks a Python agent to materialize envs, worker_pool.h
caches workers keyed by the env).  Single-host model: envs are materialized
into a session-local cache directory (the URI-cache role, uri_cache.py) and
applied inside *process-tier* workers — a task carrying a runtime_env is
automatically routed to the process pool, whose leases are keyed by the env
hash exactly like the reference's runtime-env-keyed worker caching.

Supported fields (this image is offline — installer plugins are gated):
  env_vars:    {str: str} exported in the worker
  working_dir: local directory staged into the cache and chdir'd into
  py_modules:  list of local module/package paths prepended to sys.path
  pip/conda/uv: rejected with a clear error (no network in this image)
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import sys
import threading
from typing import Any, Dict, List, Optional

_CACHE_LOCK = threading.Lock()


def _cache_root() -> str:
    from ray_tpu._private.config import GLOBAL_CONFIG

    root = os.path.join(GLOBAL_CONFIG.session_dir, "runtime_envs")
    os.makedirs(root, exist_ok=True)
    return root


class RuntimeEnv(dict):
    """Validated runtime-env dict (ref: ray.runtime_env.RuntimeEnv)."""

    _ALLOWED = {"env_vars", "working_dir", "py_modules", "pip", "conda",
                "uv", "config"}
    _GATED = ("pip", "conda", "uv")

    def __init__(self, **kwargs):
        super().__init__()
        for k, v in kwargs.items():
            if v is not None:
                self[k] = v
        self.validate()

    @classmethod
    def normalize(cls, obj) -> Optional["RuntimeEnv"]:
        if obj is None:
            return None
        if isinstance(obj, RuntimeEnv):
            obj.validate()
            return obj
        if isinstance(obj, dict):
            return cls(**obj)
        raise TypeError(f"runtime_env must be a dict, got {type(obj)}")

    def validate(self) -> None:
        unknown = set(self) - self._ALLOWED
        if unknown:
            raise ValueError(f"unknown runtime_env fields: {sorted(unknown)}")
        for gated in self._GATED:
            if self.get(gated):
                raise RuntimeError(
                    f"runtime_env[{gated!r}] needs package installation, "
                    "which is unavailable in this offline image; pre-bake "
                    "dependencies or use py_modules/working_dir")
        ev = self.get("env_vars", {})
        if not isinstance(ev, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in ev.items()):
            raise ValueError("env_vars must be Dict[str, str]")
        wd = self.get("working_dir")
        if wd is not None and not os.path.isdir(wd):
            raise ValueError(f"working_dir {wd!r} is not a directory")
        for p in self.get("py_modules", ()):
            if not os.path.exists(p):
                raise ValueError(f"py_modules path {p!r} does not exist")

    def env_key(self) -> str:
        """Stable hash of the declared env (worker-pool lease key; prefer
        payload_key(stage()) which also captures working_dir content)."""
        return hashlib.sha1(
            json.dumps(self, sort_keys=True).encode()).hexdigest()[:16]

    # ------------------------------------------------------------- staging
    def stage(self) -> dict:
        """Materialize (driver side): copy working_dir into the session cache
        once per content key; return the payload shipped to workers."""
        payload: Dict[str, Any] = {"env_vars": dict(self.get("env_vars", {}))}
        wd = self.get("working_dir")
        if wd:
            payload["working_dir"] = _stage_dir(os.path.abspath(wd))
        mods = [os.path.abspath(p) for p in self.get("py_modules", ())]
        if mods:
            payload["py_modules"] = mods
            # Content-fingerprint each module like working_dir, so editing a
            # module produces a new lease key instead of silently reusing a
            # cached worker that already imported the stale code.
            payload["py_modules_fingerprint"] = [
                _dir_fingerprint(p) if os.path.isdir(p) else _file_fingerprint(p)
                for p in mods
            ]
        return payload


def _file_fingerprint(path: str) -> str:
    try:
        stat = os.stat(path)
        tail = f"{stat.st_mtime_ns}:{stat.st_size}"
    except OSError:
        tail = "missing"
    return hashlib.sha1(f"{path}:{tail}".encode()).hexdigest()[:16]


def _dir_fingerprint(src: str) -> str:
    """Content fingerprint: every file's relpath+mtime+size.  (Directory
    mtime alone misses in-place edits to contained files.)"""
    h = hashlib.sha1(src.encode())
    for root, dirs, files in os.walk(src):
        dirs[:] = sorted(d for d in dirs if d not in (".git", "__pycache__"))
        for name in sorted(files):
            p = os.path.join(root, name)
            try:
                stat = os.stat(p)
            except OSError:
                continue
            h.update(f"{os.path.relpath(p, src)}:{stat.st_mtime_ns}:"
                     f"{stat.st_size};".encode())
    return h.hexdigest()[:16]


def _stage_dir(src: str) -> str:
    """Copy `src` into the cache keyed by content (URI cache equivalent —
    repeated leases reuse the staged copy; edits re-stage)."""
    stamp = _dir_fingerprint(src)
    dst = os.path.join(_cache_root(), stamp)
    with _CACHE_LOCK:
        if not os.path.isdir(dst):
            tmp = dst + ".tmp"
            shutil.copytree(src, tmp,
                            ignore=shutil.ignore_patterns(".git", "__pycache__"))
            os.replace(tmp, dst)
    return dst


def payload_key(payload: dict) -> str:
    """Lease key from the *staged* payload: the working_dir path in it is
    content-stamped, so editing files yields a fresh key (and worker)."""
    return hashlib.sha1(
        json.dumps(payload, sort_keys=True).encode()).hexdigest()[:16]


def apply_in_worker(payload: dict) -> None:
    """Apply a staged env inside a (process-tier) worker."""
    for k, v in payload.get("env_vars", {}).items():
        os.environ[k] = v
    for p in reversed(payload.get("py_modules", [])):
        if p not in sys.path:
            sys.path.insert(0, p)
    wd = payload.get("working_dir")
    if wd:
        if wd not in sys.path:
            sys.path.insert(0, wd)
        os.chdir(wd)
