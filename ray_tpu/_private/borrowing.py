"""Cross-node borrowing protocol for the ownership-based refcount.

TPU-native analogue of the reference's distributed ReferenceCounter
borrowing (ref: src/ray/core_worker/reference_count.h:66 — when a ref is
serialized to another worker, the owner records the borrower and keeps the
object alive until every borrower reports its local count hit zero).

Shape here: the borrower side registers a borrow with the object's owner
the first time a remote-owned ref materializes in this process (ObjectRef
deserialization), and releases it when the process-local refcount for that
id drops to zero.  Messages ride the object-transfer TCP protocol
(OP_ADD_BORROW / OP_RELEASE_BORROW) synchronously (see BorrowClient for the
ordering argument).  The owner's store frees an object only when BOTH its
local refcount is zero and no borrows remain.

Serialization-time coverage: a ref serialized out-of-band (KV, pubsub,
actor state) may outlive the sender's last local handle before any receiver
deserializes it.  To close that window, pickling a remote-owned ref takes a
**wire pin** on the owner — an ADD_BORROW under a one-shot ``wire:`` id
carried inside the serialized form — which the receiver releases right
after registering its own borrow (the reference gets the same guarantee by
piggybacking borrower reports on task replies, reference_count.h:66).
Serialized bytes that are dropped without ever being deserialized leak
their pin until the owner shuts down — the same caveat the reference
documents for refs stashed in external storage.

Borrower-death reclamation: each borrower holds one long-lived liveness
connection per owner (OP_BORROW_SESSION); when the borrower process dies
— including kill -9, where the OS closes the socket — the owner sees EOF
and drops every borrow registered under that borrower's id, freeing
objects it was the last holder of (the role of the reference's
worker-death pubsub in reference_count.h).  Wire pins (``wire:*``) and
cluster export pins are NOT session-backed and are never reaped this way
— their lifetime is the serialized copy / the head's refcount.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ray_tpu._private.ids import ObjectID


class BorrowClient:
    """Borrower-side tracker (one per process).

    All protocol messages are sent SYNCHRONOUSLY under the client lock:
    - ADD_BORROW inside register() gives happens-before between a ref
      materializing here and the owner's next free decision — by the time
      deserialization returns, the owner has the borrow on its ledger.
    - RELEASE_BORROW inside on_local_release() (still under the lock, after
      re-checking the live refcount) serializes release-vs-re-register so a
      re-borrow can never be cancelled by a stale release.
    These events are rare (first/last handle per object per process) and the
    round trip is one localhost-or-ICI-class TCP exchange, so blocking is
    the right trade for the ordering guarantees (the reference gets the same
    guarantees by piggybacking borrow reports on synchronous task replies —
    reference_count.h:66).
    """

    def __init__(self, borrower_id: str):
        self.borrower_id = borrower_id
        self._lock = threading.Lock()
        #: oid -> owner address; membership = this process holds a borrow.
        #: (Liveness of individual handles is the refcounter's job — the
        #: release path re-reads the live count rather than shadowing it.)
        self._borrows: Dict[ObjectID, str] = {}
        #: owner addr -> long-lived liveness socket: its EOF tells the
        #: owner this process died, reclaiming every borrow under our id
        #: (ref: reference_count.h worker-death pubsub).
        self._sessions: Dict[str, object] = {}
        self._keeper: Optional[threading.Thread] = None
        self.stats = {"registered": 0, "released": 0, "send_failures": 0,
                      "session_repairs": 0}

    def _open_session(self, addr: str):
        from ray_tpu._private import object_transfer as ot

        sock = ot._request_sock(addr, 2.0)
        sock.sendall(ot._req_header(ot.OP_BORROW_SESSION, self.borrower_id))
        ot._recv_exact(sock, 1)
        sock.settimeout(None)
        return sock

    def _ensure_session(self, addr: str) -> None:
        """Open (once per owner) the liveness connection; caller holds the
        lock.  Best-effort: an unreachable owner also fails the borrow
        send right after, which is the loud path."""
        if addr in self._sessions:
            return
        try:
            self._sessions[addr] = self._open_session(addr)
        except Exception:
            self.stats["send_failures"] += 1
            return
        if self._keeper is None:
            self._keeper = threading.Thread(
                target=self._session_keeper, name="borrow-session-keeper",
                daemon=True)
            self._keeper.start()

    def _session_keeper(self) -> None:
        """Watch the liveness sockets: a reset session (owner restart or a
        transient network failure) is reopened — RETRIED every pass while
        borrows to that owner remain — and every borrow RE-REGISTERED, so
        a borrower whose session blipped stays protected (the owner
        cancels its pending reap if we reconnect within its grace
        window).  All network I/O happens OUTSIDE the client lock: a slow
        owner must not stall register/release (or another owner's repair
        past its grace window)."""
        import select
        import time

        broken: set = set()  # addrs needing a reconnect attempt
        while True:
            with self._lock:
                socks = dict(self._sessions)
                held_addrs = set(self._borrows.values())
            live = {a: s for a, s in socks.items() if s is not None}
            if live:
                try:
                    readable, _, _ = select.select(
                        list(live.values()), [], [], 2.0)
                except (OSError, ValueError):
                    readable = []
                for addr, sock in live.items():
                    dead = False
                    if sock in readable:
                        try:
                            dead = sock.recv(64) == b""
                        except (ConnectionError, OSError):
                            dead = True
                    if dead:
                        try:
                            sock.close()
                        except OSError:
                            pass
                        with self._lock:
                            if self._sessions.get(addr) is sock:
                                del self._sessions[addr]
                        broken.add(addr)
            else:
                time.sleep(1.0)
            broken |= {a for a in held_addrs if a not in self._sessions}
            for addr in list(broken):
                if addr not in held_addrs:
                    broken.discard(addr)  # nothing borrowed there anymore
                    continue
                self._repair_session(addr)
                if addr in self._sessions:
                    broken.discard(addr)

    def _repair_session(self, addr: str) -> None:
        """Reconnect + re-register borrows for one owner; network I/O runs
        lock-free, with a release fix-up for borrows dropped mid-repair."""
        try:
            sock = self._open_session(addr)
        except Exception:
            self.stats["send_failures"] += 1
            return  # keeper retries next pass
        with self._lock:
            if addr in self._sessions:
                try:
                    sock.close()  # raced a concurrent _ensure_session
                except OSError:
                    pass
                return
            self._sessions[addr] = sock
            held = [oid for oid, a in self._borrows.items() if a == addr]
        for oid in held:
            _send_borrow_op("add", oid, addr, self.borrower_id)
        with self._lock:
            dropped = [oid for oid in held if oid not in self._borrows]
        for oid in dropped:
            # Released while we were re-adding: undo the stale re-add.
            _send_borrow_op("release", oid, addr, self.borrower_id)
        self.stats["session_repairs"] += 1

    # ----------------------------------------------------------- borrower API
    def register(self, oid: ObjectID, owner_addr: str) -> None:
        """Called on deserialization of a remote-owned ref; the first handle
        per object registers with the owner before returning."""
        with self._lock:
            if oid in self._borrows:
                return
            self._ensure_session(owner_addr)
            self._borrows[oid] = owner_addr
            self.stats["registered"] += 1
            self._send("add", oid, owner_addr)

    def on_local_release(self, oid: ObjectID, count_fn=None) -> None:
        """Refcounter zero-callback: all local handles died.  ``count_fn``
        re-reads the live refcount under the borrow lock — a concurrent
        re-deserialization may have revived the object between the zero
        event and this call."""
        with self._lock:
            addr = self._borrows.get(oid)
            if addr is None:
                return
            if count_fn is not None and count_fn(oid) > 0:
                return  # revived: a fresh handle exists, keep the borrow
            del self._borrows[oid]
            self.stats["released"] += 1
            self._send("release", oid, addr)

    def holds(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._borrows

    # ------------------------------------------------------------- transport
    def _send(self, kind: str, oid: ObjectID, addr: str) -> None:
        """Synchronous one-shot exchange; caller holds the lock."""
        if not _send_borrow_op(kind, oid, addr, self.borrower_id):
            # Owner gone or unreachable: nothing to protect anymore.
            self.stats["send_failures"] += 1


def _send_borrow_op(kind: str, oid: ObjectID, addr: str,
                    borrower_id: str, timeout: float = 2.0) -> bool:
    """One synchronous ADD/RELEASE_BORROW exchange; True on ack."""
    from ray_tpu._private import object_transfer as ot

    try:
        op = ot.OP_ADD_BORROW if kind == "add" else ot.OP_RELEASE_BORROW
        sock = ot._request_sock(addr, timeout)
        try:
            bid = borrower_id.encode()
            import struct

            sock.sendall(ot._req_header(op, oid)
                         + struct.pack("<H", len(bid)) + bid)
            ot._recv_exact(sock, 1)
            return True
        finally:
            sock.close()
    except Exception:
        return False


def pin_for_wire(oid: ObjectID, owner_addr: str) -> str:
    """Take a one-shot owner-side pin covering a serialized copy in flight.

    Called while the sender still holds a live handle (pickle requires one),
    so the ADD lands before the sender's own borrow/refcount can release.
    Returns the pin id to embed in the wire form, or "" if the owner is
    unreachable (the copy then rides on the sender's handle alone — the
    pre-fix behavior).
    """
    import uuid

    pin = f"wire:{uuid.uuid4().hex[:12]}"
    return pin if _send_borrow_op("add", oid, owner_addr, pin) else ""


def release_wire_pin(oid: ObjectID, owner_addr: str, pin: str) -> None:
    """Receiver side: drop the wire pin once a real borrow (or the owner's
    own refcount, when the bytes came home) protects the object."""
    _send_borrow_op("release", oid, owner_addr, pin)


_client: Optional[BorrowClient] = None
_client_lock = threading.Lock()


def global_borrow_client() -> BorrowClient:
    global _client
    with _client_lock:
        if _client is None:
            import os
            import uuid

            _client = BorrowClient(f"{os.getpid()}-{uuid.uuid4().hex[:8]}")
        return _client


def notify_zero(oid: ObjectID, count_fn=None) -> None:
    """Refcounter zero hook: release the borrow if this process held one.
    No-op (and allocation-free) unless this process ever borrowed."""
    c = _client
    if c is not None:
        c.on_local_release(oid, count_fn=count_fn)


def release_all() -> None:
    """Runtime shutdown: return every outstanding borrow to its owner.
    Sends are synchronous, so every release is on the wire (and acked)
    before this returns — nothing is lost to interpreter teardown.  The
    liveness sessions close LAST, so the owner sees orderly releases, not
    a death to reap."""
    c = _client
    if c is None:
        return
    with c._lock:
        entries = list(c._borrows.items())
        c._borrows.clear()
        for oid, addr in entries:
            c.stats["released"] += 1
            c._send("release", oid, addr)
        for sock in c._sessions.values():
            try:
                sock.close()
            except OSError:
                pass
        c._sessions.clear()


class BorrowLedger:
    """Owner-side record of which remote processes borrow which objects."""

    def __init__(self):
        self._lock = threading.Lock()
        self._borrowers: Dict[ObjectID, set] = {}

    def add(self, oid: ObjectID, borrower_id: str) -> None:
        with self._lock:
            self._borrowers.setdefault(oid, set()).add(borrower_id)

    def release(self, oid: ObjectID, borrower_id: str) -> bool:
        """Returns True when the LAST borrower released (caller may free)."""
        with self._lock:
            holders = self._borrowers.get(oid)
            if holders is None:
                return False
            holders.discard(borrower_id)
            if not holders:
                del self._borrowers[oid]
                return True
            return False

    def is_borrowed(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._borrowers

    def borrowed_ids(self):
        with self._lock:
            return list(self._borrowers)

    def drop_borrower(self, borrower_id: str) -> list:
        """A borrower died without releasing: remove it everywhere.
        Returns the oids whose LAST borrower it was (candidates to free)."""
        freed = []
        with self._lock:
            for oid, holders in list(self._borrowers.items()):
                if borrower_id in holders:
                    holders.discard(borrower_id)
                    if not holders:
                        del self._borrowers[oid]
                        freed.append(oid)
        return freed
