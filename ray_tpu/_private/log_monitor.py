"""Worker log capture + driver-side log monitor.

TPU-native analogue of the reference's worker log pipeline (ref:
python/ray/_private/log_monitor.py:103 LogMonitor — tails
/tmp/ray/session_*/logs worker files and republishes lines to the driver
with (pid=...) prefixes; workers redirect stdout/stderr at startup).

Here: process-tier workers dup2 their stdout/stderr onto per-pid files
under ``<session>/logs`` (fd-level, so native prints are captured too);
the driver runs one tailer thread that follows every ``worker-*.out/err``
file and re-emits new lines prefixed ``(worker pid=N)`` while
``log_to_driver`` is on.  Thread-tier workers share the driver's stdio and
need no capture.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, Optional, TextIO


def log_dir(export: bool = False) -> str:
    """Resolved worker-log dir: env override first (so spawned workers and
    the driver agree), else the live config's session dir; export=True
    publishes the driver's resolved path for children (see
    config.session_subdir)."""
    from ray_tpu._private.config import session_subdir

    return session_subdir("logs", "RAY_TPU_WORKER_LOG_DIR", export=export)


def redirect_worker_output() -> None:
    """Called in every process worker's main: stdout/stderr → per-pid files
    at the FD level (dup2), so python prints, warnings, and native writes
    all land in the session log dir (ref: worker stdout/stderr redirection
    in _private/worker.py)."""
    try:
        d = log_dir()
        pid = os.getpid()
        out = open(os.path.join(d, f"worker-{pid}.out"), "a", buffering=1)
        err = open(os.path.join(d, f"worker-{pid}.err"), "a", buffering=1)
        os.dup2(out.fileno(), 1)
        os.dup2(err.fileno(), 2)
        sys.stdout = out
        sys.stderr = err
    except Exception:
        pass  # logging must never stop a worker from starting


class LogMonitor:
    """Tails worker-*.out/err under the session log dir, re-emitting new
    lines to the driver's stdout with a (worker pid=N) prefix."""

    def __init__(self, directory: Optional[str] = None,
                 emit: Optional[callable] = None,
                 poll_interval_s: float = 0.2):
        self._dir = directory
        self._emit = emit or (lambda line: print(line, flush=True))
        self._interval = poll_interval_s
        self._offsets: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "LogMonitor":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="log-monitor", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def poll_once(self) -> int:
        """One tail pass (also the test entry point); returns lines emitted."""
        d = self._dir or log_dir()
        emitted = 0
        try:
            names = sorted(os.listdir(d))
        except OSError:
            return 0
        for name in names:
            if not (name.startswith("worker-")
                    and name.endswith((".out", ".err"))):
                continue
            path = os.path.join(d, name)
            pid = name.split("-", 1)[1].rsplit(".", 1)[0]
            stream = "stderr" if name.endswith(".err") else "stdout"
            try:
                size = os.path.getsize(path)
                offset = self._offsets.get(path, 0)
                if size <= offset:
                    if size < offset:  # truncated/rotated: start over
                        self._offsets[path] = 0
                    continue
                with open(path, "rb") as f:
                    f.seek(offset)
                    chunk = f.read()
                # Hold back a trailing PARTIAL line (mid-write poll): emit
                # only through the last newline; the rest re-reads next pass.
                cut = chunk.rfind(b"\n")
                if cut < 0:
                    continue
                self._offsets[path] = offset + cut + 1
                chunk = chunk[:cut]
            except OSError:
                continue
            for line in chunk.decode(errors="replace").splitlines():
                if line.strip():
                    prefix = f"(worker pid={pid})" if stream == "stdout" \
                        else f"(worker pid={pid}, stderr)"
                    self._emit(f"{prefix} {line}")
                    emitted += 1
        return emitted

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the tailer must survive
                pass
