"""Process worker pool for GIL-isolated task execution.

TPU-native analogue of the reference's WorkerPool + worker lease protocol
(ref: src/ray/raylet/worker_pool.h:216, normal_task_submitter.h:74).  In the
reference every task runs in a leased worker *process*; here processes are the
*opt-in* tier (``options(isolation="process")`` or CPU-heavy library paths),
because on TPU hosts the chips are owned by one JAX client in the driver
process and compute-bound work releases the GIL inside XLA anyway.

Protocol per worker (spawn ctx; a fork after JAX/TPU init is unsafe):
  driver -> worker: ("exec"|"exec_gen", seq, fn_id, fn_bytes|None, args_spec)
                    ("actor_call"|"actor_call_gen", seq, method, args_spec)
  worker -> driver: ("ok", seq, result_spec) | ("err", seq, flat_exc)
                    | ("yield", seq, item_spec)   [streaming kinds]
where a spec is ("inline", bytes) or ("plasma", key) — payloads above
``plasma_handoff_threshold`` travel through the native shared-memory arena
(ray_tpu/native/src/plasma.cc) zero-copy instead of the pipe, the analogue of
the reference passing ObjectIDs + plasma fds rather than bytes
(ref: plasma/client.h, fling.cc).

The pipe is MULTIPLEXED by seq: the driver side has one reader thread per
worker routing replies to per-request queues, and the worker side runs
exec/actor_call requests on threads (bounded) with a send lock — so a
process actor with max_concurrency > 1 really executes concurrently, and
streaming generators interleave with other requests (ref: core_worker's
concurrent actor calls + streaming generator protocol, _raylet.pyx:1097).
Functions are cached worker-side by fn_id so hot loops ship only args
(ref: function table export via GCS KV, _private/function_manager.py).
Leases are reused: a released worker goes back to the idle pool keyed by
runtime-env hash.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import serialization
from ray_tpu._private.config import GLOBAL_CONFIG


def _attach_arena(path: Optional[str]):
    if not path:
        return None
    try:
        from ray_tpu.native.plasma import PlasmaClient

        return PlasmaClient(path, create=False)
    except Exception:
        return None


def _spec_put(arena, key_hint: str, payload: bytes):
    """Choose the transport for one payload."""
    if arena is not None and len(payload) > GLOBAL_CONFIG.plasma_handoff_threshold:
        try:
            arena.put_bytes(key_hint, payload)
            return ("plasma", key_hint)
        except (MemoryError, ValueError):
            pass  # arena full or key collision: the pipe always works
    return ("inline", payload)


def _spec_take(arena, spec) -> bytes:
    """Fetch and consume one payload (plasma objects are freed here)."""
    kind, val = spec
    if kind == "inline":
        return val
    if arena is None:
        raise RuntimeError(
            f"peer sent plasma handoff {val} but this side has no arena client")
    data = arena.get_bytes(val, timeout=30)
    if data is None:
        raise RuntimeError(f"plasma handoff object {val} missing")
    arena.release(val)  # creator's ref
    arena.delete(val)
    return data


def _spec_cleanup(arena, spec) -> None:
    """Best-effort free of an unconsumed plasma handoff (idempotent: no-op if
    the peer already consumed it via _spec_take)."""
    if arena is None or spec[0] != "plasma":
        return
    try:
        arena.release(spec[1])
        arena.delete(spec[1])
    except Exception:
        pass


def _actor_task_context(actor_id):
    """Worker-side actor-scoped context manager so exit_actor() and
    get_runtime_context() work inside process-isolated actor methods."""
    from contextlib import contextmanager

    @contextmanager
    def cm():
        from ray_tpu._private.ids import TaskID
        from ray_tpu._private.runtime import TaskContext, _task_ctx

        _task_ctx.ctx = TaskContext(TaskID.from_random(), actor_id)
        try:
            yield
        finally:
            _task_ctx.ctx = None

    return cm()


def _worker_main(conn, arena_path: Optional[str], back_conn=None) -> None:
    # Keep workers off the TPU: the driver process owns the chips.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # On-demand stack dumps (`ray_tpu stack`, ref: py-spy via the reporter
    # agent): SIGUSR1 → faulthandler dump readable by the driver.
    from ray_tpu._private.stack_profiler import install_worker_dump_handler

    install_worker_dump_handler()
    # Worker stdout/stderr → per-pid session log files, tailed back to the
    # driver by the LogMonitor (ref: _private/log_monitor.py:103).
    from ray_tpu._private.log_monitor import redirect_worker_output

    redirect_worker_output()
    fn_cache: Dict[str, Any] = {}
    actor_instance: List[Any] = [None]  # box: set by actor_new
    arena = _attach_arena(arena_path)
    if back_conn is not None:
        # Nested-API support: install the proxy runtime so user code in this
        # worker can call ray_tpu.remote/get/put/wait (client_runtime.py).
        from ray_tpu._private.client_runtime import ClientRuntime
        from ray_tpu._private.runtime import install_runtime

        install_runtime(ClientRuntime(
            back_conn, worker_id=f"proc-worker-{os.getpid()}"))

    send_lock = threading.Lock()
    #: Streams the driver abandoned (cancel/early error): the worker's
    #: yield loops check membership and stop pumping the user generator.
    stopped_streams: set = set()

    def send(msg) -> None:
        with send_lock:
            conn.send_bytes(serialization.dumps(msg))

    def reply_ok(seq, payload):
        send(("ok", seq, payload))

    def reply_err(seq, e):
        import traceback

        tb = traceback.format_exc()
        try:
            blob = serialization.dumps((e, tb))
        except Exception:
            blob = serialization.dumps((RuntimeError(repr(e)), tb))
        send(("err", seq, blob))

    def run_exec(seq, fn_id, fn_bytes, args_spec, streaming):
        try:
            if fn_id not in fn_cache:
                if fn_bytes is not None:
                    fn_cache[fn_id] = serialization.loads(fn_bytes)
                else:
                    # Concurrent first-use race: another in-flight request
                    # carries the bytes; wait for its thread to cache them.
                    deadline = time.monotonic() + 10
                    while fn_id not in fn_cache:
                        if time.monotonic() > deadline:
                            raise RuntimeError(
                                f"function {fn_id} never arrived")
                        time.sleep(0.005)
            fn = fn_cache[fn_id]
            flat_args = _spec_take(arena, args_spec)
            args, kwargs = serialization.deserialize_flat(memoryview(flat_args))
            if streaming:
                n = 0
                for item in fn(*args, **kwargs):
                    if seq in stopped_streams:
                        break  # driver abandoned the stream
                    payload = serialization.serialize(item).to_bytes()
                    send(("yield", seq, _spec_put(
                        arena, f"res:{os.getpid()}:{seq}:{n}", payload)))
                    n += 1
                stopped_streams.discard(seq)
                reply_ok(seq, None)
                return
            result = fn(*args, **kwargs)
            payload = serialization.serialize(result).to_bytes()
            reply_ok(seq, _spec_put(arena, f"res:{os.getpid()}:{seq}", payload))
        except BaseException as e:  # noqa: BLE001 — errors cross the boundary
            reply_err(seq, e)

    def run_actor_call(seq, method_name, args_spec, streaming):
        try:
            if actor_instance[0] is None:
                raise RuntimeError("actor_call before actor_new")
            method = getattr(actor_instance[0], method_name)
            flat_args = _spec_take(arena, args_spec)
            args, kwargs = serialization.deserialize_flat(memoryview(flat_args))
            # Run under an actor-scoped task context so exit_actor() and
            # get_runtime_context() work inside the method; _ActorExit
            # crosses back via reply_err and is unwrapped driver-side.
            with _actor_task_context(actor_instance[1]):
                if streaming:
                    n = 0
                    for item in method(*args, **kwargs):
                        if seq in stopped_streams:
                            break  # driver abandoned the stream
                        payload = serialization.serialize(item).to_bytes()
                        send(("yield", seq, _spec_put(
                            arena, f"res:{os.getpid()}:{seq}:{n}", payload)))
                        n += 1
                    stopped_streams.discard(seq)
                    reply_ok(seq, None)
                    return
                result = method(*args, **kwargs)
            payload = serialization.serialize(result).to_bytes()
            reply_ok(seq, _spec_put(arena, f"res:{os.getpid()}:{seq}", payload))
        except BaseException as e:  # noqa: BLE001
            reply_err(seq, e)

    #: Bound on concurrent in-worker requests (actor max_concurrency is
    #: enforced by the driver's mailbox threads; this is a backstop).
    work_sem = threading.BoundedSemaphore(64)

    def spawn(target, *args):
        def run():
            with work_sem:
                target(*args)

        threading.Thread(target=run, daemon=True).start()

    while True:
        try:
            msg = conn.recv_bytes()
        except (EOFError, OSError):
            return
        req = serialization.loads(msg)
        kind = req[0]
        if kind == "setup_env":
            # Applied once per worker; the pool keys leases by env hash so a
            # worker only ever hosts one runtime env (ref: worker_pool.h
            # runtime-env-keyed caching).
            try:
                from ray_tpu._private.runtime_env import apply_in_worker

                apply_in_worker(req[1])
                reply_ok(0, None)
            except BaseException as e:  # noqa: BLE001
                reply_err(0, e)
        elif kind in ("exec", "exec_gen"):
            _, seq, fn_id, fn_bytes, args_spec = req
            # Off-thread: concurrent requests (max_concurrency > 1 actors,
            # interleaved streams) must not serialize behind one another.
            spawn(run_exec, seq, fn_id, fn_bytes, args_spec,
                  kind == "exec_gen")
        elif kind == "actor_new":
            # This worker becomes a dedicated actor host: instantiate the
            # class and hold it for the worker's lifetime (ref: the reference
            # runs every actor in its own worker process by default).
            _, seq, cls_bytes, actor_id, args_spec = req
            try:
                cls = serialization.loads(cls_bytes)
                flat_args = _spec_take(arena, args_spec)
                args, kwargs = serialization.deserialize_flat(memoryview(flat_args))
                with _actor_task_context(actor_id):
                    actor_instance[0] = cls(*args, **kwargs)
                actor_instance.append(actor_id)
                reply_ok(seq, None)
            except BaseException as e:  # noqa: BLE001
                reply_err(seq, e)
        elif kind in ("actor_call", "actor_call_gen"):
            _, seq, method_name, args_spec = req
            spawn(run_actor_call, seq, method_name, args_spec,
                  kind == "actor_call_gen")
        elif kind == "actor_exec":
            # Run an arbitrary shipped function against the resident actor
            # instance (compiled-DAG executor loops live here: long-running,
            # multiplexed beside ordinary calls).
            _, seq, fn_bytes, args_spec = req

            def run_actor_exec(seq=seq, fn_bytes=fn_bytes,
                               args_spec=args_spec):
                try:
                    if actor_instance[0] is None:
                        raise RuntimeError("actor_exec before actor_new")
                    if arena is not None:
                        # Unpickled shm channels attach by path: reuse THIS
                        # worker's client instead of opening a second mmap.
                        try:
                            from ray_tpu.dag.channel import seed_arena_client

                            seed_arena_client(arena.path, arena)
                        except Exception:
                            pass
                    fn = serialization.loads(fn_bytes)
                    flat = _spec_take(arena, args_spec)
                    args, kwargs = serialization.deserialize_flat(
                        memoryview(flat))
                    with _actor_task_context(
                            actor_instance[1] if len(actor_instance) > 1
                            else None):
                        result = fn(actor_instance[0], *args, **kwargs)
                    payload = serialization.serialize(result).to_bytes()
                    reply_ok(seq, _spec_put(
                        arena, f"res:{os.getpid()}:{seq}", payload))
                except BaseException as e:  # noqa: BLE001
                    reply_err(seq, e)

            spawn(run_actor_exec)
        elif kind == "gen_stop":
            stopped_streams.add(req[1])
        elif kind == "shutdown":
            return


_HANDOFF_COUNTER = 0
_HANDOFF_LOCK = threading.Lock()


def _next_handoff_key(prefix: str) -> str:
    global _HANDOFF_COUNTER
    with _HANDOFF_LOCK:
        _HANDOFF_COUNTER += 1
        return f"{prefix}:{os.getpid()}:{_HANDOFF_COUNTER}"


_LOG_MONITOR = None
_LOG_MONITOR_LOCK = threading.Lock()


def _ensure_log_monitor() -> None:
    """One driver-wide tailer streaming worker logs back to this terminal
    while config.log_to_driver is on (ref: LogMonitor publishes to the
    driver via GCS pubsub; in-process here)."""
    global _LOG_MONITOR
    if not GLOBAL_CONFIG.log_to_driver:
        return
    with _LOG_MONITOR_LOCK:
        if _LOG_MONITOR is None:
            from ray_tpu._private.log_monitor import LogMonitor

            _LOG_MONITOR = LogMonitor().start()


def stop_log_monitor() -> None:
    """Runtime shutdown: end the tailer so a later init (possibly with
    log_to_driver=False) doesn't inherit a still-streaming thread."""
    global _LOG_MONITOR
    with _LOG_MONITOR_LOCK:
        if _LOG_MONITOR is not None:
            _LOG_MONITOR.stop()
            _LOG_MONITOR = None


class _ProcWorker:
    def __init__(self, arena_path: Optional[str] = None, arena=None,
                 env_key: str = "", env_payload: Optional[dict] = None) -> None:
        import sys

        self.env_key = env_key

        # Export resolved dirs so the spawned child (which sees only config
        # DEFAULTS) writes its SIGUSR1 dump file and stdout/stderr logs
        # where this driver will look for them.
        from ray_tpu._private.log_monitor import log_dir
        from ray_tpu._private.stack_profiler import dump_dir

        dump_dir(export=True)
        log_dir(export=True)
        _ensure_log_monitor()

        ctx = mp.get_context("spawn")
        self.conn, child_conn = ctx.Pipe()
        # Second pipe: the worker-initiated nested-API backchannel, serviced
        # by a dedicated driver thread (client_runtime.serve_backchannel) so
        # a child blocking in get() is independent of this request pipe.
        back_parent, back_child = ctx.Pipe()
        self.proc = ctx.Process(
            target=_worker_main, args=(child_conn, arena_path, back_child),
            daemon=True)
        # Drivers run from a pipe/heredoc have __main__.__file__ == "<stdin>";
        # spawn's prepare step would try to re-execute that path in the child
        # and crash it.  Mask the pseudo-file for the duration of start().
        main_mod = sys.modules.get("__main__")
        main_file = getattr(main_mod, "__file__", None)
        masked = main_file is not None and str(main_file).startswith("<")
        if masked:
            del main_mod.__file__
        try:
            self.proc.start()
        finally:
            if masked:
                main_mod.__file__ = main_file
        child_conn.close()
        back_child.close()
        from ray_tpu._private.client_runtime import serve_backchannel

        self._back_thread = threading.Thread(
            target=serve_backchannel, args=(back_parent,),
            name=f"backchannel-{self.proc.pid}", daemon=True)
        self._back_thread.start()
        self._arena = arena  # the pool's shared driver-side client
        import itertools
        import queue as queue_mod

        self._seq_counter = itertools.count(1)  # GIL-atomic next()
        self.sent_fns: set = set()
        self.last_used = time.monotonic()
        # The pipe is seq-multiplexed: sends serialize under this lock; a
        # reader thread routes replies (ok/err/yield) to per-seq queues, so
        # max_concurrency > 1 actors and interleaved streams really overlap.
        self._send_lock = threading.Lock()
        self._pending: Dict[int, "queue_mod.SimpleQueue"] = {}
        self._pending_lock = threading.Lock()
        self._dead = False
        self._queue_mod = queue_mod
        self._reader = threading.Thread(
            target=self._read_loop, name=f"procworker-read-{self.proc.pid}",
            daemon=True)
        self._reader.start()
        if env_payload is not None:
            from ray_tpu.exceptions import TaskError

            q = self._register(0)
            with self._send_lock:
                self.conn.send_bytes(
                    serialization.dumps(("setup_env", env_payload)))
            kind, payload = q.get()
            self._unregister(0)
            if kind == "err":
                exc, tb = serialization.loads(payload)
                self.kill()
                raise TaskError(exc, tb=tb)
            if kind == "crash":
                self.kill()
                raise RuntimeError("process worker died during env setup")

    # ----------------------------------------------------------- multiplexer
    def _register(self, seq: int):
        q = self._queue_mod.SimpleQueue()
        with self._pending_lock:
            if self._dead:
                q.put(("crash", None))
            self._pending[seq] = q
        return q

    def _unregister(self, seq: int) -> None:
        with self._pending_lock:
            self._pending.pop(seq, None)

    def _read_loop(self) -> None:
        while True:
            try:
                reply = serialization.loads(self.conn.recv_bytes())
            except (EOFError, OSError):
                break
            except Exception:
                break
            rkind, seq, payload = reply
            with self._pending_lock:
                q = self._pending.get(seq)
            if q is not None:
                q.put((rkind, payload))
            elif rkind == "yield":
                # Stream abandoned before this item arrived: a plasma
                # payload would otherwise pin arena memory forever.
                _spec_cleanup(self._arena, payload)
        # Worker gone: wake every in-flight request with a crash marker.
        with self._pending_lock:
            self._dead = True
            waiters = list(self._pending.values())
        for q in waiters:
            q.put(("crash", None))

    def _submit(self, kind: str, header_rest: tuple, args: tuple,
                kwargs: dict):
        """Ship one request; returns (seq, queue, args_spec)."""
        arena = self._arena
        seq = next(self._seq_counter)  # GIL-atomic
        flat_args = serialization.serialize((args, kwargs)).to_bytes()
        args_spec = _spec_put(arena, _next_handoff_key("args"), flat_args)
        header = (kind, seq) + header_rest
        q = self._register(seq)
        try:
            with self._send_lock:
                self.conn.send_bytes(serialization.dumps(header + (args_spec,)))
        except (EOFError, OSError) as e:
            from ray_tpu.exceptions import WorkerCrashedError

            self._unregister(seq)
            _spec_cleanup(arena, args_spec)
            raise WorkerCrashedError(f"process worker died: {e}") from e
        return seq, q, args_spec

    def _raise_reply_error(self, payload):
        from ray_tpu.exceptions import TaskError
        from ray_tpu._private.runtime import _ActorExit

        exc, tb = serialization.loads(payload)
        if isinstance(exc, _ActorExit):
            # exit_actor() inside a process actor: re-raise unwrapped so the
            # runtime's actor FSM sees it (runtime.py _execute_actor_task).
            raise exc
        raise TaskError(exc, tb=tb)

    def _roundtrip(self, kind: str, header_rest: tuple, args: tuple,
                   kwargs: dict, has_result: bool = True) -> Any:
        """One request/reply over the multiplexed pipe.

        Raises WorkerCrashedError if the process dies, TaskError on a
        worker-side exception."""
        from ray_tpu.exceptions import WorkerCrashedError

        arena = self._arena
        seq, q, args_spec = self._submit(kind, header_rest, args, kwargs)
        try:
            rkind, payload = q.get()
        finally:
            self._unregister(seq)
        self.last_used = time.monotonic()
        if rkind == "crash":
            # Reclaim the args if unconsumed, and the result object if the
            # worker got far enough to produce one before dying — a sealed-
            # but-unreported result would otherwise pin arena memory forever.
            _spec_cleanup(arena, args_spec)
            _spec_cleanup(arena, ("plasma", f"res:{self.proc.pid}:{seq}"))
            raise WorkerCrashedError("process worker died")
        if rkind == "ok":
            # The worker reached the result, so it consumed the args spec.
            if not has_result or payload is None:
                return None
            return serialization.deserialize_flat(
                memoryview(_spec_take(arena, payload)))
        # Error may have struck before the worker consumed the args.
        _spec_cleanup(arena, args_spec)
        self._raise_reply_error(payload)

    def _stream(self, kind: str, header_rest: tuple, args: tuple,
                kwargs: dict):
        """Streaming request: yields items as the worker produces them;
        terminates on the worker's ok (end) / err (raised) / crash."""
        from ray_tpu.exceptions import WorkerCrashedError

        arena = self._arena
        seq, q, args_spec = self._submit(kind, header_rest, args, kwargs)
        finished = False
        try:
            while True:
                rkind, payload = q.get()
                self.last_used = time.monotonic()
                if rkind == "yield":
                    yield serialization.deserialize_flat(
                        memoryview(_spec_take(arena, payload)))
                    continue
                if rkind == "ok":
                    finished = True
                    return
                finished = True
                if rkind == "crash":
                    _spec_cleanup(arena, args_spec)
                    raise WorkerCrashedError("process worker died mid-stream")
                _spec_cleanup(arena, args_spec)
                self._raise_reply_error(payload)
        finally:
            self._unregister(seq)
            if not finished:
                # Consumer abandoned the stream (cancel / early close):
                # tell the worker to stop pumping; items already in our
                # queue are reclaimed here, late ones by the reader's
                # dropped-yield cleanup.
                try:
                    with self._send_lock:
                        self.conn.send_bytes(
                            serialization.dumps(("gen_stop", seq)))
                except (EOFError, OSError):
                    pass
                while not q.empty():
                    rkind, payload = q.get()
                    if rkind == "yield":
                        _spec_cleanup(arena, payload)

    def execute(self, fn_id: str, fn_bytes: bytes, args: tuple, kwargs: dict) -> Any:
        """Run one task; raises WorkerCrashedError if the process dies."""
        send_fn = fn_bytes if fn_id not in self.sent_fns else None
        self.sent_fns.add(fn_id)
        return self._roundtrip("exec", (fn_id, send_fn), args, kwargs)

    def execute_gen(self, fn_id: str, fn_bytes: bytes, args: tuple,
                    kwargs: dict):
        """Run one GENERATOR task; yields items as the worker sends them."""
        send_fn = fn_bytes if fn_id not in self.sent_fns else None
        self.sent_fns.add(fn_id)
        return self._stream("exec_gen", (fn_id, send_fn), args, kwargs)

    def actor_new(self, cls_bytes: bytes, actor_id: str, args: tuple,
                  kwargs: dict) -> None:
        """Instantiate an actor in this worker (dedicates the worker)."""
        self._roundtrip("actor_new", (cls_bytes, actor_id), args, kwargs,
                        has_result=False)

    def actor_call(self, method_name: str, args: tuple, kwargs: dict) -> Any:
        """Invoke a method on the worker-resident actor instance."""
        return self._roundtrip("actor_call", (method_name,), args, kwargs)

    def actor_call_gen(self, method_name: str, args: tuple, kwargs: dict):
        """Invoke a GENERATOR method; yields items as the worker sends them."""
        return self._stream("actor_call_gen", (method_name,), args, kwargs)

    def actor_exec(self, fn_bytes: bytes, args: tuple, kwargs: dict) -> Any:
        """Run fn(instance, *args, **kwargs) against the worker-resident
        actor instance (compiled-DAG resident loops)."""
        return self._roundtrip("actor_exec", (fn_bytes,), args, kwargs)

    def alive(self) -> bool:
        return self.proc.is_alive()

    def kill(self) -> None:
        try:
            self.proc.terminate()
        except Exception:
            pass


class ProcessPool:
    """Idle-pool of reusable spawned workers with an upper bound."""

    def __init__(self, arena_path: Optional[str] = None, arena=None) -> None:
        #: Idle workers keyed by runtime-env hash ("" = no env) — the
        #: reference's runtime-env-keyed WorkerPool cache (worker_pool.h:216).
        self._idle: Dict[str, List[_ProcWorker]] = {}
        self._lock = threading.Lock()
        self._count = 0
        self.arena_path = arena_path
        # One shared driver-side arena client for all workers (one mmap + fd
        # per process, as plasma.py documents) — normally the ObjectStore's
        # own client, passed in by the runtime.
        self._arena = arena if arena is not None else _attach_arena(arena_path)

    def lease(self, env_key: str = "",
              env_payload: Optional[dict] = None) -> _ProcWorker:
        with self._lock:
            pool = self._idle.get(env_key, [])
            while pool:
                w = pool.pop()
                if w.alive():
                    return w
                self._count -= 1
            self._count += 1
        try:
            return _ProcWorker(self.arena_path, self._arena,
                               env_key=env_key, env_payload=env_payload)
        except BaseException:
            with self._lock:
                self._count -= 1
            raise

    def release(self, worker: _ProcWorker) -> None:
        if not worker.alive():
            with self._lock:
                self._count -= 1
            return
        with self._lock:
            if self._count <= GLOBAL_CONFIG.max_process_workers:
                self._idle.setdefault(worker.env_key, []).append(worker)
                return
            self._count -= 1
        worker.kill()

    def discard(self, worker: _ProcWorker) -> None:
        with self._lock:
            self._count -= 1
        worker.kill()

    def shutdown(self) -> None:
        with self._lock:
            pools, self._idle, self._count = self._idle, {}, 0
        workers = [w for pool in pools.values() for w in pool]
        for w in workers:
            try:
                w.conn.send_bytes(serialization.dumps(("shutdown",)))
            except Exception:
                pass
            w.kill()
