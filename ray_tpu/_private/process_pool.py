"""Process worker pool for GIL-isolated task execution.

TPU-native analogue of the reference's WorkerPool + worker lease protocol
(ref: src/ray/raylet/worker_pool.h:216, normal_task_submitter.h:74).  In the
reference every task runs in a leased worker *process*; here processes are the
*opt-in* tier (``options(isolation="process")`` or CPU-heavy library paths),
because on TPU hosts the chips are owned by one JAX client in the driver
process and compute-bound work releases the GIL inside XLA anyway.

Protocol per worker (spawn ctx; a fork after JAX/TPU init is unsafe):
  driver -> worker: ("exec", seq, fn_id, fn_bytes|None, flat_args)
  worker -> driver: ("ok", seq, flat_result) | ("err", seq, flat_exc)
Functions are cached worker-side by fn_id so hot loops ship only args
(ref: function table export via GCS KV, _private/function_manager.py).
Leases are reused: a released worker goes back to the idle pool keyed by
nothing (runtime-env keying can come with runtime envs).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import serialization
from ray_tpu._private.config import GLOBAL_CONFIG


def _worker_main(conn) -> None:
    # Keep workers off the TPU: the driver process owns the chips.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    fn_cache: Dict[str, Any] = {}
    while True:
        try:
            msg = conn.recv_bytes()
        except (EOFError, OSError):
            return
        req = serialization.loads(msg)
        kind = req[0]
        if kind == "exec":
            _, seq, fn_id, fn_bytes, flat_args = req
            try:
                if fn_id not in fn_cache:
                    fn_cache[fn_id] = serialization.loads(fn_bytes)
                fn = fn_cache[fn_id]
                args, kwargs = serialization.deserialize_flat(memoryview(flat_args))
                result = fn(*args, **kwargs)
                payload = serialization.serialize(result).to_bytes()
                conn.send_bytes(serialization.dumps(("ok", seq, payload)))
            except BaseException as e:  # noqa: BLE001 — errors cross the boundary
                import traceback

                tb = traceback.format_exc()
                try:
                    blob = serialization.dumps((e, tb))
                except Exception:
                    blob = serialization.dumps((RuntimeError(repr(e)), tb))
                conn.send_bytes(serialization.dumps(("err", seq, blob)))
        elif kind == "shutdown":
            return


class _ProcWorker:
    def __init__(self) -> None:
        ctx = mp.get_context("spawn")
        self.conn, child_conn = ctx.Pipe()
        self.proc = ctx.Process(target=_worker_main, args=(child_conn,), daemon=True)
        self.proc.start()
        child_conn.close()
        self.seq = 0
        self.sent_fns: set = set()
        self.last_used = time.monotonic()

    def execute(self, fn_id: str, fn_bytes: bytes, args: tuple, kwargs: dict) -> Any:
        """Run one task; raises WorkerCrashedError if the process dies."""
        from ray_tpu.exceptions import TaskError, WorkerCrashedError

        self.seq += 1
        flat_args = serialization.serialize((args, kwargs)).to_bytes()
        send_fn = fn_bytes if fn_id not in self.sent_fns else None
        self.conn.send_bytes(
            serialization.dumps(("exec", self.seq, fn_id, send_fn, flat_args))
        )
        self.sent_fns.add(fn_id)
        try:
            reply = serialization.loads(self.conn.recv_bytes())
        except (EOFError, OSError) as e:
            raise WorkerCrashedError(f"process worker died: {e}") from e
        kind, seq, payload = reply
        self.last_used = time.monotonic()
        if kind == "ok":
            return serialization.deserialize_flat(memoryview(payload))
        exc, tb = serialization.loads(payload)
        raise TaskError(exc, tb=tb)

    def alive(self) -> bool:
        return self.proc.is_alive()

    def kill(self) -> None:
        try:
            self.proc.terminate()
        except Exception:
            pass


class ProcessPool:
    """Idle-pool of reusable spawned workers with an upper bound."""

    def __init__(self) -> None:
        self._idle: List[_ProcWorker] = []
        self._lock = threading.Lock()
        self._count = 0

    def lease(self) -> _ProcWorker:
        with self._lock:
            while self._idle:
                w = self._idle.pop()
                if w.alive():
                    return w
                self._count -= 1
            self._count += 1
        return _ProcWorker()

    def release(self, worker: _ProcWorker) -> None:
        if not worker.alive():
            with self._lock:
                self._count -= 1
            return
        with self._lock:
            if self._count <= GLOBAL_CONFIG.max_process_workers:
                self._idle.append(worker)
                return
            self._count -= 1
        worker.kill()

    def discard(self, worker: _ProcWorker) -> None:
        with self._lock:
            self._count -= 1
        worker.kill()

    def shutdown(self) -> None:
        with self._lock:
            workers, self._idle, self._count = self._idle, [], 0
        for w in workers:
            try:
                w.conn.send_bytes(serialization.dumps(("shutdown",)))
            except Exception:
                pass
            w.kill()
