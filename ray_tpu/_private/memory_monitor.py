"""Memory monitor + worker-killing policy for OOM protection.

TPU-native analogue of the reference's OOM defense (ref:
src/ray/common/memory_monitor.h:52 — periodic cgroup/proc sampling against
a usage threshold; src/ray/raylet/worker_killing_policy.h and
worker_killing_policy_retriable_fifo.h — pick a victim worker, preferring
retriable then newest, and kill it so the node survives).

Here the monitored population is the process-tier worker pool (thread-tier
workers share the driver's address space, where the object store's own
spilling is the pressure valve).  The sampler is injectable so tests drive
deterministic pressure without allocating memory.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional


class MemoryMonitor:
    """Samples usage fraction; over threshold → kill one victim per tick."""

    def __init__(self, *,
                 usage_fraction_fn: Optional[Callable[[], float]] = None,
                 victims_fn: Optional[Callable[[], List]] = None,
                 kill_fn: Optional[Callable[[object], None]] = None,
                 threshold: float = 0.95,
                 check_interval_s: float = 1.0,
                 min_memory_free_bytes: Optional[int] = None,
                 free_bytes_fn: Optional[Callable[[], int]] = None):
        self._usage = usage_fraction_fn or _system_usage_fraction
        self._victims = victims_fn or (lambda: [])
        self._kill = kill_fn or (lambda w: None)
        self.threshold = threshold
        self.interval = check_interval_s
        #: absolute floor (ref: min_memory_free_bytes): pressure also when
        #: free memory drops under this many bytes, whatever the fraction.
        self.min_memory_free_bytes = min_memory_free_bytes
        self._free_bytes = free_bytes_fn or _system_free_bytes
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stats = {"checks": 0, "kills": 0, "last_usage": 0.0}

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="memory-monitor", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def tick(self) -> bool:
        """One check (also the test entry point).  Returns True if a worker
        was killed."""
        self.stats["checks"] += 1
        usage = self._usage()
        self.stats["last_usage"] = usage
        under_floor = (self.min_memory_free_bytes is not None
                       and self._free_bytes() < self.min_memory_free_bytes)
        if usage < self.threshold and not under_floor:
            return False
        victim = self._choose_victim(self._victims())
        if victim is None:
            return False
        self._kill(victim)
        self.stats["kills"] += 1
        return True

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — monitoring must not die
                pass

    @staticmethod
    def _choose_victim(workers: List) -> Optional[object]:
        """Retriable-first, then LIFO (newest task loses — it has the least
        progress to lose; ref: worker_killing_policy_retriable_fifo.h)."""
        if not workers:
            return None
        def sort_key(w):
            retriable = bool(getattr(w, "retriable", True))
            started = float(getattr(w, "started_at", 0.0))
            # Retriable first (False sorts after True via `not`), then newest.
            return (not retriable, -started)

        return sorted(workers, key=sort_key)[0]


def _system_usage_fraction() -> float:
    try:
        import psutil

        return psutil.virtual_memory().percent / 100.0
    except Exception:
        return 0.0


def _system_free_bytes() -> int:
    try:
        import psutil

        return int(psutil.virtual_memory().available)
    except Exception:
        return 1 << 62  # unknowable: never trip the floor
