"""Worker-side runtime proxy: the full ray_tpu API from inside a process
worker.

TPU-native analogue of the reference's nested-task support: every worker
process embeds a core worker that can submit tasks back to the cluster
(ref: src/ray/core_worker/core_worker.h:166 — task submission from any
worker; python/ray/util/client/ — the proxy pattern).  Here the child
process installs a ``ClientRuntime`` as its global runtime; API calls
(`remote`/`get`/`put`/`wait`/actor ops) become request/response messages
over a dedicated backchannel pipe to the driver, which executes them
against the real Runtime.

One request is in flight per worker at a time (child-side lock); the driver
services each worker's backchannel on its own daemon thread, so a child
blocking in ``get`` never wedges the driver's dispatcher.
"""

from __future__ import annotations

import threading
from typing import Any, Optional, Sequence

from ray_tpu._private import serialization


class _ProxiedRefGenerator:
    """Worker-side face of a driver-hosted ObjectRefGenerator: each pull is
    one nested-API round trip returning the next yielded ObjectRef (VERDICT
    r2 item 8 — streaming submission from process workers/ray:// drivers;
    ref: _raylet.pyx streaming generator protocol)."""

    def __init__(self, call, token: str):
        self._call = call
        self._token = token
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        kind, ref = self._call("gen_next", self._token)
        if kind == "done":
            self._done = True
            raise StopIteration
        return ref

    def cancel(self) -> None:
        if not self._done:
            self._done = True
            try:
                self._call("gen_cancel", self._token)
            except Exception:
                pass

    def __del__(self):
        try:
            self.cancel()
        except Exception:
            pass


class ClientRuntime:
    """Installed as the global runtime inside process workers."""

    def __init__(self, conn, worker_id: str = "", namespace: str = "default"):
        self._conn = conn
        self._lock = threading.Lock()
        self.worker_id = worker_id or "proc-worker"
        self.namespace = namespace

    # ------------------------------------------------------------- transport
    def _call(self, kind: str, *payload) -> Any:
        # In-band: the head deserializes while this call blocks, inside the
        # sender's handle lifetime — wire pins would be pure overhead.
        req = serialization.dumps_inband((kind, payload))
        with self._lock:
            self._conn.send_bytes(req)
            status, blob = serialization.loads(self._conn.recv_bytes())
        if status == "err":
            exc, tb = serialization.loads(blob)
            raise exc
        return serialization.deserialize_flat(memoryview(blob))

    # ------------------------------------------------------------ public API
    def submit_task(self, spec) -> Any:
        if spec.generator:
            token = self._call("submit_task_gen",
                               serialization.dumps_inband(spec))
            return _ProxiedRefGenerator(self._call, token)
        return self._call("submit_task", serialization.dumps_inband(spec))

    def submit_actor_task(self, actor_id, spec) -> Any:
        if spec.generator:
            token = self._call("submit_actor_task_gen", actor_id,
                               serialization.dumps_inband(spec))
            return _ProxiedRefGenerator(self._call, token)
        return self._call("submit_actor_task", actor_id,
                          serialization.dumps_inband(spec))

    def create_actor(self, spec) -> None:
        return self._call("create_actor", serialization.dumps_inband(spec))

    def put(self, value: Any, _owner: str = "") -> Any:
        return self._call("put", serialization.dumps_inband(value))

    def get(self, refs: Any, timeout: Optional[float] = None) -> Any:
        return self._call("get", serialization.dumps_inband(refs), timeout)

    def wait(self, refs: Sequence, num_returns: int = 1,
             timeout: Optional[float] = None, fetch_local: bool = True):
        refs = list(refs)
        ready_idx, rest_idx = self._call(
            "wait", serialization.dumps_inband(refs), num_returns, timeout)
        return [refs[i] for i in ready_idx], [refs[i] for i in rest_idx]

    def kill_actor(self, actor_id, no_restart: bool = True) -> None:
        return self._call("kill_actor", actor_id, no_restart)

    def cancel(self, ref, force: bool = False) -> None:
        return self._call("cancel", serialization.dumps_inband(ref), force)

    def get_named_actor(self, name: str, namespace: Optional[str] = None):
        return self._call("get_named_actor", name, namespace)

    def cluster_resources(self):
        return self._call("cluster_resources")

    def available_resources(self):
        return self._call("available_resources")

    def nodes(self):
        return self._call("nodes")

    def list_task_events(self):
        return self._call("list_task_events")

    def kv_call(self, op: str, *args) -> Any:
        """Route an internal-KV operation to the head's store so the KV tier
        is cluster-global, matching the reference's GCS KV (ADVICE r2 —
        a worker-local store silently diverges from the driver's)."""
        return self._call("internal_kv", op, *args)

    def get_actor_state(self, actor_id):
        # Worker-side callers (ray_tpu.get_actor) need .spec.cls and
        # .spec.max_task_retries plus .state — return a lightweight shim.
        cls, max_task_retries, state_name = self._call("actor_info", actor_id)

        class _Spec:
            pass

        class _State:
            pass

        spec = _Spec()
        spec.cls = cls
        spec.max_task_retries = max_task_retries
        shim = _State()
        shim.spec = spec
        shim.state = state_name
        return shim

    def shutdown(self) -> None:
        """ray:// drivers close their TCP transport, ending the server's
        per-connection serve thread and releasing the refs it borrowed on
        this driver's behalf.  Process workers (pipe backchannel) must NOT
        close: the driver owns that lifecycle, and a user task calling
        ray_tpu.shutdown() inside a pooled worker would wedge the worker."""
        if getattr(self, "_client_conn", None) is not None:
            try:
                self._client_conn.close()
            except Exception:
                pass


def serve_backchannel(conn, describe: str = "") -> None:
    """Driver-side loop: service one worker's nested-API requests.

    Runs on a daemon thread per worker; exits when the worker's pipe closes.
    """
    from ray_tpu._private.runtime import runtime_or_none

    # Refs handed to the child are BORROWED: the driver must keep them alive
    # or the refcounter frees results the moment the reply tuple is GC'd
    # (ref: reference_count.h borrower protocol — here the borrow lives until
    # the worker disconnects, which clears this dict).
    borrowed: dict = {}
    state: dict = {"gens": {}}  # live proxied generators, per connection
    while True:
        try:
            msg = conn.recv_bytes()
        except (EOFError, OSError):
            return
        try:
            kind, payload = serialization.loads(msg)
            runtime = runtime_or_none()
            if runtime is None:
                raise RuntimeError(
                    "driver runtime is gone; nested call cannot be served")
            result = _handle(runtime, kind, payload, state=state)
            sobj = serialization.serialize(result)
            if kind != "gen_next":
                # gen_next replies are pinned by their stream's token entry
                # (released when the stream ends) — parking them here too
                # would hold every streamed item for the CONNECTION's life.
                for r in sobj.contained_refs:
                    borrowed[r.id] = r
            reply = ("ok", sobj.to_bytes())
        except BaseException as e:  # noqa: BLE001 — errors cross the boundary
            import traceback

            tb = traceback.format_exc()
            try:
                blob = serialization.dumps((e, tb))
            except Exception:
                blob = serialization.dumps((RuntimeError(repr(e)), tb))
            reply = ("err", blob)
        try:
            conn.send_bytes(serialization.dumps(reply))
        except (EOFError, OSError, BrokenPipeError):
            return


def _handle(runtime, kind: str, payload: tuple, state: dict = None) -> Any:
    if kind == "submit_task":
        return runtime.submit_task(serialization.loads(payload[0]))
    if kind == "submit_actor_task":
        return runtime.submit_actor_task(payload[0],
                                         serialization.loads(payload[1]))
    if kind in ("submit_task_gen", "submit_actor_task_gen"):
        # Streaming submission: host the driver-side ObjectRefGenerator,
        # hand back a pull token (the worker iterates via gen_next).
        import uuid

        if state is None:
            raise RuntimeError("streaming submission needs per-connection "
                               "state (gen tokens)")
        if kind == "submit_task_gen":
            gen = runtime.submit_task(serialization.loads(payload[0]))
        else:
            gen = runtime.submit_actor_task(
                payload[0], serialization.loads(payload[1]))
        token = uuid.uuid4().hex[:16]
        # refs: driver-side handles for yielded items, holding them alive
        # until the STREAM ends (not the connection — a long-lived worker
        # must not pin every item it ever streamed).
        state.setdefault("gens", {})[token] = {"gen": gen, "refs": []}
        return token
    if kind == "gen_next":
        entry = (state or {}).get("gens", {}).get(payload[0])
        if entry is None:
            raise ValueError(f"unknown or finished generator {payload[0]!r}")
        try:
            ref = next(entry["gen"])
            entry["refs"].append(ref)
            return ("item", ref)
        except StopIteration:
            state["gens"].pop(payload[0], None)
            return ("done", None)
        except BaseException:
            state["gens"].pop(payload[0], None)
            raise
    if kind == "gen_cancel":
        (state or {}).get("gens", {}).pop(payload[0], None)
        return None
    if kind == "create_actor":
        return runtime.create_actor(serialization.loads(payload[0]))
    if kind == "put":
        return runtime.put(serialization.loads(payload[0]))
    if kind == "get":
        return runtime.get(serialization.loads(payload[0]), timeout=payload[1])
    if kind == "wait":
        refs = serialization.loads(payload[0])
        ready, rest = runtime.wait(refs, num_returns=payload[1],
                                   timeout=payload[2])
        ready_ids = {r.id for r in ready}
        ready_idx = [i for i, r in enumerate(refs) if r.id in ready_ids]
        rest_idx = [i for i, r in enumerate(refs) if r.id not in ready_ids]
        return ready_idx, rest_idx
    if kind == "kill_actor":
        return runtime.kill_actor(payload[0], no_restart=payload[1])
    if kind == "cancel":
        return runtime.cancel(serialization.loads(payload[0]), force=payload[1])
    if kind == "get_named_actor":
        return runtime.get_named_actor(payload[0], payload[1])
    if kind == "cluster_resources":
        return runtime.cluster_resources()
    if kind == "available_resources":
        return runtime.available_resources()
    if kind == "nodes":
        return runtime.nodes()
    if kind == "list_task_events":
        return runtime.list_task_events()
    if kind == "internal_kv":
        # Runs in the head process, where _remote_call() is None, so these
        # hit the head's real store (no recursion).
        from ray_tpu.experimental import internal_kv as kv

        op = payload[0]
        if op == "get":
            return kv._internal_kv_get(payload[1], namespace=payload[2])
        if op == "put":
            return kv._internal_kv_put(payload[1], payload[2],
                                       overwrite=payload[3],
                                       namespace=payload[4])
        if op == "del":
            return kv._internal_kv_del(payload[1], namespace=payload[2])
        if op == "exists":
            return kv._internal_kv_exists(payload[1], namespace=payload[2])
        if op == "list":
            return kv._internal_kv_list(payload[1], namespace=payload[2])
        raise ValueError(f"unknown internal_kv op: {op!r}")
    if kind == "actor_info":
        state = runtime.get_actor_state(payload[0])
        if state is None:
            raise ValueError(f"unknown actor {payload[0]}")
        return state.spec.cls, state.spec.max_task_retries, state.state
    raise ValueError(f"unknown nested-API request: {kind!r}")
