"""Validation/resolution of @remote options (ref: python/ray/_private/ray_option_utils.py)."""

from __future__ import annotations

from typing import Any, Dict

_COMMON_KEYS = {
    "num_cpus", "num_tpus", "num_gpus", "resources", "scheduling_strategy",
    "name", "runtime_env", "isolation", "_metadata",
}
_TASK_KEYS = _COMMON_KEYS | {"num_returns", "max_retries", "retry_exceptions"}
_ACTOR_KEYS = _COMMON_KEYS | {
    "max_restarts", "max_task_retries", "max_concurrency", "lifetime",
    "namespace", "max_pending_calls", "concurrency_groups",
}


def resolve_task_options(options: Dict[str, Any], is_actor: bool) -> Dict[str, Any]:
    allowed = _ACTOR_KEYS if is_actor else _TASK_KEYS
    unknown = set(options) - allowed
    if unknown:
        raise ValueError(f"Unknown options {sorted(unknown)}; allowed: {sorted(allowed)}")

    resources: Dict[str, float] = dict(options.get("resources") or {})
    if "num_cpus" in options and options["num_cpus"] is not None:
        if "CPU" in resources and float(options["num_cpus"]) != resources["CPU"]:
            raise ValueError(
                "Specify CPU either via num_cpus or resources={'CPU': ...}, not "
                "both (they conflict).")
        resources["CPU"] = float(options["num_cpus"])
    else:
        # Tasks default to 1 CPU; actors to 0 (they hold placement, not cores)
        # — matches the reference's defaults.
        resources.setdefault("CPU", 0.0 if is_actor else 1.0)
    # num_gpus accepted as an alias for TPU chips to ease porting.
    chips = options.get("num_tpus", options.get("num_gpus"))
    if chips is not None:
        resources["TPU"] = float(chips)
    if resources.get("CPU") == 0.0:
        resources.pop("CPU")

    out: Dict[str, Any] = {
        "resources": resources,
        "scheduling_strategy": options.get("scheduling_strategy"),
        "name": options.get("name"),
        "runtime_env": options.get("runtime_env"),
        "isolation": options.get("isolation", "thread"),
    }
    if out["isolation"] not in ("thread", "process"):
        raise ValueError("isolation must be 'thread' or 'process'")
    if is_actor:
        out["max_restarts"] = int(options.get("max_restarts", 0))
        out["max_task_retries"] = int(options.get("max_task_retries", 0))
        out["max_concurrency"] = int(options.get("max_concurrency", 1))
        out["lifetime"] = options.get("lifetime")
        out["namespace"] = options.get("namespace")
        out["concurrency_groups"] = options.get("concurrency_groups")
    else:
        nr = options.get("num_returns", 1)
        if not (isinstance(nr, int) and nr >= 0) and nr not in ("dynamic", "streaming"):
            raise ValueError(f"Invalid num_returns: {nr}")
        out["num_returns"] = nr
        from ray_tpu._private.config import GLOBAL_CONFIG

        mr = options.get("max_retries")
        out["max_retries"] = GLOBAL_CONFIG.task_max_retries if mr is None else int(mr)
        out["retry_exceptions"] = bool(options.get("retry_exceptions", False))
    return out
