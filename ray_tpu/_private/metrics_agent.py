"""Per-node metrics agent: runtime gauges + /metrics Prometheus endpoint.

Counterpart of the reference's `MetricsAgent` (ref: _private/metrics_agent.py:483
+ _private/prometheus_exporter.py): samples the runtime's internal state into
gauges (the role of the C++ `stats/metric_defs.cc` core metrics) and serves
the whole registry — internal + user metrics (util/metrics.py) — over HTTP in
Prometheus text format.  One agent per runtime, started on demand.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ray_tpu.util import metrics as um

_INTERNAL: Optional[dict] = None
_LOCK = threading.Lock()


def _internal_gauges() -> dict:
    global _INTERNAL
    with _LOCK:
        if _INTERNAL is None:
            _INTERNAL = {
                "tasks_finished": um.Counter(
                    "ray_tpu_tasks_finished_total", "tasks finished OK"),
                "tasks_failed": um.Counter(
                    "ray_tpu_tasks_failed_total", "tasks failed"),
                "object_store_bytes": um.Gauge(
                    "ray_tpu_object_store_bytes", "bytes in the object store"),
                "object_store_capacity": um.Gauge(
                    "ray_tpu_object_store_capacity_bytes", "store capacity"),
                "objects": um.Gauge(
                    "ray_tpu_objects", "objects tracked", ("state",)),
                "actors": um.Gauge(
                    "ray_tpu_actors", "actors by state", ("state",)),
                "pending_tasks": um.Gauge(
                    "ray_tpu_pending_tasks", "tasks waiting for dispatch"),
                "nodes": um.Gauge("ray_tpu_nodes", "cluster nodes"),
            }
        return _INTERNAL


def record_task_finished(ok: bool) -> None:
    g = _internal_gauges()
    (g["tasks_finished"] if ok else g["tasks_failed"]).inc()


def sample_runtime(runtime) -> None:
    """Refresh the internal gauges from live runtime state."""
    g = _internal_gauges()
    used, cap = runtime.store.usage()
    g["object_store_bytes"].set(used)
    g["object_store_capacity"].set(cap)
    by_state: dict = {}
    for info in runtime.store.object_summaries():
        by_state[info["state"]] = by_state.get(info["state"], 0) + 1
    g["objects"].clear()  # states whose count dropped to 0 must not linger
    for state, n in by_state.items():
        g["objects"].set(n, {"state": state})
    actor_states: dict = {}
    for a in runtime.list_actor_states():
        actor_states[a["state"]] = actor_states.get(a["state"], 0) + 1
    g["actors"].clear()
    for state, n in actor_states.items():
        g["actors"].set(n, {"state": state})
    g["pending_tasks"].set(len(runtime._inflight))
    g["nodes"].set(len(runtime.scheduler.nodes()))


class MetricsAgent:
    """HTTP scrape endpoint (GET /metrics) over the process registry."""

    def __init__(self, runtime, port: int = 0, host: str = "127.0.0.1"):
        self._runtime = runtime

        agent = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, body: bytes, ctype: str) -> None:
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                """Routes: /metrics (Prometheus), /api/* (state API JSON —
                the REST aggregation tier, ref: dashboard/head.py:65 +
                modules/state/state_head.py:47), / (HTML status page)."""
                import json as _json

                path = self.path.split("?")[0].rstrip("/")
                try:
                    if path == "/metrics":
                        sample_runtime(agent._runtime)
                        self._send(um.registry().prometheus_text().encode(),
                                   "text/plain; version=0.0.4; charset=utf-8")
                        return
                    if path == "/timeseries":
                        # Sliding-window rollups (util/metrics_agent.py):
                        # each scrape samples the registry into the process
                        # aggregator, so the window fills at scrape cadence.
                        sample_runtime(agent._runtime)
                        from ray_tpu.util.metrics_agent import get_aggregator

                        agg = get_aggregator()
                        agg.sample_registry()
                        self._send(
                            agg.openmetrics_text().encode(),
                            "application/openmetrics-text; version=1.0.0; "
                            "charset=utf-8")
                        return
                    if path.startswith("/api"):
                        payload = _api_payload(agent._runtime, path)
                        if payload is None:
                            self.send_error(404)
                            return
                        self._send(_json.dumps(payload, default=str).encode(),
                                   "application/json")
                        return
                    if path == "":
                        self._send(_status_page(agent._runtime).encode(),
                                   "text/html; charset=utf-8")
                        return
                    if path.startswith("/node/"):
                        body = _node_page(agent._runtime,
                                          path[len("/node/"):])
                        if body is None:
                            self.send_error(404)
                            return
                        self._send(body.encode(), "text/html; charset=utf-8")
                        return
                    self.send_error(404)
                except Exception as e:  # a scrape must never kill the server
                    self.send_error(500, str(e))

            def log_message(self, *a):  # quiet
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="ray_tpu_metrics_agent",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def _log_tails(limit_files: int = 3, tail_bytes: int = 1200) -> dict:
    """Last bytes of the newest session log files (the drilldown's log
    view; ref: dashboard log endpoints + _private/log_monitor.py)."""
    import os

    try:
        from ray_tpu._private.log_monitor import log_dir

        d = log_dir()
        files = sorted(
            (os.path.join(d, f) for f in os.listdir(d) if f.endswith(".log")),
            key=os.path.getmtime, reverse=True)[:limit_files]
    except Exception:
        return {}
    tails = {}
    for path in files:
        try:
            with open(path, "rb") as f:
                f.seek(max(0, os.path.getsize(path) - tail_bytes))
                tails[os.path.basename(path)] = f.read().decode(
                    errors="replace")
        except OSError:
            continue
    return tails


def _local_actor_states(runtime) -> list:
    """Actors HOSTED BY this runtime: the head's ledger also tracks actors
    it forwarded to worker nodes — counting those on the head's own row
    would double-count them against the hosting node's report."""
    local_id = str(runtime.head_node_id)
    return [a for a in runtime.list_actor_states()
            if a.get("node_id") in ("", local_id)]


def runtime_summary(runtime) -> dict:
    """The cheap per-runtime row (no log I/O, no object listing) — what the
    cluster table needs on its 5-second refresh hot path."""
    import os

    used, cap = runtime.store.usage()
    return {
        "pid": os.getpid(),
        "store_bytes_used": used,
        "store_capacity_bytes": cap,
        "actors": _local_actor_states(runtime),
        "num_running_tasks": len(runtime._running),
        "num_inflight_tasks": len(runtime._inflight),
        "serve_totals": _serve_totals(),
    }


def _serve_totals() -> dict:
    """Per-deployment request/error totals seen by this process's serve
    routers — {} when serve was never imported (the import is the signal:
    no serve module, no serve metrics)."""
    import sys

    if "ray_tpu.serve.metrics" not in sys.modules:
        return {}
    try:
        return sys.modules["ray_tpu.serve.metrics"].process_totals()
    except Exception:
        return {}


def runtime_snapshot(runtime) -> dict:
    """One runtime's FULL live state — served by worker nodes over info_req
    and by the head for its drilldown page (the per-node agent report the
    aggregation tier collects; ref: dashboard/head.py:65 + reporter
    agent)."""
    import threading as _threading

    snap = runtime_summary(runtime)
    snap.update({
        "num_objects": len(runtime.store.object_summaries()),
        "num_threads": _threading.active_count(),
        "log_tail": _log_tails(),
    })
    return snap


# Node-detail fetches are bounded by a semaphore (max 8 concurrent daemon
# threads, cluster-wide) with a short-TTL cache per runtime: the dashboard
# page auto-refreshes every 5 s per viewer, and a wedged node's info_req
# blocks ~3 s — a thread per node per request accumulated threads under
# concurrent viewers on large clusters.  Daemon threads (not a pool) so
# wedged fetches never block interpreter exit nor queue unboundedly: when
# all 8 slots are taken a node's detail is simply omitted this round.
import threading as _snap_threading
import weakref as _snap_weakref

_SNAP_BUDGET = _snap_threading.Semaphore(8)
_SNAP_DEADLINE_S = 5.0  # hard per-round deadline on node_info fan-out
_SNAP_CACHE: "_snap_weakref.WeakKeyDictionary" = \
    _snap_weakref.WeakKeyDictionary()  # runtime -> (expires, details)
_SNAP_INFLIGHT: "_snap_weakref.WeakKeyDictionary" = \
    _snap_weakref.WeakKeyDictionary()  # runtime -> {node_id: fetch wedged}
_SNAP_LOCK = _snap_threading.Lock()


def _release_token():
    """One-shot semaphore release shared between a fetch thread and the
    round's deadline sweep: whoever fires first releases the slot, the
    other call is a no-op.  Without this, a node_info wedged in conn.send
    (full pipe to a stalled node — the ONLY unbounded block in that stack;
    the reply wait is Event-bounded) held its slot forever, and 8 wedged
    nodes silently zeroed the dashboard's node-detail budget for the rest
    of the process lifetime."""
    once = _snap_threading.Lock()

    def release():
        if once.acquire(blocking=False):
            _SNAP_BUDGET.release()

    return release


def _node_details(runtime, remote) -> dict:
    import threading as _threading
    import time as _time

    now = _time.monotonic()
    with _SNAP_LOCK:
        ent = _SNAP_CACHE.get(runtime)
        if ent is not None and ent[0] > now:
            return ent[1]
        inflight = _SNAP_INFLIGHT.setdefault(runtime, set())

    details: dict = {}

    def fetch(nid, rn, release):
        try:
            details[nid] = runtime.node_server.node_info(rn, detail="summary")
        except Exception as e:  # noqa: BLE001
            details[nid] = {"error": repr(e)}
        finally:
            release()
            with _SNAP_LOCK:
                inflight.discard(nid)

    threads = []
    for nid, rn in remote.items():
        with _SNAP_LOCK:
            if nid in inflight:
                # A previous round's fetch never returned: don't stack a
                # second thread behind the same wedged node.
                details[nid] = {"error": "previous info fetch still wedged"}
                continue
        if not _SNAP_BUDGET.acquire(blocking=False):
            break  # every slot wedged on slow nodes: omit the rest
        release = _release_token()
        with _SNAP_LOCK:
            inflight.add(nid)  # BEFORE start: a fast fetch must not discard
        try:                   # first and leave a phantom inflight entry
            t = _threading.Thread(target=fetch, args=(nid, rn, release),
                                  name="dash-snap", daemon=True)
            t.start()
        except RuntimeError:
            release()  # start failed: fetch's finally never runs
            with _SNAP_LOCK:
                inflight.discard(nid)
            break
        threads.append((t, release))
    deadline = _time.monotonic() + _SNAP_DEADLINE_S
    for t, release in threads:
        t.join(timeout=max(0.0, deadline - _time.monotonic()))
        if t.is_alive():
            # Hard deadline: reclaim the slot NOW (the fetch's own release
            # becomes a no-op).  The node stays marked inflight until its
            # thread actually finishes, so later rounds skip it instead of
            # leaking one thread per refresh.
            release()
    if threads:
        # Never cache a zero-fetch round: a concurrent miss that lost every
        # semaphore slot must not overwrite a just-cached complete snapshot
        # with {} for the whole TTL.
        with _SNAP_LOCK:
            _SNAP_CACHE[runtime] = (_time.monotonic() + 2.0, details)
    return details


def cluster_snapshot(runtime, with_details: bool = True) -> dict:
    """Aggregate the whole cluster: the head's scheduler/ledger view joined
    with each node's own agent report (ref: dashboard/head.py:65 — the
    aggregating head the per-runtime REST tier lacked)."""
    import time as _time

    head_id = str(runtime.head_node_id)
    remote = {str(n.node_id): n for n in runtime._remote_nodes_snapshot()}
    details: dict = {}
    if with_details and runtime.node_server is not None and remote:
        details = _node_details(runtime, remote)
    per_node = []
    for n in runtime.scheduler.nodes():
        nid = str(n.id)
        is_head = nid == head_id
        rn = remote.get(nid)
        detail = (runtime_summary(runtime) if is_head and with_details
                  else details.get(nid))
        row = {
            "node_id": nid,
            "is_head": is_head,
            "alive": n.alive,
            "resources": dict(n.total),
            "available": dict(n.available),
            "heartbeat_age_s": round(_time.monotonic() - rn.last_heartbeat, 1)
            if rn else None,
        }
        if detail:
            row.update({
                "pid": detail.get("pid"),
                "store_bytes_used": detail.get("store_bytes_used"),
                "num_actors": len(detail.get("actors") or []),
                "num_running_tasks": detail.get("num_running_tasks"),
            })
        per_node.append(row)
    return {
        "cluster_resources": runtime.scheduler.cluster_resources(),
        "available_resources": runtime.scheduler.available_resources(),
        "head_node_id": head_id,
        "per_node": per_node,
    }


def node_detail(runtime, node_id: str):
    """Full drilldown for one node (""/head id = the head runtime)."""
    if node_id in ("", str(runtime.head_node_id)):
        snap = runtime_snapshot(runtime)
        snap["node_id"] = str(runtime.head_node_id)
        return snap
    for rn in runtime._remote_nodes_snapshot():
        if str(rn.node_id) == node_id:
            if runtime.node_server is None:
                return None
            return runtime.node_server.node_info(rn)
    return None


def _api_payload(runtime, path: str):
    """REST views over the state API (ref: dashboard state_head.py:47 — the
    same rows `ray list ...` prints, as JSON over HTTP)."""
    from ray_tpu.util import state as state_api

    if path in ("/api", "/api/cluster"):
        payload = cluster_snapshot(runtime)
        payload.update({
            "nodes": len(payload["per_node"]),
            "tasks": state_api.summarize_tasks(),
            "actors": state_api.summarize_actors(),
        })
        return payload
    if path.startswith("/api/node/"):
        return node_detail(runtime, path[len("/api/node/"):])
    if path == "/api/serve":
        # Serve observability rollup (ref: dashboard serve head —
        # modules/serve/serve_head.py): controller state joined with the
        # routers' RED metric snapshots, one JSON document.
        return _serve_payload()
    if path == "/api/serve/slo":
        # One fresh watchdog evaluation per scrape: burn rates, alert
        # state and windows for every registered objective.
        from ray_tpu.serve import slo as _slo

        watchdog = _slo.get_watchdog()
        return {
            "objectives_registry": sorted(_slo.SLO_OBJECTIVES),
            "deployments": watchdog.evaluate(),
        }
    listings = {
        "/api/tasks": state_api.list_tasks,
        "/api/actors": state_api.list_actors,
        "/api/objects": state_api.list_objects,
        "/api/nodes": state_api.list_nodes,
        "/api/placement_groups": state_api.list_placement_groups,
        "/api/train_runs": state_api.list_train_runs,
        "/api/postmortems": state_api.list_postmortems,
    }
    fn = listings.get(path)
    if fn is not None:
        return fn()
    if path == "/api/postmortems/bundle":
        # Full cluster postmortem: every dump merged with the head's
        # recent time-series window and the run registry.
        from ray_tpu.util import forensics

        return forensics.build_bundle()
    if path.startswith("/api/postmortems/"):
        from ray_tpu.util import forensics

        return forensics.load_postmortem(path[len("/api/postmortems/"):])
    if path == "/api/stacks":
        # On-demand profiling (ref: dashboard reporter profile_manager.py:78
        # py-spy dumps; here sys._current_frames + SIGUSR1 faulthandler).
        from ray_tpu._private import stack_profiler

        return stack_profiler.collect_all_stacks()
    if path == "/api/memory":
        from ray_tpu._private import heap_profiler

        return heap_profiler.heap_summary()
    if path == "/api/jobs":
        from ray_tpu.job import job_manager as jm_mod

        mgr = jm_mod._MANAGER  # peek, never create on a GET
        if mgr is None:
            return []
        return [dict(job_id=j.job_id, status=j.status,
                     entrypoint=j.entrypoint, log_path=j.log_path)
                for j in mgr.list_jobs()]
    return None


def _serve_payload() -> dict:
    """Everything the serve dashboard view needs in one fetch: deployment
    rows (status + p50/p95/p99 rollups), replica FSM rows, applications."""
    from ray_tpu.util import state as state_api

    deployments = state_api.list_deployments()
    replicas = state_api.list_replicas()
    apps = sorted({d["app"] for d in deployments})
    return {
        "applications": apps,
        "num_deployments": len(deployments),
        "num_replicas": len(replicas),
        "deployments": deployments,
        "replicas": replicas,
    }


def _status_page(runtime) -> str:
    """Minimal live HTML status page (the dashboard UI floor).  Every
    interpolated value is escaped — actor/task NAMES are user input."""
    import html as _html

    from ray_tpu.util import state as state_api

    def esc(v) -> str:
        return _html.escape(str(v))

    def table(rows, cols):
        if not rows:
            return "<p><i>none</i></p>"
        head = "".join(f"<th>{esc(c)}</th>" for c in cols)
        body = "".join(
            "<tr>" + "".join(f"<td>{esc(r.get(c, ''))}</td>" for c in cols)
            + "</tr>"
            for r in rows[:100])
        return f"<table border=1 cellpadding=4><tr>{head}</tr>{body}</table>"

    snap = cluster_snapshot(runtime)
    actors = state_api.list_actors()
    tasks = state_api.list_tasks()[-50:]
    res = esc(snap["cluster_resources"])
    avail = esc(snap["available_resources"])
    node_rows = []
    for row in snap["per_node"]:
        nid = esc(row["node_id"])
        node_rows.append(
            f"<tr><td><a href=\"/node/{nid}\">{nid}</a></td>"
            f"<td>{'head' if row['is_head'] else 'worker'}</td>"
            f"<td>{esc(row['alive'])}</td>"
            f"<td>{esc(row['resources'])}</td>"
            f"<td>{esc(row['available'])}</td>"
            f"<td>{esc(row.get('num_actors', ''))}</td>"
            f"<td>{esc(row.get('store_bytes_used', ''))}</td>"
            f"<td>{esc(row.get('heartbeat_age_s', ''))}</td></tr>")
    nodes_table = (
        "<table border=1 cellpadding=4><tr><th>node</th><th>role</th>"
        "<th>alive</th><th>resources</th><th>available</th><th>actors</th>"
        "<th>store bytes</th><th>hb age s</th></tr>"
        + "".join(node_rows) + "</table>")
    return f"""<!doctype html><html><head><title>ray_tpu status</title>
<meta http-equiv="refresh" content="5"></head><body>
<h2>ray_tpu cluster</h2>
<p>resources: {res} &nbsp; available: {avail}</p>
<h3>nodes ({len(snap['per_node'])})</h3>{nodes_table}
<h3>actors ({len(actors)})</h3>
{table(actors, ["actor_id", "class_name", "state", "name", "num_restarts"])}
<h3>recent tasks</h3>
{table(tasks, ["task_id", "name", "state", "attempt"])}
<p><a href="/metrics">/metrics</a> &middot; <a href="/api/cluster">/api/cluster</a></p>
</body></html>"""


def _node_page(runtime, node_id: str):
    """Per-node drilldown: the node's own agent report rendered as HTML
    (ref: dashboard per-node view — modules/node/node_head.py)."""
    import html as _html

    try:
        detail = node_detail(runtime, node_id)
    except Exception as e:  # noqa: BLE001 — render the failure, not a 500
        detail = {"node_id": node_id, "error": repr(e)}
    if detail is None:
        return None

    def esc(v) -> str:
        return _html.escape(str(v))

    actors = detail.get("actors") or []
    actor_rows = "".join(
        "<tr>" + "".join(
            f"<td>{esc(a.get(c, ''))}</td>"
            for c in ("actor_id", "class_name", "state", "name"))
        + "</tr>" for a in actors) or "<tr><td colspan=4><i>none</i></td></tr>"
    logs = "".join(
        f"<h4>{esc(name)}</h4><pre>{esc(tail)}</pre>"
        for name, tail in (detail.get("log_tail") or {}).items())
    return f"""<!doctype html><html><head>
<title>node {esc(node_id)}</title></head><body>
<p><a href="/">&larr; cluster</a></p>
<h2>node {esc(detail.get('node_id', node_id))}</h2>
<p>pid: {esc(detail.get('pid', '?'))} &nbsp;
store: {esc(detail.get('store_bytes_used', '?'))} /
{esc(detail.get('store_capacity_bytes', '?'))} bytes &nbsp;
objects: {esc(detail.get('num_objects', '?'))} &nbsp;
running tasks: {esc(detail.get('num_running_tasks', '?'))} &nbsp;
threads: {esc(detail.get('num_threads', '?'))}</p>
{f"<p><b>error:</b> {esc(detail['error'])}</p>" if detail.get('error') else ''}
<h3>actors ({len(actors)})</h3>
<table border=1 cellpadding=4>
<tr><th>actor_id</th><th>class</th><th>state</th><th>name</th></tr>
{actor_rows}</table>
<h3>log tails</h3>{logs or '<p><i>none</i></p>'}
</body></html>"""
