"""Per-node metrics agent: runtime gauges + /metrics Prometheus endpoint.

Counterpart of the reference's `MetricsAgent` (ref: _private/metrics_agent.py:483
+ _private/prometheus_exporter.py): samples the runtime's internal state into
gauges (the role of the C++ `stats/metric_defs.cc` core metrics) and serves
the whole registry — internal + user metrics (util/metrics.py) — over HTTP in
Prometheus text format.  One agent per runtime, started on demand.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ray_tpu.util import metrics as um

_INTERNAL: Optional[dict] = None
_LOCK = threading.Lock()


def _internal_gauges() -> dict:
    global _INTERNAL
    with _LOCK:
        if _INTERNAL is None:
            _INTERNAL = {
                "tasks_finished": um.Counter(
                    "ray_tpu_tasks_finished_total", "tasks finished OK"),
                "tasks_failed": um.Counter(
                    "ray_tpu_tasks_failed_total", "tasks failed"),
                "object_store_bytes": um.Gauge(
                    "ray_tpu_object_store_bytes", "bytes in the object store"),
                "object_store_capacity": um.Gauge(
                    "ray_tpu_object_store_capacity_bytes", "store capacity"),
                "objects": um.Gauge(
                    "ray_tpu_objects", "objects tracked", ("state",)),
                "actors": um.Gauge(
                    "ray_tpu_actors", "actors by state", ("state",)),
                "pending_tasks": um.Gauge(
                    "ray_tpu_pending_tasks", "tasks waiting for dispatch"),
                "nodes": um.Gauge("ray_tpu_nodes", "cluster nodes"),
            }
        return _INTERNAL


def record_task_finished(ok: bool) -> None:
    g = _internal_gauges()
    (g["tasks_finished"] if ok else g["tasks_failed"]).inc()


def sample_runtime(runtime) -> None:
    """Refresh the internal gauges from live runtime state."""
    g = _internal_gauges()
    used, cap = runtime.store.usage()
    g["object_store_bytes"].set(used)
    g["object_store_capacity"].set(cap)
    by_state: dict = {}
    for info in runtime.store.object_summaries():
        by_state[info["state"]] = by_state.get(info["state"], 0) + 1
    g["objects"].clear()  # states whose count dropped to 0 must not linger
    for state, n in by_state.items():
        g["objects"].set(n, {"state": state})
    actor_states: dict = {}
    for a in runtime.list_actor_states():
        actor_states[a["state"]] = actor_states.get(a["state"], 0) + 1
    g["actors"].clear()
    for state, n in actor_states.items():
        g["actors"].set(n, {"state": state})
    g["pending_tasks"].set(len(runtime._inflight))
    g["nodes"].set(len(runtime.scheduler.nodes()))


class MetricsAgent:
    """HTTP scrape endpoint (GET /metrics) over the process registry."""

    def __init__(self, runtime, port: int = 0):
        self._runtime = runtime

        agent = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, body: bytes, ctype: str) -> None:
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                """Routes: /metrics (Prometheus), /api/* (state API JSON —
                the REST aggregation tier, ref: dashboard/head.py:65 +
                modules/state/state_head.py:47), / (HTML status page)."""
                import json as _json

                path = self.path.split("?")[0].rstrip("/")
                try:
                    if path == "/metrics":
                        sample_runtime(agent._runtime)
                        self._send(um.registry().prometheus_text().encode(),
                                   "text/plain; version=0.0.4; charset=utf-8")
                        return
                    if path.startswith("/api"):
                        payload = _api_payload(agent._runtime, path)
                        if payload is None:
                            self.send_error(404)
                            return
                        self._send(_json.dumps(payload, default=str).encode(),
                                   "application/json")
                        return
                    if path == "":
                        self._send(_status_page(agent._runtime).encode(),
                                   "text/html; charset=utf-8")
                        return
                    self.send_error(404)
                except Exception as e:  # a scrape must never kill the server
                    self.send_error(500, str(e))

            def log_message(self, *a):  # quiet
                pass

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="ray_tpu_metrics_agent",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def _api_payload(runtime, path: str):
    """REST views over the state API (ref: dashboard state_head.py:47 — the
    same rows `ray list ...` prints, as JSON over HTTP)."""
    from ray_tpu.util import state as state_api

    if path in ("/api", "/api/cluster"):
        return {
            "cluster_resources": runtime.scheduler.cluster_resources(),
            "available_resources": runtime.scheduler.available_resources(),
            "nodes": len(runtime.scheduler.nodes()),
            "tasks": state_api.summarize_tasks(),
            "actors": state_api.summarize_actors(),
        }
    listings = {
        "/api/tasks": state_api.list_tasks,
        "/api/actors": state_api.list_actors,
        "/api/objects": state_api.list_objects,
        "/api/nodes": state_api.list_nodes,
        "/api/placement_groups": state_api.list_placement_groups,
    }
    fn = listings.get(path)
    if fn is not None:
        return fn()
    if path == "/api/stacks":
        # On-demand profiling (ref: dashboard reporter profile_manager.py:78
        # py-spy dumps; here sys._current_frames + SIGUSR1 faulthandler).
        from ray_tpu._private import stack_profiler

        return stack_profiler.collect_all_stacks()
    if path == "/api/memory":
        from ray_tpu._private import heap_profiler

        return heap_profiler.heap_summary()
    if path == "/api/jobs":
        from ray_tpu.job import job_manager as jm_mod

        mgr = jm_mod._MANAGER  # peek, never create on a GET
        if mgr is None:
            return []
        return [dict(job_id=j.job_id, status=j.status,
                     entrypoint=j.entrypoint, log_path=j.log_path)
                for j in mgr.list_jobs()]
    return None


def _status_page(runtime) -> str:
    """Minimal live HTML status page (the dashboard UI floor).  Every
    interpolated value is escaped — actor/task NAMES are user input."""
    import html as _html

    from ray_tpu.util import state as state_api

    def esc(v) -> str:
        return _html.escape(str(v))

    def table(rows, cols):
        if not rows:
            return "<p><i>none</i></p>"
        head = "".join(f"<th>{esc(c)}</th>" for c in cols)
        body = "".join(
            "<tr>" + "".join(f"<td>{esc(r.get(c, ''))}</td>" for c in cols)
            + "</tr>"
            for r in rows[:100])
        return f"<table border=1 cellpadding=4><tr>{head}</tr>{body}</table>"

    nodes = state_api.list_nodes()
    actors = state_api.list_actors()
    tasks = state_api.list_tasks()[-50:]
    res = esc(runtime.scheduler.cluster_resources())
    avail = esc(runtime.scheduler.available_resources())
    return f"""<!doctype html><html><head><title>ray_tpu status</title>
<meta http-equiv="refresh" content="5"></head><body>
<h2>ray_tpu cluster</h2>
<p>resources: {res} &nbsp; available: {avail}</p>
<h3>nodes ({len(nodes)})</h3>{table(nodes, ["node_id", "alive", "resources"])}
<h3>actors ({len(actors)})</h3>
{table(actors, ["actor_id", "class_name", "state", "name", "num_restarts"])}
<h3>recent tasks</h3>
{table(tasks, ["task_id", "name", "state", "attempt"])}
<p><a href="/metrics">/metrics</a> &middot; <a href="/api/cluster">/api/cluster</a></p>
</body></html>"""
