"""Per-node metrics agent: runtime gauges + /metrics Prometheus endpoint.

Counterpart of the reference's `MetricsAgent` (ref: _private/metrics_agent.py:483
+ _private/prometheus_exporter.py): samples the runtime's internal state into
gauges (the role of the C++ `stats/metric_defs.cc` core metrics) and serves
the whole registry — internal + user metrics (util/metrics.py) — over HTTP in
Prometheus text format.  One agent per runtime, started on demand.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ray_tpu.util import metrics as um

_INTERNAL: Optional[dict] = None
_LOCK = threading.Lock()


def _internal_gauges() -> dict:
    global _INTERNAL
    with _LOCK:
        if _INTERNAL is None:
            _INTERNAL = {
                "tasks_finished": um.Counter(
                    "ray_tpu_tasks_finished_total", "tasks finished OK"),
                "tasks_failed": um.Counter(
                    "ray_tpu_tasks_failed_total", "tasks failed"),
                "object_store_bytes": um.Gauge(
                    "ray_tpu_object_store_bytes", "bytes in the object store"),
                "object_store_capacity": um.Gauge(
                    "ray_tpu_object_store_capacity_bytes", "store capacity"),
                "objects": um.Gauge(
                    "ray_tpu_objects", "objects tracked", ("state",)),
                "actors": um.Gauge(
                    "ray_tpu_actors", "actors by state", ("state",)),
                "pending_tasks": um.Gauge(
                    "ray_tpu_pending_tasks", "tasks waiting for dispatch"),
                "nodes": um.Gauge("ray_tpu_nodes", "cluster nodes"),
            }
        return _INTERNAL


def record_task_finished(ok: bool) -> None:
    g = _internal_gauges()
    (g["tasks_finished"] if ok else g["tasks_failed"]).inc()


def sample_runtime(runtime) -> None:
    """Refresh the internal gauges from live runtime state."""
    g = _internal_gauges()
    used, cap = runtime.store.usage()
    g["object_store_bytes"].set(used)
    g["object_store_capacity"].set(cap)
    by_state: dict = {}
    for info in runtime.store.object_summaries():
        by_state[info["state"]] = by_state.get(info["state"], 0) + 1
    g["objects"].clear()  # states whose count dropped to 0 must not linger
    for state, n in by_state.items():
        g["objects"].set(n, {"state": state})
    actor_states: dict = {}
    for a in runtime.list_actor_states():
        actor_states[a["state"]] = actor_states.get(a["state"], 0) + 1
    g["actors"].clear()
    for state, n in actor_states.items():
        g["actors"].set(n, {"state": state})
    g["pending_tasks"].set(len(runtime._inflight))
    g["nodes"].set(len(runtime.scheduler.nodes()))


class MetricsAgent:
    """HTTP scrape endpoint (GET /metrics) over the process registry."""

    def __init__(self, runtime, port: int = 0):
        self._runtime = runtime

        agent = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_error(404)
                    return
                try:
                    sample_runtime(agent._runtime)
                    body = um.registry().prometheus_text().encode()
                except Exception as e:  # scrape must never kill the server
                    self.send_error(500, str(e))
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="ray_tpu_metrics_agent",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
