"""Fault injection for chaos testing.

TPU-native analogue of the reference's RPC chaos layer
(ref: src/ray/rpc/rpc_chaos.h:22 RpcFailure driven by RAY_testing_rpc_failure,
ray_config_def.h:850-857 RAY_testing_asio_delay_us): internal operations
consult the injector at named failure points and probabilistically raise a
transient ``InjectedFailure`` (subclass of WorkerCrashedError, so the
runtime's retry machinery treats it as a system fault, not an app error) or
sleep an injected delay.

Enable via config (env RAY_TPU_TESTING_RPC_FAILURE or _system_config):
    testing_rpc_failure = "execute=0.3,process_exec=0.5:4,serve_route=0.1"
Each entry is <point>=<probability>[:<max_failures>]; max_failures caps how
many times the point fires (unbounded if omitted).  Delays:
    testing_delay_us = 500   # every CONFIGURED point sleeps 500us
(points with no spec entry skip the delay — unconfigured points on hot
paths must stay a cheap dict miss).

Every framework failure point is declared in :data:`FAULT_POINTS` below —
the canonical table cross-referenced by the static analyzer
(``scripts/analyze.py``, registry-consistency checker): a ``check("x")``
call site naming an undeclared point fails CI, as does a declared point no
call site consults.  Tests may still use ad-hoc points against a local
``FaultInjector`` instance; the registry governs call sites inside
``ray_tpu/`` only.

Deterministic across runs for a fixed RAY_TPU_TESTING_CHAOS_SEED.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, Optional, Tuple

from ray_tpu.exceptions import WorkerCrashedError


class InjectedFailure(WorkerCrashedError):
    """Raised by a chaos failure point (transient, retryable)."""


#: Canonical registry of framework failure points: name -> where it fires /
#: what failure it simulates.  The static analyzer enforces consistency both
#: ways (call site <-> registry); tests/chaos_utils.py and the chaos suites
#: pick points from this table.
FAULT_POINTS: Dict[str, str] = {
    # core runtime (tests/test_chaos.py)
    "execute": "task execution entry on the worker — generic task crash",
    "process_exec": "process-actor subprocess exec — actor process dies",
    # serve data/control plane (tests/test_serve_chaos.py)
    "serve_route": "router dispatch (handle/proxy -> replica pick)",
    "serve_replica_handle": "replica request entry (unary handle_request)",
    "serve_health_probe": "replica check_health (drives UNHEALTHY recovery)",
    "serve_long_poll": "controller listen_for_change (client must retry)",
    "serve_autoscale": "autoscaler apply site (controller _autoscale_tick, "
                       "before set_target_num) — an injected scale-decision "
                       "failure leaves the target unchanged; no replica is "
                       "started or drained",
    # checkpoint subsystem (tests/test_checkpoint_chaos.py)
    "ckpt_shard_write": "shard persist in the writer thread — kills a save "
                        "mid-flight; the pending step aborts",
    "ckpt_commit": "coordinator commit phase, before the atomic rename — "
                   "the step stays uncommitted, restore skips it",
    "ckpt_restore": "restore entry (restore_pytree) — retryable",
    # elastic training (tests/test_train_elastic.py, scripts/bench_elastic.py)
    "train_worker_run": "train worker step boundary (run entry + every "
                        "report()) — the elastic controller shrinks and "
                        "resumes",
    "preempt_node": "trainer controller tick — a whole worker-group node is "
                    "preempted (actors killed + node removed), simulating a "
                    "TPU slice vanishing",
    # llm inference engine (tests/test_serve_llm.py)
    "llm_block_alloc": "KV-block pool allocation — the scheduler's "
                       "preemption/backoff paths absorb the failure",
    "llm_kv_handoff": "prefill→decode KV-page import on the decode "
                      "replica — the frontend re-prefills on a survivor",
    "llm_spec_verify": "speculative-decode verify pass — draft KV pages "
                       "roll back and the stream degrades to plain "
                       "decoding for the step (no torn or duplicated "
                       "tokens)",
    "llm_kv_promote": "host/object-tier KV-page promotion back into the "
                      "device pool — the tier entry is restored and the "
                      "caller falls back to a byte-identical re-prefill",
    # crash forensics (tests/test_forensics.py)
    "forensics_dump": "flight-recorder postmortem dump entry — the dump "
                      "fails; every trigger site absorbs it (a forensics "
                      "failure must never worsen the failure being "
                      "recorded)",
    # streaming ingest (tests/test_data_ingest.py)
    "data_ingest_fetch": "block materialization in the ingest stream — the "
                         "fetch retries (bounded) before surfacing to the "
                         "training loop",
    "data_ingest_prefetch": "host->device batch transfer dispatch — retried "
                            "once before surfacing",
    # device telemetry (tests/test_device_telemetry.py)
    "device_telemetry_snapshot": "device-telemetry snapshot assembly — every "
                                 "embedding site (forensics bundle, "
                                 "serve.status, run registry) absorbs a "
                                 "telemetry failure rather than worsening "
                                 "the event being observed",
    # cluster autoscaler (tests/test_cluster_autoscaler.py)
    "cluster_autoscale": "cluster-autoscaler actuation (target change or "
                         "quarantine) — consulted BEFORE acting; an "
                         "injected failure leaves the cluster untouched",
}


class FaultInjector:
    def __init__(self, spec: str, delay_us: int = 0, seed: Optional[int] = None):
        #: point -> (probability, remaining_budget or None)
        self._points: Dict[str, Tuple[float, Optional[int]]] = {}  # guarded_by: _lock
        self._lock = threading.Lock()
        self._delay_us = delay_us
        if seed is None:
            seed = int(os.environ.get("RAY_TPU_TESTING_CHAOS_SEED", "0")) or None
        self._rng = random.Random(seed)
        for entry in filter(None, (e.strip() for e in spec.split(","))):
            point, _, rest = entry.partition("=")
            prob_s, _, budget_s = rest.partition(":")
            self._points[point.strip()] = (
                float(prob_s), int(budget_s) if budget_s else None)
        # The set of configured points is fixed after construction (budgets
        # decrement but entries never appear/disappear), so enabled-ness is
        # immutable — precompute it instead of reading _points unlocked on
        # every hot-path enabled check.
        self._enabled = bool(self._points) or self._delay_us > 0

    @property
    def enabled(self) -> bool:
        return self._enabled

    def fires(self, point: str) -> bool:
        """Evaluate a failure point (consumes budget when it fires).

        One locked read-evaluate-update; the injected delay applies only
        to CONFIGURED points (an unconfigured point on a hot path must
        stay a dict miss, not a sleep) and happens outside the lock so a
        slow point cannot serialize every other thread's evaluation.
        """
        fired = False
        configured = False
        with self._lock:
            entry = self._points.get(point)
            if entry is not None:
                configured = True
                prob, budget = entry
                if (budget is None or budget > 0) \
                        and self._rng.random() < prob:
                    fired = True
                    if budget is not None:
                        self._points[point] = (prob, budget - 1)
        if configured and self._delay_us:
            time.sleep(self._delay_us / 1e6)
        return fired

    def check(self, point: str) -> None:
        """Raise InjectedFailure if the point fires."""
        if self.fires(point):
            raise InjectedFailure(f"chaos: injected failure at '{point}'")


_injector: Optional[FaultInjector] = None  # guarded_by: _injector_lock
_injector_lock = threading.Lock()


def get_injector() -> FaultInjector:
    """The process-wide injector, built from GLOBAL_CONFIG on first use
    (rebuilt by reset_injector() after config changes)."""
    global _injector
    if _injector is None:
        with _injector_lock:
            if _injector is None:
                from ray_tpu._private.config import GLOBAL_CONFIG

                _injector = FaultInjector(GLOBAL_CONFIG.testing_rpc_failure,
                                          GLOBAL_CONFIG.testing_delay_us)
    return _injector


def reset_injector() -> None:
    global _injector
    with _injector_lock:
        _injector = None


def check(point: str) -> None:
    """Module-level convenience: no-op unless chaos is configured."""
    inj = get_injector()
    if inj.enabled:
        inj.check(point)
