"""In-process + shared-memory object store with spilling.

TPU-native analogue of the reference's two-tier store: the in-process
CoreWorkerMemoryStore for small/inline objects (ref: src/ray/core_worker/
store_provider/memory_store/memory_store.h:42) and the per-node plasma
shared-memory store for large ones (ref: src/ray/object_manager/plasma/
store.h:55).  Differences, by design:

* Thread workers share the driver's address space, so the primary tier holds
  the *deserialized* Python value — a zero-copy "plasma" for the common TPU
  case (jax.Array device buffers must never be pickled between processes
  anyway; they stay in HBM and move via ICI collectives, not the store).
* A shared-memory tier (`multiprocessing.shared_memory`) materializes the
  serialized form on demand when an object crosses a process boundary.
* Capacity pressure triggers LRU spilling of the serialized form to disk
  (ref: raylet/local_object_manager.h:41 spilling via IO workers; here an
  internal thread), restored transparently on access.
"""

from __future__ import annotations

import os
import threading
import time
from multiprocessing import shared_memory
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import serialization
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.ids import ObjectID


class ObjectState:
    PENDING = "PENDING"
    READY = "READY"
    SPILLED = "SPILLED"
    FAILED = "FAILED"
    FREED = "FREED"


class _Entry:
    __slots__ = (
        "state", "value", "has_value", "error", "shm", "spill_path",
        "size", "event", "pinned", "last_access", "owner",
    )

    def __init__(self) -> None:
        self.state = ObjectState.PENDING
        self.value: Any = None
        self.has_value = False
        self.error: Optional[BaseException] = None
        self.shm: Optional[shared_memory.SharedMemory] = None
        self.spill_path: Optional[str] = None
        self.size = 0
        self.event = threading.Event()
        self.pinned = 0
        self.last_access = 0.0
        self.owner = ""


class ObjectStore:
    def __init__(self, capacity_bytes: int = 0) -> None:
        self._entries: Dict[ObjectID, _Entry] = {}
        self._lock = threading.RLock()
        self._bytes_used = 0
        if capacity_bytes <= 0:
            try:
                import psutil

                capacity_bytes = int(psutil.virtual_memory().total * 0.3)
            except Exception:
                capacity_bytes = 2 << 30
        self.capacity_bytes = capacity_bytes
        os.makedirs(GLOBAL_CONFIG.spill_dir, exist_ok=True)
        self.stats = {"puts": 0, "gets": 0, "spills": 0, "restores": 0, "freed": 0}
        self._graveyard: List[shared_memory.SharedMemory] = []

    # ------------------------------------------------------------------ puts
    def put(self, object_id: ObjectID, value: Any, owner: str = "") -> None:
        """Store a ready value (thread-tier: no serialization)."""
        with self._lock:
            entry = self._entries.setdefault(object_id, _Entry())
            entry.value = value
            entry.has_value = True
            entry.state = ObjectState.READY
            entry.owner = owner
            entry.last_access = time.monotonic()
            self.stats["puts"] += 1
        entry.event.set()

    def put_serialized(self, object_id: ObjectID, flat: bytes, owner: str = "") -> None:
        """Store an object already in wire form (arrived from a process worker)."""
        with self._lock:
            entry = self._entries.setdefault(object_id, _Entry())
            self._attach_shm(object_id, entry, flat)
            entry.state = ObjectState.READY
            entry.owner = owner
            self.stats["puts"] += 1
        entry.event.set()

    def put_error(self, object_id: ObjectID, error: BaseException) -> None:
        with self._lock:
            entry = self._entries.setdefault(object_id, _Entry())
            entry.error = error
            entry.state = ObjectState.FAILED
        entry.event.set()

    # ------------------------------------------------------------------ gets
    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            return e is not None and e.state in (ObjectState.READY, ObjectState.SPILLED, ObjectState.FAILED)

    def wait_ready(self, object_id: ObjectID, timeout: Optional[float]) -> bool:
        entry = self._ensure(object_id)
        return entry.event.wait(timeout)

    def get(self, object_id: ObjectID, timeout: Optional[float] = None) -> Any:
        """Blocking get of the deserialized value; raises stored errors."""
        entry = self._ensure(object_id)
        if not entry.event.wait(timeout):
            from ray_tpu.exceptions import GetTimeoutError

            raise GetTimeoutError(f"Timed out getting object {object_id}")
        return self._materialize(object_id, entry)

    def get_error(self, object_id: ObjectID) -> Optional[BaseException]:
        with self._lock:
            e = self._entries.get(object_id)
            return e.error if e else None

    def _materialize(self, object_id: ObjectID, entry: _Entry) -> Any:
        with self._lock:
            entry.last_access = time.monotonic()
            self.stats["gets"] += 1
            if entry.state == ObjectState.FAILED:
                raise entry.error  # type: ignore[misc]
            if entry.state == ObjectState.FREED:
                from ray_tpu.exceptions import ObjectFreedError

                raise ObjectFreedError(f"Object {object_id} was freed")
            if entry.has_value:
                return entry.value
            if entry.shm is not None:
                value = serialization.deserialize_flat(memoryview(entry.shm.buf))
                entry.value, entry.has_value = value, True
                return value
            if entry.spill_path is not None:
                self.stats["restores"] += 1
                with open(entry.spill_path, "rb") as f:
                    flat = f.read()
                value = serialization.deserialize_flat(memoryview(flat))
                entry.value, entry.has_value = value, True
                entry.state = ObjectState.READY
                return value
            from ray_tpu.exceptions import ObjectLostError

            raise ObjectLostError(f"Object {object_id} has no value")

    def get_serialized(self, object_id: ObjectID, timeout: Optional[float] = None) -> memoryview:
        """Wire form for shipping to a process worker (shm-backed, zero-copy)."""
        entry = self._ensure(object_id)
        if not entry.event.wait(timeout):
            from ray_tpu.exceptions import GetTimeoutError

            raise GetTimeoutError(f"Timed out getting object {object_id}")
        with self._lock:
            if entry.state == ObjectState.FAILED:
                raise entry.error  # type: ignore[misc]
            if entry.shm is None and entry.spill_path is None:
                flat = serialization.serialize(entry.value).to_bytes()
                self._attach_shm(object_id, entry, flat)
            if entry.shm is not None:
                return memoryview(entry.shm.buf)[: entry.size]
            with open(entry.spill_path, "rb") as f:  # type: ignore[arg-type]
                return memoryview(f.read())

    def shm_name(self, object_id: ObjectID) -> Optional[str]:
        with self._lock:
            e = self._entries.get(object_id)
            return e.shm.name if e and e.shm is not None else None

    # --------------------------------------------------------------- lifecycle
    def _ensure(self, object_id: ObjectID) -> _Entry:
        with self._lock:
            return self._entries.setdefault(object_id, _Entry())

    def _attach_shm(self, object_id: ObjectID, entry: _Entry, flat: bytes) -> None:
        size = len(flat)
        self._maybe_spill(size)
        try:
            shm = shared_memory.SharedMemory(create=True, size=max(size, 1))
        except Exception:
            # shm exhausted: keep in heap via spill file instead.
            path = os.path.join(GLOBAL_CONFIG.spill_dir, f"{object_id}.bin".replace(":", "_"))
            with open(path, "wb") as f:
                f.write(flat)
            entry.spill_path = path
            entry.size = size
            return
        shm.buf[:size] = flat
        entry.shm = shm
        entry.size = size
        self._bytes_used += size

    def _maybe_spill(self, incoming: int) -> None:
        """LRU-spill serialized objects when over threshold (caller holds lock)."""
        threshold = self.capacity_bytes * GLOBAL_CONFIG.object_spilling_threshold
        if self._bytes_used + incoming <= threshold:
            return
        candidates = sorted(
            (
                (e.last_access, oid, e)
                for oid, e in self._entries.items()
                if e.shm is not None and not e.pinned
            ),
        )
        for _, oid, entry in candidates:
            if self._bytes_used + incoming <= threshold:
                break
            path = os.path.join(GLOBAL_CONFIG.spill_dir, f"{oid}.bin".replace(":", "_"))
            with open(path, "wb") as f:
                f.write(bytes(entry.shm.buf[: entry.size]))
            self._release_shm(entry)
            entry.spill_path = path
            entry.state = ObjectState.SPILLED
            self.stats["spills"] += 1

    def _release_shm(self, entry: _Entry) -> None:
        if entry.shm is not None:
            self._bytes_used -= entry.size
            try:
                entry.shm.unlink()
            except Exception:
                pass
            try:
                entry.shm.close()
            except BufferError:
                # Zero-copy views into this segment are still alive (numpy
                # arrays deserialized out-of-band).  The mapping stays valid
                # until the views die; park the handle so its __del__ doesn't
                # raise, and retry at shutdown.
                self._graveyard.append(entry.shm)
            except Exception:
                pass
            entry.shm = None

    def pin(self, object_id: ObjectID) -> None:
        with self._lock:
            self._ensure(object_id).pinned += 1

    def unpin(self, object_id: ObjectID) -> None:
        with self._lock:
            e = self._entries.get(object_id)
            if e:
                e.pinned = max(0, e.pinned - 1)

    def free(self, object_id: ObjectID) -> None:
        """Called when the distributed refcount hits zero."""
        with self._lock:
            entry = self._entries.pop(object_id, None)
            if entry is None:
                return
            self._release_shm(entry)
            if entry.spill_path:
                try:
                    os.unlink(entry.spill_path)
                except OSError:
                    pass
            entry.state = ObjectState.FREED
            entry.value = None
            self.stats["freed"] += 1

    def evict_value(self, object_id: ObjectID) -> None:
        """Drop the deserialized copy, keep wire form (tests/memory pressure)."""
        with self._lock:
            e = self._entries.get(object_id)
            if e and (e.shm is not None or e.spill_path):
                e.value, e.has_value = None, False

    def shutdown(self) -> None:
        import gc

        with self._lock:
            for entry in self._entries.values():
                self._release_shm(entry)
            self._entries.clear()
        gc.collect()
        for shm in self._graveyard:
            try:
                shm.close()
            except Exception:
                pass
        self._graveyard.clear()

    def usage(self) -> Tuple[int, int]:
        with self._lock:
            return self._bytes_used, self.capacity_bytes
