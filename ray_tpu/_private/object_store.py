"""In-process + shared-memory object store with spilling.

TPU-native analogue of the reference's two-tier store: the in-process
CoreWorkerMemoryStore for small/inline objects (ref: src/ray/core_worker/
store_provider/memory_store/memory_store.h:42) and the per-node plasma
shared-memory store for large ones (ref: src/ray/object_manager/plasma/
store.h:55).  Differences, by design:

* Thread workers share the driver's address space, so the primary tier holds
  the *deserialized* Python value — a zero-copy "plasma" for the common TPU
  case (jax.Array device buffers must never be pickled between processes
  anyway; they stay in HBM and move via ICI collectives, not the store).
* The serialized tier is the native C++ arena (``ray_tpu/native/src/
  plasma.cc`` — mmap'd shared memory, boundary-tag allocator, LRU eviction),
  shared zero-copy with process-tier workers.  If the native library cannot
  build, `multiprocessing.shared_memory` is the fallback.
* Capacity pressure triggers LRU spilling of the serialized form to disk
  (ref: raylet/local_object_manager.h:41 spilling via IO workers), restored
  transparently on access.
"""

from __future__ import annotations

import os
import threading
import time
from multiprocessing import shared_memory
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu._private import serialization
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.ids import ObjectID


class ObjectState:
    PENDING = "PENDING"
    READY = "READY"
    SPILLED = "SPILLED"
    FAILED = "FAILED"
    FREED = "FREED"


class _Entry:
    __slots__ = (
        "state", "value", "has_value", "error", "shm", "in_plasma", "exported",
        "spill_path", "size", "event", "pinned", "last_access", "owner",
        "backup_flat",
    )

    def __init__(self) -> None:
        self.state = ObjectState.PENDING
        self.value: Any = None
        self.has_value = False
        self.error: Optional[BaseException] = None
        self.shm: Optional[shared_memory.SharedMemory] = None
        self.in_plasma = False
        self.exported = False  # zero-copy views into the arena were handed out
        self.spill_path: Optional[str] = None
        self.size = 0
        # Lazy: most objects are put before anyone blocks on them, and a
        # threading.Event costs two Condition allocations — measurable at
        # task-throughput rates.  Waiters create it via _wait_entry.
        self.event: Optional[threading.Event] = None
        self.pinned = 0
        self.last_access = 0.0
        self.owner = ""
        #: Duplicate wire bytes that arrived while a zero-copy landing of
        #: the same object was mid-flight; promoted by abort(), cleared by
        #: commit() — so an acknowledged duplicate can never be lost.
        self.backup_flat = None


_ARENA_SEQ = [0]


def _sweep_dead_arenas() -> None:
    """Unlink arena files left by hard-killed processes (the path embeds
    the owning pid; a dead pid means nobody can map it again).  Keeps
    /dev/shm from filling with orphans across chaos tests / node kills."""
    import glob
    import re

    for root in ("/dev/shm", "/tmp"):
        for path in glob.glob(os.path.join(root, "tpu_plasma_*")):
            m = re.match(r"tpu_plasma_(\d+)_", os.path.basename(path))
            if not m:
                continue
            pid = int(m.group(1))
            if pid == os.getpid():
                continue
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            except PermissionError:
                pass  # pid alive under another uid


def _try_plasma(capacity_bytes: int):
    """Build + create the native arena; None if the toolchain is missing.

    The path carries pid + a per-process sequence number so two stores in
    one process (tests, in-process multi-runtime) never unlink each
    other's arena file out from under the same-host handoff path."""
    try:
        from ray_tpu.native.plasma import PlasmaClient, default_arena_path

        if _ARENA_SEQ[0] == 0:  # once per process
            _sweep_dead_arenas()
        _ARENA_SEQ[0] += 1
        path = default_arena_path(
            f"{os.getpid()}_{threading.get_native_id()}_{_ARENA_SEQ[0]}")
        if os.path.exists(path):
            os.unlink(path)
        return PlasmaClient(path, capacity=capacity_bytes, create=True)
    except Exception:
        return None


class ObjectStore:
    def __init__(self, capacity_bytes: int = 0) -> None:
        self._entries: Dict[ObjectID, _Entry] = {}
        self._lock = threading.RLock()
        self._bytes_used = 0
        if capacity_bytes <= 0:
            try:
                import psutil

                capacity_bytes = int(psutil.virtual_memory().total * 0.3)
            except Exception:
                capacity_bytes = 2 << 30
        self.capacity_bytes = capacity_bytes
        os.makedirs(GLOBAL_CONFIG.spill_dir, exist_ok=True)
        self.stats = {"puts": 0, "gets": 0, "spills": 0, "restores": 0, "freed": 0}
        self._graveyard: List[shared_memory.SharedMemory] = []
        self._plasma_graveyard: Set[ObjectID] = set()
        self.plasma = _try_plasma(capacity_bytes)

    def _signal(self, entry: _Entry) -> None:
        """Wake waiters after a state transition (transition made under the
        lock; the event read here happens-after, so a waiter either saw the
        new state or had already published its event)."""
        ev = entry.event
        if ev is not None:
            ev.set()

    def _wait_entry(self, entry: _Entry, timeout: Optional[float]) -> bool:
        """Block until the entry leaves PENDING (True) or timeout (False)."""
        ev = entry.event
        if ev is None:
            with self._lock:
                if entry.state != ObjectState.PENDING:
                    return True
                ev = entry.event
                if ev is None:
                    ev = entry.event = threading.Event()
        return ev.wait(timeout)

    @property
    def arena_path(self) -> Optional[str]:
        """Path process workers attach to for zero-copy arg/result handoff."""
        return self.plasma.path if self.plasma is not None else None

    # ------------------------------------------------------------------ puts
    def put(self, object_id: ObjectID, value: Any, owner: str = "") -> None:
        """Store a ready value (thread-tier: no serialization)."""
        with self._lock:
            entry = self._entries.setdefault(object_id, _Entry())
            entry.value = value
            entry.has_value = True
            entry.state = ObjectState.READY
            entry.owner = owner
            entry.last_access = time.monotonic()
            self.stats["puts"] += 1
        self._signal(entry)

    def put_serialized(self, object_id: ObjectID, flat: bytes, owner: str = "") -> None:
        """Store an object already in wire form (arrived from a process worker)."""
        with self._lock:
            entry = self._entries.setdefault(object_id, _Entry())
            if entry.in_plasma and entry.state == ObjectState.PENDING:
                # A zero-copy landing (create_for_receive) of the same bytes
                # is mid-flight: its commit will seal and wake waiters —
                # attaching the duplicate now would mark the entry READY
                # while the arena object is still unsealed.  Park the bytes
                # so abort() can promote them if the landing dies (an
                # acknowledged delivery must never be lost).
                entry.backup_flat = bytes(flat)
                return
            self._attach_serialized(object_id, entry, flat)
            entry.state = ObjectState.READY
            entry.owner = owner
            self.stats["puts"] += 1
        self._signal(entry)

    def put_error(self, object_id: ObjectID, error: BaseException) -> None:
        with self._lock:
            entry = self._entries.setdefault(object_id, _Entry())
            entry.error = error
            entry.state = ObjectState.FAILED
        self._signal(entry)

    # ------------------------------------------------------------------ gets
    def size_of(self, object_id: ObjectID) -> int:
        """Recorded byte size of a stored object (0 if unknown/absent)."""
        with self._lock:
            e = self._entries.get(object_id)
            return e.size if e is not None else 0

    def size_hint(self, object_id: ObjectID) -> int:
        """Best-effort byte size WITHOUT serializing: the recorded size
        when known, else a cheap len/nbytes probe of a value-tier entry.
        The broadcast-tree gate needs this — a 1 GiB value put()'s size is
        otherwise unknown until its first pull serializes it, which would
        let every concurrent cold puller bypass the tree."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None:
                return 0
            if e.size:
                return e.size
            if not e.has_value:
                return 0
            v = e.value
        n = getattr(v, "nbytes", None)
        if isinstance(n, int):
            return n
        if isinstance(v, (bytes, bytearray, memoryview)):
            return len(v)
        return 0

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            return e is not None and e.state in (ObjectState.READY, ObjectState.SPILLED, ObjectState.FAILED)

    def contains_many(self, object_ids) -> List[bool]:
        """One lock pass over a batch (10k-arg calls would otherwise pay
        one lock round-trip per ref)."""
        resolved = (ObjectState.READY, ObjectState.SPILLED, ObjectState.FAILED)
        with self._lock:
            entries = self._entries
            out = []
            for oid in object_ids:
                e = entries.get(oid)
                out.append(e is not None and e.state in resolved)
            return out

    def state_of(self, object_id: ObjectID) -> Optional[str]:
        """Entry state without creating an entry (None = never seen)."""
        with self._lock:
            e = self._entries.get(object_id)
            return e.state if e is not None else None

    def wait_ready(self, object_id: ObjectID, timeout: Optional[float]) -> bool:
        entry = self._ensure(object_id)
        return self._wait_entry(entry, timeout)

    def get(self, object_id: ObjectID, timeout: Optional[float] = None) -> Any:
        """Blocking get of the deserialized value; raises stored errors."""
        entry = self._ensure(object_id)
        if not self._wait_entry(entry, timeout):
            from ray_tpu.exceptions import GetTimeoutError

            raise GetTimeoutError(f"Timed out getting object {object_id}")
        return self._materialize(object_id, entry)

    def try_get_many(self, object_ids) -> Tuple[List[Any], List[int]]:
        """Vectorized non-blocking get: ``(values, missing_indexes)``.

        One lock pass resolves every entry whose deserialized value is
        already in the primary tier (the overwhelmingly common in-process
        case); entries that need deserialization or a spill restore are
        materialized after the pass, and anything unresolved (pending,
        failed, freed, lost) is reported in ``missing_indexes`` for the
        caller's per-object slow path.  Never raises and never blocks —
        the slow path owns error/reconstruction semantics."""
        n = len(object_ids)
        values: List[Any] = [None] * n
        missing: List[int] = []
        slow: List[int] = []
        now = time.monotonic()
        with self._lock:
            entries = self._entries
            hits = 0
            for i in range(n):
                e = entries.get(object_ids[i])
                if e is not None and e.state == ObjectState.READY and e.has_value:
                    values[i] = e.value
                    e.last_access = now
                    hits += 1
                elif e is not None and e.state in (ObjectState.READY,
                                                   ObjectState.SPILLED):
                    slow.append(i)
                else:
                    missing.append(i)
            self.stats["gets"] += hits
        for i in slow:
            oid = object_ids[i]
            with self._lock:
                e = self._entries.get(oid)
            if e is None:
                missing.append(i)
                continue
            try:
                values[i] = self._materialize(oid, e)
            except BaseException:  # noqa: BLE001 — lost/freed mid-batch
                missing.append(i)
        if slow and missing:
            missing.sort()
        return values, missing

    def get_error(self, object_id: ObjectID) -> Optional[BaseException]:
        with self._lock:
            e = self._entries.get(object_id)
            return e.error if e else None

    def _serialized_view(self, object_id: ObjectID, entry: _Entry,
                         export: bool = False) -> Optional[memoryview]:
        """Wire-form view (zero-copy when in the arena). Caller holds lock.

        ``export=True`` marks the entry as aliased by long-lived zero-copy
        consumers (deserialized numpy views), pinning it against spilling;
        plain views are only valid until the next operation that may spill."""
        if entry.in_plasma and self.plasma is not None:
            view = self.plasma.get(object_id, timeout=0)
            if view is not None:
                # The store's own ref from create() pins the object; the extra
                # get() ref is returned immediately — the entry keeps it live.
                self.plasma.release(object_id)
                if export:
                    entry.exported = True
                return view[: entry.size]
        if entry.shm is not None:
            return memoryview(entry.shm.buf)[: entry.size]
        return None

    def _materialize(self, object_id: ObjectID, entry: _Entry) -> Any:
        with self._lock:
            entry.last_access = time.monotonic()
            self.stats["gets"] += 1
            if entry.state == ObjectState.FAILED:
                raise entry.error  # type: ignore[misc]
            if entry.state == ObjectState.FREED:
                from ray_tpu.exceptions import ObjectFreedError

                raise ObjectFreedError(f"Object {object_id} was freed")
            if entry.has_value:
                return entry.value
            view = self._serialized_view(object_id, entry, export=True)
            if view is not None:
                value = serialization.deserialize_flat(view)
                entry.value, entry.has_value = value, True
                return value
            if entry.spill_path is not None:
                self.stats["restores"] += 1
                with open(entry.spill_path, "rb") as f:
                    flat = f.read()
                value = serialization.deserialize_flat(memoryview(flat))
                entry.value, entry.has_value = value, True
                entry.state = ObjectState.READY
                return value
            from ray_tpu.exceptions import ObjectLostError

            raise ObjectLostError(f"Object {object_id} has no value")

    def get_serialized(self, object_id: ObjectID, timeout: Optional[float] = None) -> memoryview:
        """Wire form for shipping to a process worker (arena-backed, zero-copy)."""
        entry = self._ensure(object_id)
        if not self._wait_entry(entry, timeout):
            from ray_tpu.exceptions import GetTimeoutError

            raise GetTimeoutError(f"Timed out getting object {object_id}")
        with self._lock:
            if entry.state == ObjectState.FAILED:
                raise entry.error  # type: ignore[misc]
            view = self._serialized_view(object_id, entry)
            if view is None and entry.spill_path is None:
                so = serialization.serialize(entry.value)
                if not self._attach_serialized_obj(object_id, entry, so):
                    self._attach_serialized(object_id, entry, so.to_bytes())
                view = self._serialized_view(object_id, entry)
            if view is not None:
                return view
            with open(entry.spill_path, "rb") as f:  # type: ignore[arg-type]
                return memoryview(f.read())

    def spilled_range(self, object_id: ObjectID, off: int, ln: int):
        """(total_size, bytes) of [off, off+ln) seek-read straight from a
        READY spilled object's file — None when not spilled.  Parallel
        range streams would otherwise re-read the whole spill file once per
        chunk via get_serialized (a 1 GiB object pulled as 32 MiB chunks =
        32 GiB of disk reads)."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or e.spill_path is None or e.in_plasma \
                    or e.shm is not None or e.has_value:
                return None
            path, total = e.spill_path, e.size
            e.last_access = time.monotonic()
        try:
            with open(path, "rb") as f:
                f.seek(off)
                return total, f.read(max(0, min(ln, total - off)))
        except OSError:
            return None

    def shm_name(self, object_id: ObjectID) -> Optional[str]:
        with self._lock:
            e = self._entries.get(object_id)
            return e.shm.name if e and e.shm is not None else None

    def serialized_region(self, object_id: ObjectID):
        """(arena_fd, offset, size, release) of a READY arena-resident
        object, with the entry pinned against spilling while held — lets
        the object server ``os.sendfile`` payloads straight out of the
        tmpfs arena with zero user-space copies (ref: the reference's
        object_buffer_pool chunk reads, minus the copy).  None when the
        object is not arena-resident (caller falls back to a view copy)."""
        if self.plasma is None:
            return None
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None or entry.state != ObjectState.READY \
                    or not entry.in_plasma:
                return None
            region = self.plasma.get_region(object_id, timeout=0)
            if region is None:
                return None
            entry.pinned += 1
            entry.last_access = time.monotonic()

        released = threading.Event()

        def release() -> None:
            if released.is_set():
                return
            released.set()
            with self._lock:
                self.plasma.release(object_id)
                entry.pinned = max(0, entry.pinned - 1)

        return self.plasma.fd, region[0], entry.size, release

    def create_for_receive(self, object_id: ObjectID, size: int,
                           owner: str = ""):
        """Writable arena buffer for landing a remote object's wire bytes
        straight off a socket (zero-copy receive: the kernel's recv copy is
        the ONLY copy).  Returns (buf, commit, abort) — fill ``buf``, then
        ``commit()`` to seal + wake waiters, or ``abort()`` to unwind.
        None when the arena can't take it (exists / OOM / no arena); the
        caller falls back to put_serialized."""
        if self.plasma is None or size <= 0:
            return None
        with self._lock:
            entry = self._entries.setdefault(object_id, _Entry())
            if entry.state != ObjectState.PENDING or entry.in_plasma \
                    or object_id in self._plasma_graveyard:
                return None
            self._maybe_spill(size)
            try:
                buf = self.plasma.create(object_id, size)
            except Exception:
                return None
            self._bytes_used += size
            entry.in_plasma = True
            entry.size = size
            if owner:
                entry.owner = owner

        def commit() -> None:
            try:
                buf.release()
            except BufferError:
                pass
            self.plasma.seal(object_id)
            with self._lock:
                entry.state = ObjectState.READY
                entry.last_access = time.monotonic()
                entry.backup_flat = None
                self.stats["puts"] += 1
            self._signal(entry)

        def abort() -> None:
            try:
                buf.release()
            except BufferError:
                pass
            promoted = False
            with self._lock:
                self._bytes_used -= size
                entry.in_plasma = False
                entry.size = 0
                try:
                    self.plasma.release(object_id)
                    self.plasma.delete(object_id)
                except Exception:
                    pass
                backup = entry.backup_flat
                entry.backup_flat = None
                if backup is not None and entry.state == ObjectState.PENDING:
                    # A duplicate delivery was acknowledged while this
                    # landing was in flight — promote it now so waiters
                    # wake with the data instead of hanging.
                    self._attach_serialized(object_id, entry, backup)
                    entry.state = ObjectState.READY
                    self.stats["puts"] += 1
                    promoted = True
            if promoted:
                self._signal(entry)

        return buf, commit, abort

    # --------------------------------------------------------------- lifecycle
    def _ensure(self, object_id: ObjectID) -> _Entry:
        with self._lock:
            return self._entries.setdefault(object_id, _Entry())

    def _attach_serialized_obj(self, object_id: ObjectID, entry: _Entry,
                               so) -> bool:
        """Serialize-at-pull fast path: write a SerializedObject's wire form
        straight into a fresh arena buffer (skipping the to_bytes() flat
        copy).  Caller holds the lock.  False = arena unavailable; caller
        falls back to the flat-bytes path."""
        if self.plasma is None or entry.in_plasma:
            return False
        size = so.flat_size
        self._maybe_spill(size)
        if object_id in self._plasma_graveyard:
            return False
        try:
            buf = self.plasma.create(object_id, max(size, 1))
        except Exception:
            return False
        try:
            so.write_into(buf)
        except BaseException:
            # A created-but-unsealed object would poison every later access:
            # the retry's create hits PlasmaObjectExists, the dup-delivery
            # handler marks it in_plasma, and plasma.get of the unsealed
            # entry returns None forever.  Seal+delete the orphan; if the
            # delete can't land, graveyard the key so nothing aliases it.
            buf.release()
            try:
                self.plasma.seal(object_id)
            except Exception:
                pass
            try:
                self.plasma.release(object_id)  # drop creator ref
                if not self.plasma.delete(object_id):
                    self._plasma_graveyard.add(object_id)
            except Exception:
                self._plasma_graveyard.add(object_id)
            raise
        buf.release()
        self.plasma.seal(object_id)
        self._bytes_used += size
        entry.in_plasma = True
        entry.size = size
        return True

    def _attach_serialized(self, object_id: ObjectID, entry: _Entry, flat: bytes) -> None:
        size = len(flat)
        self._maybe_spill(size)
        if self.plasma is not None:
            try:
                from ray_tpu.native.plasma import PlasmaObjectExists

                try:
                    buf = self.plasma.create(object_id, max(size, 1))
                    buf[:size] = flat
                    buf.release()
                    self.plasma.seal(object_id)
                    self._bytes_used += size
                    entry.in_plasma = True
                    entry.size = size
                    return
                except PlasmaObjectExists:
                    if object_id not in self._plasma_graveyard:
                        # Duplicate delivery of the same bytes (task retry);
                        # the first create's accounting and ref stand.
                        if not entry.in_plasma:
                            self._bytes_used += size
                        entry.in_plasma = True
                        entry.size = size
                        return
                    # A freed-but-still-mapped (graveyarded) object holds this
                    # key: its bytes are STALE for a re-created ObjectID
                    # (lineage reconstruction after free).  Aliasing it would
                    # serve old data and un-pin live views; keep the new
                    # incarnation out of the arena instead (disk below).
            except MemoryError:
                pass  # arena full even after eviction: spill to disk below
        else:
            try:
                shm = shared_memory.SharedMemory(create=True, size=max(size, 1))
                shm.buf[:size] = flat
                entry.shm = shm
                entry.size = size
                self._bytes_used += size
                return
            except Exception:
                pass
        # Last resort: keep wire form on disk.
        path = os.path.join(GLOBAL_CONFIG.spill_dir, f"{object_id}.bin".replace(":", "_"))
        with open(path, "wb") as f:
            f.write(flat)
        entry.spill_path = path
        entry.size = size

    def _maybe_spill(self, incoming: int) -> None:
        """LRU-spill serialized objects when over threshold (caller holds lock).

        Plasma-resident entries with exported zero-copy views are skipped: the
        arena recycles memory on delete, so spilling them would invalidate
        live numpy views (the reference pins such objects in plasma the same
        way, via client refcounts)."""
        threshold = self.capacity_bytes * GLOBAL_CONFIG.object_spilling_threshold
        if self._bytes_used + incoming <= threshold:
            return
        candidates = sorted(
            (
                (e.last_access, oid, e)
                for oid, e in self._entries.items()
                if not e.pinned
                and ((e.shm is not None) or (e.in_plasma and not e.exported))
            ),
        )
        for _, oid, entry in candidates:
            if self._bytes_used + incoming <= threshold:
                break
            view = self._serialized_view(oid, entry)
            if view is None:
                continue
            path = os.path.join(GLOBAL_CONFIG.spill_dir, f"{oid}.bin".replace(":", "_"))
            with open(path, "wb") as f:
                f.write(bytes(view))
            # Drop the view BEFORE releasing: a live memoryview into the shm
            # segment makes shm.close() raise BufferError, parking the
            # segment in the graveyard and reclaiming nothing.
            view.release()
            del view
            self._release_serialized(oid, entry)
            entry.spill_path = path
            entry.state = ObjectState.SPILLED
            self.stats["spills"] += 1

    def _release_serialized(self, object_id: ObjectID, entry: _Entry) -> None:
        if entry.in_plasma and self.plasma is not None:
            self._bytes_used -= entry.size
            if entry.exported:
                # Zero-copy numpy views into the arena are (or may be) still
                # alive in user code: deleting would let the allocator recycle
                # the block under them.  Keep the creator ref so neither
                # delete nor LRU eviction can touch it; reclaimed only when
                # the arena is unlinked at shutdown (the plasma analogue of
                # the shm graveyard below).
                self._plasma_graveyard.add(object_id)
            else:
                self.plasma.release(object_id)  # drop creator ref
                self.plasma.delete(object_id)
            entry.in_plasma = False
            entry.exported = False
            return
        if entry.shm is not None:
            self._bytes_used -= entry.size
            try:
                entry.shm.unlink()
            except Exception:
                pass
            try:
                entry.shm.close()
            except BufferError:
                # Zero-copy views into this segment are still alive (numpy
                # arrays deserialized out-of-band).  The mapping stays valid
                # until the views die; park the handle so its __del__ doesn't
                # raise, and retry at shutdown.
                self._graveyard.append(entry.shm)
            except Exception:
                pass
            entry.shm = None

    def pin(self, object_id: ObjectID) -> None:
        with self._lock:
            self._ensure(object_id).pinned += 1

    def unpin(self, object_id: ObjectID) -> None:
        with self._lock:
            e = self._entries.get(object_id)
            if e:
                e.pinned = max(0, e.pinned - 1)

    def free(self, object_id: ObjectID) -> None:
        """Called when the distributed refcount hits zero."""
        with self._lock:
            entry = self._entries.pop(object_id, None)
            if entry is None:
                return
            self._release_serialized(object_id, entry)
            if entry.spill_path:
                try:
                    os.unlink(entry.spill_path)
                except OSError:
                    pass
            entry.state = ObjectState.FREED
            entry.value = None
            self.stats["freed"] += 1

    def evict_value(self, object_id: ObjectID) -> None:
        """Drop the deserialized copy, keep wire form (tests/memory pressure)."""
        with self._lock:
            e = self._entries.get(object_id)
            if e and (e.in_plasma or e.shm is not None or e.spill_path):
                e.value, e.has_value = None, False

    def shutdown(self) -> None:
        import gc

        with self._lock:
            # Detach the table before touching entries: releasing allocates,
            # an allocation can trigger GC, and a collected ObjectRef's
            # __del__ re-enters free() on this same thread (RLock) — which
            # must see an empty table, not pop out of the dict mid-iteration.
            entries = self._entries
            self._entries = {}
            for oid, entry in entries.items():
                if entry.shm is not None:
                    self._release_serialized(oid, entry)
        gc.collect()
        for shm in self._graveyard:
            try:
                shm.close()
            except Exception:
                pass
        self._graveyard.clear()
        self._plasma_graveyard.clear()
        if self.plasma is not None:
            self.plasma.close(unlink=True)
            self.plasma = None

    def usage(self) -> Tuple[int, int]:
        with self._lock:
            return self._bytes_used, self.capacity_bytes

    def object_summaries(self) -> List[dict]:
        """Per-object view for the state API / metrics agent
        (ref: `ray list objects`, util/state/api.py)."""
        with self._lock:
            return [
                {"object_id": str(oid), "state": e.state, "size": e.size,
                 "pinned": e.pinned, "owner": e.owner,
                 "in_plasma": e.in_plasma,
                 "spilled": e.spill_path is not None}
                for oid, e in self._entries.items()
            ]
