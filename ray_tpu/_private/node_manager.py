"""Cross-host task/actor execution: worker nodes that JOIN a head and
RECEIVE work.

TPU-native analogue of the reference's raylet node manager + GCS node
registry (ref: src/ray/raylet/node_manager.h:117 — per-node agent that
leases workers and executes dispatched tasks; src/ray/gcs/gcs_server/
gcs_node_manager.h — node registration/death; cluster_task_manager.h:42 —
spillback to other nodes' resources).  The shapes differ deliberately:

* The HEAD keeps the single global scheduler (one resource ledger, no
  gossip needed at this scale).  A worker node registers its resources as
  a REAL scheduler node; the dispatcher, on acquiring a lease on that
  node, ships the TaskSpec over the node's persistent TCP connection
  instead of running it in-process.
* A WORKER NODE is a full local Runtime (store + object server + process
  pool + actor FSM) minus global scheduling: dispatched specs execute
  through the ordinary local pipeline (dependency pulls ride the object
  plane), so generators, process isolation, retries and runtime envs all
  work on remote nodes for free.
* RESULTS follow the reference's direct-call split (ref: common/
  ray_config_def.h max_direct_call_object_size): small returns travel
  inline in the completion frame and land in the head's store; large
  returns STAY in the producing node's store — the head records the
  location, stamps it into refs that cross process boundaries, and peers
  pull directly from the producer (no head relay).  The producer pins an
  exported object with a ledger borrow under ``EXPORT_BORROWER`` until the
  head's refcount for it dies, which releases the pin over the borrow
  protocol (reusing reference_count.h-style lifetime rules).
* NODE DEATH (connection loss or missed heartbeats) removes the node,
  fails its in-flight tasks as retryable worker crashes, restarts its
  actors elsewhere via the ordinary FSM, and resubmits lineage for
  objects whose only copy lived there (ref: gcs_health_check_manager.h:45,
  object_recovery_manager.h:38).

Wire protocol: u32-length-prefixed pickled tuples (the ray:// framing);
first frame worker->head is ("register", info).  All further frames are
fire-and-forget messages except ("req", id, kind, payload) — the worker's
control-plane fallback (named actors, foreign-actor calls, internal KV)
answered by ("reply", id, ok, blob) through the same nested-API handler
that powers process workers and ray:// drivers.
"""

from __future__ import annotations

import os
import queue
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import serialization
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.ids import ActorID, NodeID, ObjectID, TaskID
from ray_tpu.exceptions import ActorDiedError, WorkerCrashedError

#: Ledger borrower id under which a node pins results exported to the
#: cluster; the head releases it when its refcount for the object dies.
EXPORT_BORROWER = "cluster-head"


class _FramedConn:
    """u32-length-prefixed frames of pickled tuples over one socket, with a
    write lock so concurrent senders never interleave frames."""

    def __init__(self, sock: socket.socket):
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self._rfile = sock.makefile("rb")
        self._wlock = threading.Lock()

    def send(self, msg: tuple) -> None:
        data = serialization.dumps_inband(msg)
        with self._wlock:
            self._sock.sendall(struct.pack("<I", len(data)) + data)

    def recv(self) -> tuple:
        header = self._rfile.read(4)
        if len(header) < 4:
            raise EOFError("node connection closed")
        (n,) = struct.unpack("<I", header)
        data = self._rfile.read(n)
        if len(data) < n:
            raise EOFError("node connection closed mid-frame")
        return serialization.loads(data)

    def close(self) -> None:
        try:
            self._rfile.close()
        except Exception:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


# ======================================================================
# Head side
# ======================================================================
class RemoteNode:
    """Head-side record of one joined worker node."""

    def __init__(self, node_id: NodeID, conn: _FramedConn, info: dict):
        self.node_id = node_id
        self.conn = conn
        self.info = info
        self.object_addr: str = info.get("object_addr", "")
        self.alive = True
        #: Loss recovery ran (dispatch-failure, reader EOF and the monitor
        #: all race to declare a node dead; recovery must run once).
        self.lost_handled = False
        self.last_heartbeat = time.monotonic()
        #: Per-node nested-API state (streaming-submission gen tokens).
        self.gen_state: dict = {"gens": {}}
        #: In-flight head->node info requests (dashboard drilldown).
        self.pending_info: Dict[int, list] = {}
        self.info_counter = 0
        self.info_lock = threading.Lock()


class NodeManagerServer:
    """Accepts worker-node registrations; routes dispatches and replies.

    One reader thread per node connection; sends go through the per-conn
    write lock from whatever thread dispatches.
    """

    def __init__(self, runtime, host: str = "127.0.0.1", port: int = 0):
        self._runtime = runtime
        self._listener = socket.create_server((host, port))
        self.host, self.port = self._listener.getsockname()[:2]
        self.address = f"{self.host}:{self.port}"
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="ray_tpu_node_server", daemon=True)
        self._accept_thread.start()
        self._monitor_thread = threading.Thread(
            target=self._monitor_loop, name="ray_tpu_node_monitor", daemon=True)
        self._monitor_thread.start()

    # --------------------------------------------------------------- accept
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                if self._stop.is_set() or self._listener.fileno() < 0:
                    return
                time.sleep(0.02)
                continue
            threading.Thread(target=self._serve_node, args=(sock,),
                             name="ray_tpu_node_conn", daemon=True).start()

    def _serve_node(self, sock: socket.socket) -> None:
        conn = _FramedConn(sock)
        node: Optional[RemoteNode] = None
        try:
            kind, info = conn.recv()
            if kind != "register":
                conn.close()
                return
            node_id = NodeID(info["node_id"])
            node = RemoteNode(node_id, conn, info)
            # Ack BEFORE the scheduler learns the node: the first dispatch
            # may race the ack onto the wire, and the worker expects
            # ("registered", ...) as its first frame.  The third field
            # tells a REJOINING node whether the head still knows it
            # (False = keep local state; True = fresh session, reset —
            # loss recovery already restarted its actors elsewhere).
            existing = self._runtime.scheduler.get_node(node_id)
            known = (self._runtime._remote_node(node_id) is not None
                     and existing is not None and existing.alive)
            conn.send(("registered", str(self._runtime.head_node_id),
                       not known))
            self._runtime._register_remote_node(node, info)
            while not self._stop.is_set():
                frame = conn.recv()
                self._handle_frame(node, frame)
        except (EOFError, OSError, ConnectionError):
            pass
        except Exception:
            import traceback

            traceback.print_exc()
        finally:
            conn.close()
            if node is not None and node.alive:
                self._runtime._declare_node_lost(node)

    # -------------------------------------------------------------- frames
    def _handle_frame(self, node: RemoteNode, frame: tuple) -> None:
        kind = frame[0]
        node.last_heartbeat = time.monotonic()
        if kind == "heartbeat":
            return
        if kind == "task_done":
            _, task_id, results = frame
            self._runtime._on_remote_task_done(node, TaskID(task_id), results)
        elif kind == "task_yield":
            _, task_id, index, item = frame
            self._runtime._on_remote_task_yield(node, TaskID(task_id), index, item)
        elif kind == "actor_ready":
            self._runtime._on_remote_actor_ready(node, ActorID(frame[1]))
        elif kind == "actor_dead":
            err = serialization.loads(frame[2])
            self._runtime._on_remote_actor_dead(node, ActorID(frame[1]), err)
        elif kind == "req":
            # Control-plane fallback: answered by the nested-API handler on
            # a pool thread (reqs may block, e.g. a get()); the reader
            # thread must stay free to receive task_done frames.
            _, msg_id, rkind, payload = frame
            threading.Thread(
                target=self._serve_request,
                args=(node, msg_id, rkind, payload),
                name="ray_tpu_node_req", daemon=True).start()
        elif kind == "info_reply":
            _, msg_id, blob = frame
            with node.info_lock:
                slot = node.pending_info.get(msg_id)
            if slot is not None:
                slot[1] = serialization.loads(blob)
                slot[0].set()
        else:
            raise ValueError(f"unknown node frame: {kind!r}")

    def node_info(self, node: RemoteNode, timeout: float = 3.0,
                  detail: str = "full") -> dict:
        """Ask a node for its live state snapshot (the dashboard
        aggregation/drilldown path — ref: dashboard/head.py:65 collecting
        per-node agent reports).  ``detail="summary"`` skips log tails and
        object listings (the cluster table's refresh path)."""
        with node.info_lock:
            node.info_counter += 1
            msg_id = node.info_counter
            slot = [threading.Event(), None]
            node.pending_info[msg_id] = slot
        try:
            node.conn.send(("info_req", msg_id, detail))
            if not slot[0].wait(timeout):
                raise TimeoutError(f"node {node.node_id} info timed out")
            return slot[1]
        finally:
            with node.info_lock:
                node.pending_info.pop(msg_id, None)

    def _serve_request(self, node: RemoteNode, msg_id: int, kind: str,
                       payload: tuple) -> None:
        from ray_tpu._private.client_runtime import _handle

        try:
            result = _handle(self._runtime, kind, payload,
                             state=node.gen_state)
            # wire_pins=True: refs in the reply take owner-side pins that
            # the worker's deserialization converts into real borrows — a
            # bounded lifetime, unlike parking every reply ref in a
            # per-node dict forever.
            sobj = serialization.serialize(result, wire_pins=True)
            reply = ("reply", msg_id, "ok", sobj.to_bytes())
        except BaseException as e:  # noqa: BLE001 — errors cross the wire
            try:
                blob = serialization.dumps((e, ""))
            except Exception:
                blob = serialization.dumps((RuntimeError(repr(e)), ""))
            reply = ("reply", msg_id, "err", blob)
        try:
            node.conn.send(reply)
        except (OSError, ConnectionError):
            pass

    # ------------------------------------------------------------- monitor
    def _monitor_loop(self) -> None:
        timeout = GLOBAL_CONFIG.node_heartbeat_timeout_s
        while not self._stop.is_set():
            time.sleep(min(2.0, timeout / 3))
            now = time.monotonic()
            for node in self._runtime._remote_nodes_snapshot():
                if node.alive and now - node.last_heartbeat > timeout:
                    # Partitioned or wedged: declare it dead (also closes
                    # the socket, unwinding the reader thread).
                    self._runtime._declare_node_lost(node)

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass


# ======================================================================
# Worker side
# ======================================================================
class WorkerRuntime:
    """Mixin methods installed on the worker node's local Runtime.

    The worker's Runtime executes dispatched work through the ordinary
    local pipeline; these fallbacks route CONTROL-PLANE operations the
    local runtime cannot answer (named actors, calls on actors living
    elsewhere, cluster KV) to the head over the node connection.  Built as
    a dynamic subclass so Runtime itself stays head-agnostic.
    """

    _node: "WorkerNode" = None  # set by WorkerNode after install

    def get_named_actor(self, name: str, namespace: Optional[str] = None):
        try:
            return super().get_named_actor(name, namespace)
        except ValueError:
            return self._node.head_request("get_named_actor", name, namespace)

    def submit_actor_task(self, actor_id, spec):
        if actor_id in self._actors:
            return super().submit_actor_task(actor_id, spec)
        # Actor lives on another node: the head routes the call.
        if spec.generator:
            from ray_tpu._private.client_runtime import _ProxiedRefGenerator

            token = self._node.head_request(
                "submit_actor_task_gen", actor_id,
                serialization.dumps_inband(spec))
            return _ProxiedRefGenerator(self._node.head_request, token)
        return self._node.head_request(
            "submit_actor_task", actor_id, serialization.dumps_inband(spec))

    def kill_actor(self, actor_id, no_restart: bool = True) -> None:
        if actor_id in self._actors:
            return super().kill_actor(actor_id, no_restart)
        return self._node.head_request("kill_actor", actor_id, no_restart)

    def get_actor_state(self, actor_id):
        local = super().get_actor_state(actor_id)
        if local is not None:
            return local
        cls, max_task_retries, state_name = self._node.head_request(
            "actor_info", actor_id)

        class _Shim:
            pass

        spec = _Shim()
        spec.cls = cls
        spec.max_task_retries = max_task_retries
        shim = _Shim()
        shim.spec = spec
        shim.state = state_name
        return shim

    def get_named_actor_or_none(self, name, namespace=None):  # pragma: no cover
        try:
            return self.get_named_actor(name, namespace)
        except ValueError:
            return None

    def kv_call(self, op: str, *args) -> Any:
        """internal_kv routes here (see experimental/internal_kv.py): the
        cluster KV tier lives on the head."""
        return self._node.head_request("internal_kv", op, *args)


class WorkerNode:
    """A worker-node process: joins a head, receives dispatches.

    Entry point: ``ray_tpu worker --address=HOST:PORT`` (see __main__).
    """

    def __init__(self, address: str, num_cpus: Optional[float] = None,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 node_id: Optional[str] = None):
        from ray_tpu._private.runtime import Runtime, install_runtime

        cls = type("WorkerNodeRuntime", (WorkerRuntime, Runtime), {})
        self.runtime = cls(num_cpus=num_cpus, resources=resources,
                           labels=labels)
        self.runtime._node = self
        install_runtime(self.runtime)
        self.runtime.start_object_server()

        self.address = address
        self.node_id = NodeID(node_id) if node_id else NodeID.from_random()
        self._stop = threading.Event()
        self._req_lock = threading.Lock()
        self._req_counter = 0
        self._pending_reqs: Dict[int, list] = {}
        #: Result/actor-state frames whose send failed mid-disconnect: a
        #: SAME-session rejoin re-delivers them (the head suppressed its
        #: loss recovery for us, so nothing else would complete the tasks).
        self._undelivered: list = []
        self._undelivered_lock = threading.Lock()

        # Bounded dispatch handlers (ref: worker_pool.h:216): each inbound
        # task/actor frame occupies one pool slot until its result exports;
        # idle threads are reused, and the cap stops a deep actor-call queue
        # from growing one OS thread per call.
        from ray_tpu._private.runtime import _LeanExecPool

        self._dispatch_pool = _LeanExecPool(
            max_threads=GLOBAL_CONFIG.node_dispatch_max_threads,
            name="node_dispatch")
        #: Cap on detached slow-result waiter threads (see
        #: _report_or_handoff); past it, handlers wait in-slot.
        self._waiter_slots = threading.BoundedSemaphore(2048)

        self.conn, self.head_node_id, _ = self._connect_and_register()

        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name="ray_tpu_node_hb", daemon=True)
        self._hb_thread.start()
        self._install_debug_signal()

    def _connect_and_register(self):
        """Dial the head and register; returns (conn, head session id)."""
        host, _, port_s = self.address.rpartition(":")
        sock = socket.create_connection((host, int(port_s)), timeout=30)
        # Keep the timeout through the ack: a head whose listener is up but
        # whose runtime is stalled must not wedge the rejoin loop forever.
        sock.settimeout(30)
        conn = _FramedConn(sock)
        local = self.runtime.scheduler.get_node(self.runtime.head_node_id)
        conn.send(("register", {
            "node_id": str(self.node_id),
            "resources": dict(local.total),
            "labels": dict(local.labels),
            "object_addr": self.runtime.object_server.addr,
            # Node-local plasma arena: compiled-DAG channel elements pushed
            # to this node land here (dag/channel.py RemoteChannel).
            "arena_path": self.runtime.store.arena_path,
            "pid": os.getpid(),
        }))
        msg = conn.recv()
        if msg[0] != "registered":
            raise ConnectionError(f"head rejected registration: {msg[0]!r}")
        sock.settimeout(None)  # registered: back to blocking serve mode
        fresh = bool(msg[2]) if len(msg) > 2 else True
        return conn, msg[1], fresh

    def _install_debug_signal(self) -> None:
        """`kill -USR2 <pid>`: dump dep-wait state to stderr (companion to
        the USR1 stack dump — the two together diagnose a wedged node)."""
        import signal

        def dump(_sig, _frm):
            # Off-thread: the handler interrupts the main thread mid-
            # bytecode, possibly INSIDE one of the locks the dump takes —
            # acquiring them inline would deadlock the node being probed.
            threading.Thread(target=self._dump_state, name="usr2-dump",
                             daemon=True).start()

        try:
            signal.signal(signal.SIGUSR2, dump)
        except ValueError:
            pass  # not the main thread (embedded use); skip the hook

    def _dump_state(self) -> None:
        import sys

        rt = self.runtime
        with rt._deps_lock:
            items = list(rt._pending_deps.items())
        for n in rt.scheduler.nodes():
            print(f"[node {self.node_id}] sched node {n.id} "
                  f"avail={n.available}", file=sys.stderr, flush=True)
        print(f"[node {self.node_id}] blocked={rt._blocked_count} "
              f"running={list(rt._running)} "
              f"inflight={len(rt._inflight)}",
              file=sys.stderr, flush=True)
        print(f"[node {self.node_id}] {len(items)} dep-waiting specs",
              file=sys.stderr, flush=True)
        for tid, (spec, deps) in items[:8]:
            print(f"  task {tid} {spec.name} waits {len(deps)}:",
                  file=sys.stderr, flush=True)
            for a in list(spec.args):
                oid = getattr(a, "id", None)
                if oid is not None and hasattr(a, "owner_addr"):
                    print(f"    arg {oid} owner_addr={a.owner_addr!r} "
                          f"state={rt.store.state_of(oid)}",
                          file=sys.stderr, flush=True)

    # ---------------------------------------------------------------- serve
    def serve_forever(self) -> None:
        """Reader loop; survives head restarts by re-registering within the
        reconnect grace window; returns on shutdown or grace expiry."""
        try:
            while not self._stop.is_set():
                try:
                    frame = self.conn.recv()
                except (EOFError, OSError, ConnectionError):
                    if not self._try_rejoin():
                        return
                    continue
                self._handle_frame(frame)
        finally:
            self.stop()

    def _try_rejoin(self) -> bool:
        """Head connection lost: keep retrying register for the grace
        window — a restarted head accepts us back and tasks place here
        again (ref: python/ray/_private/node.py:1407, raylet re-register
        across GCS restarts; python/ray/tests/test_gcs_fault_tolerance.py).
        """
        grace = GLOBAL_CONFIG.node_reconnect_grace_s
        if self._stop.is_set() or grace <= 0:
            return False
        try:
            self.conn.close()
        except Exception:
            pass
        # Replies to in-flight head requests will never arrive: fail them
        # now instead of letting each ride out its full timeout.
        lost = ConnectionError("head connection lost (rejoining)")
        with self._req_lock:
            for slot in self._pending_reqs.values():
                slot[1] = ("err", serialization.dumps((lost, None)))
                slot[0].set()
            self._pending_reqs.clear()
        deadline = time.monotonic() + grace
        while not self._stop.is_set() and time.monotonic() < deadline:
            try:
                conn, head_id, fresh = self._connect_and_register()
            except (OSError, ConnectionError, EOFError):
                time.sleep(1.0)
                continue
            if fresh or head_id != self.head_node_id:
                # The head's control plane holds no state for us — it
                # restarted, or it already ran loss recovery and restarted
                # our actors elsewhere.  Drop everything the dead session
                # placed here (actors would be split-brain duplicates, and
                # orphan leases/export pins would leak this node's
                # resources forever).
                self._reset_local_state()
                self.head_node_id = head_id
                with self._undelivered_lock:
                    self._undelivered.clear()  # new session: stale results
            self.conn = conn
            # Same-session rejoin: re-deliver completions whose send failed
            # during the gap — the head suppressed loss recovery for us, so
            # nothing else will finish those tasks.
            with self._undelivered_lock:
                backlog, self._undelivered = self._undelivered, []
            for frame in backlog:
                self._send_to_head(frame)
            print(f"[node {self.node_id}] rejoined head {head_id} "
                  f"at {self.address} (fresh={fresh})", flush=True)
            return True
        return False

    def _send_to_head(self, frame: tuple) -> None:
        """Send a result-bearing frame; on failure queue it for re-delivery
        after a same-session rejoin (losing a task_done frame to a blip
        would hang its driver forever — the head's superseded-loss handling
        deliberately does NOT fail in-flight work of a rejoining node)."""
        try:
            self.conn.send(frame)
        except (OSError, ConnectionError):
            with self._undelivered_lock:
                self._undelivered.append(frame)

    def _reset_local_state(self) -> None:
        """Kill everything the previous head session placed on this node."""
        rt = self.runtime
        for actor_id in list(getattr(rt, "_actors", {})):
            try:
                rt.kill_actor(actor_id, no_restart=True)
            except Exception:
                pass
        # Export pins the dead head held on our results (node_manager
        # EXPORT_BORROWER borrows) will never be released by it.
        try:
            rt._on_borrower_lost(EXPORT_BORROWER)
        except Exception:
            pass

    def stop(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        self.conn.close()
        try:
            self._dispatch_pool.shutdown()
        except Exception:
            pass
        from ray_tpu._private.runtime import shutdown_runtime

        shutdown_runtime()

    def _heartbeat_loop(self) -> None:
        interval = GLOBAL_CONFIG.node_heartbeat_interval_s
        while not self._stop.is_set():
            time.sleep(interval)
            try:
                self.conn.send(("heartbeat",))
            except (OSError, ConnectionError):
                # Disconnected: keep looping — serve_forever's rejoin swaps
                # in a fresh conn and heartbeats resume on it.
                continue

    # --------------------------------------------------------------- frames
    def _handle_frame(self, frame: tuple) -> None:
        kind = frame[0]
        if kind == "task":
            spec = serialization.loads(frame[1])
            spec.strategy = None  # head already placed it on this node
            self._dispatch_pool.submit(self._run_dispatched, spec)
        elif kind == "actor_create":
            spec = serialization.loads(frame[1])
            spec.strategy = None
            self._dispatch_pool.submit(self._create_actor, spec)
        elif kind == "actor_task":
            actor_id = ActorID(frame[1])
            spec = serialization.loads(frame[2])
            self._dispatch_pool.submit(self._run_actor_task, actor_id, spec)
        elif kind == "kill_actor":
            self.runtime.kill_actor(ActorID(frame[1]), no_restart=frame[2])
        elif kind == "cancel":
            task_id = TaskID(frame[1])
            self.runtime._cancelled.add(task_id)
            ctx = self.runtime._running.get(task_id)
            if ctx is not None:
                ctx.cancelled.set()
        elif kind == "reply":
            _, msg_id, ok, blob = frame
            with self._req_lock:
                slot = self._pending_reqs.get(msg_id)
            if slot is not None:
                slot[1] = (ok, blob)
                slot[0].set()
        elif kind == "info_req":
            msg_id = frame[1]
            detail = frame[2] if len(frame) > 2 else "full"
            # Off the reader thread: the snapshot touches runtime locks.
            threading.Thread(target=self._answer_info, args=(msg_id, detail),
                             name="ray_tpu_node_info", daemon=True).start()
        elif kind == "shutdown":
            self._stop.set()
            self.conn.close()
        else:
            raise ValueError(f"unknown dispatch frame: {kind!r}")

    def _answer_info(self, msg_id: int, detail: str = "full") -> None:
        from ray_tpu._private.metrics_agent import (
            runtime_snapshot,
            runtime_summary,
        )

        try:
            # "summary" keeps the cluster table's 5s refresh off log-file
            # I/O and object listings; only the drilldown pays for "full".
            build = runtime_summary if detail == "summary" else runtime_snapshot
            snap = build(self.runtime)
            snap["node_id"] = str(self.node_id)
        except Exception as e:  # noqa: BLE001
            snap = {"node_id": str(self.node_id), "error": repr(e)}
        try:
            self.conn.send(("info_reply", msg_id,
                            serialization.dumps_inband(snap)))
        except (OSError, ConnectionError):
            pass  # head gone; it timed out anyway

    # ------------------------------------------------------------- dispatch
    #
    # Two-phase handling keeps the bounded pool deadlock-free: the pool slot
    # does the SUBMISSION (fast) and exports results that land within a
    # short grace; anything still running hands off to a detached waiter
    # thread and frees the slot.  Without the handoff, 256 handlers blocked
    # on nested same-node calls would starve the very frames they wait on;
    # with it, only genuinely long-running work costs a thread, and the
    # short-task storm path (the thread-per-frame blow-up) stays pooled.
    _FAST_EXPORT_GRACE_S = 0.25

    def _run_dispatched(self, spec) -> None:
        try:
            if spec.generator:
                gen = self.runtime.submit_task(spec)
                # Streams are long-lived by nature: never hold a pool slot.
                threading.Thread(
                    target=self._stream_generator, args=(spec, gen),
                    name="node_dispatch_stream", daemon=True).start()
                return
            refs = self.runtime.submit_task(spec)
            self._report_or_handoff(spec, refs)
        except BaseException as e:  # noqa: BLE001 — submission itself failed
            self._send_done(spec, [("error", serialization.dumps(e))
                                   for _ in range(max(spec.num_returns, 1))])

    def _results_ready_within(self, spec, budget: float) -> bool:
        store = self.runtime.store
        deadline = time.monotonic() + budget
        for i in range(max(spec.num_returns, 1)):
            oid = ObjectID.for_task_return(spec.task_id, i)
            left = deadline - time.monotonic()
            if left <= 0 or not store.wait_ready(oid, left):
                return False
        return True

    def _guarded_report(self, spec, refs) -> None:
        try:
            self._report_completion(spec, refs)
        except BaseException as e:  # noqa: BLE001
            self._send_done(spec, [("error", serialization.dumps(e))
                                   for _ in range(max(spec.num_returns, 1))])

    def _report_or_handoff(self, spec, refs) -> None:
        if self._results_ready_within(spec, self._FAST_EXPORT_GRACE_S):
            self._report_completion(spec, refs)
            return
        if self._waiter_slots.acquire(blocking=False):
            def run():
                try:
                    self._guarded_report(spec, refs)
                finally:
                    self._waiter_slots.release()

            threading.Thread(target=run, name="node_dispatch_wait",
                             daemon=True).start()
        else:
            # Waiter tier saturated too: wait in-slot (the pre-pool
            # behavior) rather than grow threads without bound.
            self._guarded_report(spec, refs)

    def _create_actor(self, spec) -> None:
        try:
            self.runtime.create_actor(spec)
            state = self.runtime.get_actor_state(spec.actor_id)
        except BaseException as e:  # noqa: BLE001
            try:
                self._send_to_head(("actor_dead", str(spec.actor_id),
                                    serialization.dumps(e)))
            except Exception:
                pass  # even serializing the cause failed
            return
        # The ready-wait can take the full creation timeout: never hold a
        # pool slot for it (creations are rare; the storm path is tasks).
        threading.Thread(target=self._await_actor_ready, args=(spec, state),
                         name="node_actor_ready", daemon=True).start()

    def _await_actor_ready(self, spec, state) -> None:
        try:
            ready = state.ready_event.wait(
                timeout=GLOBAL_CONFIG.actor_create_timeout_s)
            if state.state == "ALIVE":
                self._send_to_head(("actor_ready", str(spec.actor_id)))
            else:
                if not ready:
                    # Timed out while __init__ still runs: kill locally so
                    # a late-finishing instance cannot linger as an orphan
                    # holding this node's resources after the head already
                    # declared the actor dead.
                    self.runtime.kill_actor(spec.actor_id, no_restart=True)
                cause = state.death_cause or ActorDiedError(
                    "creation failed" if ready else
                    f"creation timed out after "
                    f"{GLOBAL_CONFIG.actor_create_timeout_s}s")
                self._send_to_head(("actor_dead", str(spec.actor_id),
                                    serialization.dumps(cause)))
        except BaseException as e:  # noqa: BLE001
            try:
                self._send_to_head(("actor_dead", str(spec.actor_id),
                                    serialization.dumps(e)))
            except Exception:
                pass

    def _run_actor_task(self, actor_id: ActorID, spec) -> None:
        try:
            if spec.generator:
                gen = self.runtime.submit_actor_task(actor_id, spec)
                threading.Thread(
                    target=self._stream_generator, args=(spec, gen),
                    name="node_dispatch_stream", daemon=True).start()
                return
            refs = self.runtime.submit_actor_task(actor_id, spec)
            self._report_or_handoff(spec, refs)
        except BaseException as e:  # noqa: BLE001
            self._send_done(spec, [("error", serialization.dumps(e))
                                   for _ in range(max(spec.num_returns, 1))])

    # -------------------------------------------------------------- results
    def _export_result(self, oid: ObjectID) -> tuple:
        """Inline a small result; pin-and-locate a large one (ref:
        max_direct_call_object_size split)."""
        store = self.runtime.store
        ser = bytes(store.get_serialized(oid))
        if len(ser) <= GLOBAL_CONFIG.direct_return_max_bytes:
            return ("inline", ser)
        # Pin before our transient handles die: the head now owns lifetime;
        # it releases this borrow when its refcount for the object dies.
        self.runtime._borrow_ledger().add(oid, EXPORT_BORROWER)
        return ("stored", self.runtime.object_server.addr)

    def _report_completion(self, spec, refs) -> None:
        # ``refs`` pins the local result objects for the duration of the
        # export: dropping them lets the refcounter free a result that a
        # FAST task produced before this frame even ran, and the store.get
        # below would then wait forever on a freshly re-created entry.
        results: List[tuple] = []
        for i in range(max(spec.num_returns, 1)):
            oid = ObjectID.for_task_return(spec.task_id, i)
            try:
                # Blocks until the local pipeline resolves the object
                # (success seals it; failure lands an error entry + raises).
                self.runtime.store.get(oid, None)
                results.append(self._export_result(oid))
            except BaseException as e:  # noqa: BLE001
                results.append(("error", serialization.dumps(e)))
        self._send_done(spec, results)
        del refs  # export done: inline copies shipped, stored copies pinned

    def _stream_generator(self, spec, gen) -> None:
        index = 0
        try:
            for ref in gen:
                try:
                    item = self._export_result(ref.id)
                except BaseException as e:  # noqa: BLE001
                    item = ("error", serialization.dumps(e))
                self._send_to_head(("task_yield", str(spec.task_id), index,
                                    item))
                index += 1
            self._send_done(spec, [])
        except BaseException as e:  # noqa: BLE001 — generator body raised
            self._send_done(spec, [("error", serialization.dumps(e))])

    def _send_done(self, spec, results: List[tuple]) -> None:
        self._send_to_head(("task_done", str(spec.task_id), results))

    # ----------------------------------------------------- head control path
    def head_request(self, kind: str, *payload) -> Any:
        """Synchronous nested-API request to the head (correlation-id
        multiplexed over the node connection — many may be in flight)."""
        with self._req_lock:
            self._req_counter += 1
            msg_id = self._req_counter
            slot = [threading.Event(), None]
            self._pending_reqs[msg_id] = slot
        try:
            self.conn.send(("req", msg_id, kind, tuple(payload)))
            if not slot[0].wait(timeout=GLOBAL_CONFIG.node_request_timeout_s):
                raise TimeoutError(f"head request {kind!r} timed out")
        finally:
            with self._req_lock:
                self._pending_reqs.pop(msg_id, None)
        ok, blob = slot[1]
        if ok == "err":
            exc, _tb = serialization.loads(blob)
            raise exc
        return serialization.deserialize_flat(memoryview(blob))
