"""Internal key-value store with optional on-disk persistence.

TPU-native analogue of the reference's GCS KV tier: the API mirrors
``ray.experimental.internal_kv`` (ref: python/ray/experimental/
internal_kv.py — _internal_kv_get/put/del/exists/keys over namespaces)
backed by the control plane's pluggable storage
(ref: src/ray/gcs/gcs_server/gcs_kv_manager.h GcsKvManager;
src/ray/gcs/store_client/ — InMemoryStoreClient vs RedisStoreClient for a
restartable head).  Here the persistence tier is an append-only JSONL WAL
under the session dir: every mutation appends, a fresh runtime replays it,
and compaction rewrites the live set when the log grows past a threshold —
so control-plane metadata (function exports, serve/app configs, workflow
indices, user keys) survives a head restart the way the reference's
Redis-backed GCS does.
"""

from __future__ import annotations

import base64
import json
import os
import threading
from typing import Dict, List, Optional


class KVStore:
    """Namespaced bytes->bytes store; thread-safe; optionally persistent."""

    def __init__(self, persist_path: Optional[str] = None,
                 compact_threshold: int = 10_000):
        self._data: Dict[str, Dict[bytes, bytes]] = {}
        self._lock = threading.RLock()
        self._persist_path = persist_path
        self._wal = None  # persistent append handle (one open, not per-write)
        self._mutations = 0
        self._compact_threshold = compact_threshold
        if persist_path:
            os.makedirs(os.path.dirname(persist_path) or ".", exist_ok=True)
            if os.path.exists(persist_path):
                self._replay()
            self._wal = open(persist_path, "a")

    # ----------------------------------------------------------------- basic
    def get(self, key: bytes, namespace: str = "") -> Optional[bytes]:
        with self._lock:
            return self._data.get(namespace, {}).get(bytes(key))

    def put(self, key: bytes, value: bytes, overwrite: bool = True,
            namespace: str = "") -> bool:
        """Returns True iff the key was NEWLY added (matching the GCS Put
        contract: an overwrite of an existing key reports added=0)."""
        key, value = bytes(key), bytes(value)
        with self._lock:
            ns = self._data.setdefault(namespace, {})
            existed = key in ns
            if not overwrite and existed:
                return False
            ns[key] = value
            self._log({"op": "put", "ns": namespace,
                       "k": _b64(key), "v": _b64(value)})
            return not existed

    def delete(self, key: bytes, namespace: str = "") -> int:
        key = bytes(key)
        with self._lock:
            ns = self._data.get(namespace, {})
            if key in ns:
                del ns[key]
                self._log({"op": "del", "ns": namespace, "k": _b64(key)})
                return 1
            return 0

    def exists(self, key: bytes, namespace: str = "") -> bool:
        with self._lock:
            return bytes(key) in self._data.get(namespace, {})

    def keys(self, prefix: bytes = b"", namespace: str = "") -> List[bytes]:
        prefix = bytes(prefix)
        with self._lock:
            return [k for k in self._data.get(namespace, {})
                    if k.startswith(prefix)]

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {ns: len(kv) for ns, kv in self._data.items()}

    def close(self) -> None:
        with self._lock:
            if self._wal is not None:
                try:
                    self._wal.close()
                finally:
                    self._wal = None

    # ------------------------------------------------------------ durability
    def _log(self, record: dict) -> None:
        """Caller holds the lock."""
        if self._wal is None:
            return
        self._wal.write(json.dumps(record) + "\n")
        self._wal.flush()
        self._mutations += 1
        if self._mutations >= self._compact_threshold:
            self._compact()

    def _replay(self) -> None:
        with open(self._persist_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail write from a crash: ignore
                ns = self._data.setdefault(rec.get("ns", ""), {})
                if rec["op"] == "put":
                    ns[_unb64(rec["k"])] = _unb64(rec["v"])
                elif rec["op"] == "del":
                    ns.pop(_unb64(rec["k"]), None)

    def _compact(self) -> None:
        """Rewrite the WAL as the live set (caller holds the lock)."""
        tmp = self._persist_path + ".tmp"
        with open(tmp, "w") as f:
            for ns, kv in self._data.items():
                for k, v in kv.items():
                    f.write(json.dumps({"op": "put", "ns": ns,
                                        "k": _b64(k), "v": _b64(v)}) + "\n")
        self._wal.close()
        os.replace(tmp, self._persist_path)
        self._wal = open(self._persist_path, "a")
        self._mutations = 0


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)
