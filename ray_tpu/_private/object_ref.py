"""ObjectRef — a distributed future handle with ownership-based reference counting.

TPU-native analogue of the reference's ObjectRef (ref: python/ray/includes/
object_ref.pxi:36) backed by the owner-side ReferenceCounter
(ref: src/ray/core_worker/reference_count.h:66).  Each ref release (GC or
explicit) decrements the owner's count; when the count reaches zero and the
object is not pinned, the store entry is freed.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from ray_tpu._private.ids import ObjectID


class ObjectRef:
    __slots__ = ("id", "owner", "owner_addr", "_released", "__weakref__")

    def __init__(self, object_id: ObjectID, owner: str = "",
                 owner_addr: str = "", _add_ref: bool = True):
        self.id = ObjectID(object_id)
        self.owner = owner
        self.owner_addr = owner_addr
        self._released = False
        if _add_ref:
            _refcounter.add(self.id)

    @staticmethod
    def _deserialize(object_id: str, owner: str, owner_addr: str = "",
                     wire_pin: str = "") -> "ObjectRef":
        ref = ObjectRef(ObjectID(object_id), owner, owner_addr)
        if owner_addr:
            from ray_tpu._private.object_transfer import local_server_addr

            if owner_addr != local_server_addr():
                # A remote-owned ref materialized here: register the borrow
                # so the owner keeps the primary copy alive until this
                # process's handles die (ref: reference_count.h borrowers).
                from ray_tpu._private.borrowing import global_borrow_client

                global_borrow_client().register(ref.id, owner_addr)
        if wire_pin and owner_addr:
            # The sender pinned the owner for this serialized copy; our own
            # borrow (or, when the bytes came home, the handle just added to
            # the owner's refcounter) now protects the object, so the pin's
            # job is done.  Order matters: release only after registration.
            from ray_tpu._private.borrowing import release_wire_pin

            release_wire_pin(ref.id, owner_addr, wire_pin)
        return ref

    def _wire_tuple(self):
        """Args for ``_deserialize`` when this ref crosses a process boundary.

        On OUT-OF-BAND pickles (serialization.wire_pins_enabled — KV,
        pubsub, actor state, user dumps) remote-owned refs take a
        serialization-time wire pin on the owner so the serialized copy
        stays valid even if every local handle dies before a receiver
        materializes it (ADVICE r2: borrow-at-serialization; ref:
        reference_count.h:66 sender-side borrower reports).  The guarantee
        is FIRST-materialization: the pin converts into the first reader's
        borrow; later readers of the same blob are protected by ordinary
        borrow liveness, exactly like any other handle.  In-band transports
        (store puts, task args, backchannel request/reply) skip the pin —
        their lifetime is carried by contained_refs capture or the sender's
        synchronous receive window.
        """
        addr = self._routable_owner_addr()
        pin = ""
        if addr:
            from ray_tpu._private import serialization
            from ray_tpu._private.object_transfer import local_server_addr

            if serialization.wire_pins_enabled():
                if addr == local_server_addr():
                    # We ARE the owner: pin via a direct ledger entry (no
                    # TCP) so the serialized copy survives our own handles
                    # dying before the receiver registers its borrow.
                    from ray_tpu._private.runtime import runtime_or_none

                    rt = runtime_or_none()
                    if rt is not None and hasattr(rt, "_borrow_ledger"):
                        import uuid

                        pin = f"wire:{uuid.uuid4().hex[:12]}"
                        rt._borrow_ledger().add(self.id, pin)
                else:
                    from ray_tpu._private.borrowing import pin_for_wire

                    pin = pin_for_wire(self.id, addr)
        return (str(self.id), self.owner, addr, pin)

    def _routable_owner_addr(self) -> str:
        """Owner address to embed when this ref crosses a process boundary.

        A ref minted in this process (empty ``owner_addr``) is stamped with
        the local object server's address when one is running AND this
        process actually owns the object (holds or is producing it), making
        it the routable owner (ownership-based directory — ref:
        ownership_based_object_directory.h).  Refs that arrived from
        elsewhere keep their original owner address; a mere forwarder that
        never held the value must not claim ownership.
        """
        if self.owner_addr:
            return self.owner_addr
        from ray_tpu._private.object_transfer import local_server_addr
        from ray_tpu._private.runtime import runtime_or_none

        rt = runtime_or_none()
        # A result that STAYED on a worker node: stamp the holder's address
        # so receivers pull peer-to-peer instead of asking this process
        # (which only knows the location, not the bytes).
        locate = getattr(rt, "location_of", None)
        if locate is not None:
            loc = locate(self.id)
            if loc:
                return loc
        addr = local_server_addr()
        if not addr:
            return ""
        owns = getattr(rt, "owns_object", None)
        if owns is None or not owns(self.id):
            return ""
        return addr

    def __reduce__(self):
        # EVERY pickle path must reconstruct through _deserialize (which
        # registers a refcount): the default slot-state protocol would build
        # a clone that never add()s but whose __del__ remove()s — each trip
        # through plain pickle would leak a negative count and free live
        # objects.  (serialization._Pickler additionally captures the ref
        # for borrow tracking via reducer_override.)
        return (ObjectRef._deserialize, self._wire_tuple())

    def hex(self) -> str:
        return self.id.hex()

    def task_id(self):
        return self.id.task_id()

    def _release(self) -> None:
        if not self._released:
            self._released = True
            _refcounter.remove(self.id)

    def __del__(self) -> None:
        try:
            self._release()
        except Exception:
            pass

    def __hash__(self) -> int:
        return hash(self.id)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self) -> str:
        return f"ObjectRef({self.id})"

    # Allow `await ref` inside async actors / drivers.
    def __await__(self):
        from ray_tpu._private.runtime import get_runtime

        return get_runtime().get_async(self).__await__()

    def future(self):
        """A concurrent.futures.Future resolving to the object's value."""
        from ray_tpu._private.runtime import get_runtime

        return get_runtime().as_future(self)


class ReferenceCounter:
    """Process-local distributed-refcount table (ref: reference_count.h:66).

    Counts local handles per object id.  The store consults ``pinned`` /
    counts before freeing.  On zero, registered zero-callbacks run (used by
    the store to free memory and by lineage to unpin specs).
    """

    def __init__(self) -> None:
        self._counts: dict = {}
        self._lock = threading.Lock()
        self._zero_callback = None

    def set_zero_callback(self, cb) -> None:
        self._zero_callback = cb

    def add(self, object_id: ObjectID, n: int = 1) -> None:
        with self._lock:
            self._counts[object_id] = self._counts.get(object_id, 0) + n

    def add_many(self, object_ids) -> None:
        """One lock round-trip for a batch of new handles (multi-return
        submits, 10k-ref arg lists)."""
        with self._lock:
            counts = self._counts
            for oid in object_ids:
                counts[oid] = counts.get(oid, 0) + 1

    def remove(self, object_id: ObjectID, n: int = 1) -> None:
        cb = None
        with self._lock:
            count = self._counts.get(object_id, 0) - n
            if count <= 0:
                self._counts.pop(object_id, None)
                cb = self._zero_callback
            else:
                self._counts[object_id] = count
        if cb is not None:
            cb(object_id)
        if count <= 0:
            # Borrower-side of the cross-node protocol: if this process
            # borrowed the object, tell the owner the last handle died.
            # The live-count re-read closes the race with a concurrent
            # re-deserialization reviving the ref.
            from ray_tpu._private import borrowing

            borrowing.notify_zero(object_id, count_fn=self.count)

    def count(self, object_id: ObjectID) -> int:
        with self._lock:
            return self._counts.get(object_id, 0)

    def live_ids(self):
        with self._lock:
            return list(self._counts)

    def clear(self) -> None:
        with self._lock:
            self._counts.clear()


_refcounter = ReferenceCounter()


def global_refcounter() -> ReferenceCounter:
    return _refcounter
