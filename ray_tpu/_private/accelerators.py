"""Accelerator autodetection — TPU first.

TPU-native analogue of the reference's accelerator plugin registry
(ref: python/ray/_private/accelerators/tpu.py:70 TPUAcceleratorManager), which
detects chips, sets visibility env vars and registers the pod-level
``TPU-<version>-<chips>-head`` resource (tpu.py:356-358) used for gang
scheduling whole slices.  Here detection goes through JAX itself when it is
already imported (the driver owns the chips), else through TPU env vars.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, Tuple


def detect_accelerators() -> Tuple[Dict[str, float], Dict[str, str]]:
    """Returns (resources, node labels) for the local host."""
    resources: Dict[str, float] = {}
    labels: Dict[str, str] = {}

    chips = 0
    version = ""
    # Prefer an already-initialized JAX client (never trigger a TPU init here).
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            devices = jax.devices()
            tpu_devices = [d for d in devices if "tpu" in d.platform.lower() or "axon" in str(getattr(d, "device_kind", "")).lower() or "TPU" in str(d)]
            chips = len(tpu_devices)
            if tpu_devices:
                version = str(getattr(tpu_devices[0], "device_kind", "tpu")).replace(" ", "-").lower()
        except Exception:
            chips = 0
    if chips == 0:
        env_chips = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS", "")
        if env_chips:
            try:
                chips = 1
                for part in env_chips.split(","):
                    chips *= int(part)
            except ValueError:
                chips = 0
        version = os.environ.get("TPU_ACCELERATOR_TYPE", version)

    if chips > 0:
        resources["TPU"] = float(chips)
        labels["accelerator-type"] = version or "tpu"
        # Pod-slice head resource for gang scheduling (ref: tpu.py:356).
        accel_type = os.environ.get("TPU_ACCELERATOR_TYPE", "")
        worker_id = os.environ.get("TPU_WORKER_ID", "0")
        if accel_type and worker_id == "0":
            resources[f"TPU-{accel_type}-head"] = 1.0
        slice_name = os.environ.get("TPU_NAME", "")
        if slice_name:
            labels["ici-slice"] = slice_name

    return resources, labels
