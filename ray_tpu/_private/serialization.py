"""Serialization: cloudpickle + out-of-band zero-copy buffers + ObjectRef capture.

TPU-native analogue of the reference's SerializationContext
(ref: python/ray/_private/serialization.py:122): pickle protocol 5 with
out-of-band buffer callbacks so large numpy / jax host arrays are carried as
raw buffers (zero-copy into the shared-memory store), and ObjectRefs embedded
in arguments are recorded so the runtime can (a) resolve them before execution
and (b) keep distributed reference counts correct while they are in flight.
"""

from __future__ import annotations

import io
import pickle
import threading
from typing import Any, List, Tuple

import cloudpickle

_THREAD_LOCAL = threading.local()


class SerializedObject:
    """Pickled payload plus its out-of-band buffers and captured ObjectRefs."""

    __slots__ = ("data", "buffers", "contained_refs")

    def __init__(self, data: bytes, buffers: List[pickle.PickleBuffer], contained_refs: List[Any]):
        self.data = data
        self.buffers = buffers
        self.contained_refs = contained_refs

    @property
    def total_bytes(self) -> int:
        return len(self.data) + sum(b.raw().nbytes for b in self.buffers)

    @property
    def flat_size(self) -> int:
        """Exact byte length of the to_bytes()/write_into() wire form."""
        return 12 + 8 * len(self.buffers) + len(self.data) + sum(
            b.raw().nbytes for b in self.buffers)

    def to_bytes(self) -> bytes:
        """Flatten into one buffer (framing: u32 count, u64 sizes, payloads)."""
        out = io.BytesIO()
        out.write(len(self.buffers).to_bytes(4, "little"))
        out.write(len(self.data).to_bytes(8, "little"))
        for b in self.buffers:
            out.write(b.raw().nbytes.to_bytes(8, "little"))
        out.write(self.data)
        for b in self.buffers:
            out.write(b.raw())
        return out.getvalue()

    def write_into(self, dest: memoryview) -> int:
        """Write the to_bytes() form straight into ``dest`` (e.g. a plasma
        arena buffer), skipping the intermediate flat copy.  Returns bytes
        written.  ``dest`` must be at least ``flat_size`` long."""
        off = 0

        def put(b) -> None:
            nonlocal off
            n = len(b)
            dest[off:off + n] = b
            off += n

        put(len(self.buffers).to_bytes(4, "little"))
        put(len(self.data).to_bytes(8, "little"))
        for b in self.buffers:
            put(b.raw().nbytes.to_bytes(8, "little"))
        put(self.data)
        for b in self.buffers:
            raw = b.raw()
            put(raw.cast("B") if raw.format != "B" or raw.ndim != 1 else raw)
        return off


def _capture_ref(ref: Any) -> None:
    refs = getattr(_THREAD_LOCAL, "captured_refs", None)
    if refs is not None:
        refs.append(ref)


# --------------------------------------------------------------- wire pins
# Wire pins (borrowing.pin_for_wire) cost a synchronous TCP round trip to
# the owner per remote-owned ref, so they are taken ONLY on out-of-band
# pickles (KV, pubsub, actor state, user dumps) where the serialized copy
# can outlive the sender's handles.  In-band paths — store puts (the store
# lock is held while serializing!), task args, and backchannel request/
# reply, where contained_refs capture or a synchronous receive window
# already guarantees lifetime — run with pins disabled.
def wire_pins_enabled() -> bool:
    return getattr(_THREAD_LOCAL, "wire_pins", True)


class no_wire_pins:
    """Context manager: disable wire-pinning on this thread while pickling
    through an in-band path whose lifetime is otherwise guaranteed."""

    def __enter__(self):
        self._prev = getattr(_THREAD_LOCAL, "wire_pins", True)
        _THREAD_LOCAL.wire_pins = False
        return self

    def __exit__(self, *exc):
        _THREAD_LOCAL.wire_pins = self._prev
        return False


class _Pickler(cloudpickle.CloudPickler):
    def reducer_override(self, obj: Any):
        # ObjectRefs serialize as their id + owner; capture for refcounting.
        from ray_tpu._private.object_ref import ObjectRef

        if isinstance(obj, ObjectRef):
            _capture_ref(obj)
            # _wire_tuple pins remote-owned refs on their owner for the
            # lifetime of this serialized copy (see borrowing.pin_for_wire).
            return (ObjectRef._deserialize, obj._wire_tuple())
        return super().reducer_override(obj)


def serialize(value: Any, wire_pins: bool = False) -> SerializedObject:
    """In-band by default (contained_refs carry the lifetime); pass
    ``wire_pins=True`` for reply-style transports where the sender drops
    its handles right after the send and the receiver's deserialization
    must find the objects still alive."""
    buffers: List[pickle.PickleBuffer] = []
    _THREAD_LOCAL.captured_refs = []
    prev = getattr(_THREAD_LOCAL, "wire_pins", True)
    _THREAD_LOCAL.wire_pins = wire_pins
    try:
        buf = io.BytesIO()
        pickler = _Pickler(buf, protocol=5, buffer_callback=buffers.append)
        pickler.dump(value)
        return SerializedObject(buf.getvalue(), buffers, list(_THREAD_LOCAL.captured_refs))
    finally:
        _THREAD_LOCAL.wire_pins = prev
        _THREAD_LOCAL.captured_refs = None


def deserialize(data: bytes, buffers: List[Any] = ()) -> Any:
    return pickle.loads(data, buffers=buffers)


def deserialize_flat(flat: memoryview) -> Any:
    """Inverse of SerializedObject.to_bytes, zero-copy for the buffers."""
    flat = memoryview(flat)
    nbuf = int.from_bytes(flat[:4], "little")
    ndata = int.from_bytes(flat[4:12], "little")
    sizes = [
        int.from_bytes(flat[12 + 8 * i : 20 + 8 * i], "little") for i in range(nbuf)
    ]
    off = 12 + 8 * nbuf
    data = flat[off : off + ndata]
    off += ndata
    buffers = []
    for size in sizes:
        buffers.append(flat[off : off + size])
        off += size
    return pickle.loads(data, buffers=buffers)


def dumps(value: Any) -> bytes:
    """One-shot pickle (control messages, KV/pubsub payloads, function
    exports).  Out-of-band by default: remote-owned refs take wire pins."""
    return cloudpickle.dumps(value, protocol=5)


def dumps_inband(value: Any) -> bytes:
    """One-shot pickle for request/reply transports where the receiver
    deserializes synchronously inside the sender's handle lifetime — skips
    the wire-pin round trips (see wire_pins_enabled)."""
    with no_wire_pins():
        return cloudpickle.dumps(value, protocol=5)


loads = pickle.loads
