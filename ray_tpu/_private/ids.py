"""Unique identifiers for jobs, tasks, actors, objects, nodes and placement groups.

TPU-native analogue of the reference's ID scheme (ref: src/ray/common/id.h:1).
The reference embeds ownership info (owner task, put-index) inside ObjectIDs so any
process can locate an object's owner without a directory lookup.  We keep that idea:
an ObjectID is ``<owner job><random task part><index>`` so the owner is recoverable,
but we use simple hex strings rather than packed binary — the control plane here is
in-process/IPC, not cross-datacenter gRPC, so compactness matters less than clarity.
"""

from __future__ import annotations

import itertools
import os
import threading

_NIL = "f" * 16

# ID generation is on the task-submission hot path (one TaskID per call):
# os.urandom is a syscall per draw (~13% of the n:n actor fan-out profile).
# Instead: one urandom draw per process seeds an 8-byte prefix, and a
# monotonic counter supplies the low 4 bytes — unique within a process by
# construction, unique across processes by the prefix (same shape as the
# reference's worker-id + task-counter packing, src/ray/common/id.h).
# 8 prefix bytes keep the birthday bound real at cluster scale: with
# 10k worker processes the collision odds are ~5e-12 (vs ~1% at 4 bytes —
# two colliding nodes would silently alias each other's objects).
# Forked children re-seed via the at-fork hook (single-threaded at that
# point, so no draw can race the reseed).
_PROC_PREFIX = os.urandom(8).hex()
_id_counter = itertools.count(1)


def _reseed_after_fork() -> None:
    global _PROC_PREFIX, _id_counter
    _PROC_PREFIX = os.urandom(8).hex()
    _id_counter = itertools.count(1)


os.register_at_fork(after_in_child=_reseed_after_fork)


def _next_id_hex() -> str:
    # No 32-bit mask: past 2^32 draws the hex simply grows a digit (ids are
    # plain strings) — a wrap would alias a multi-day run's earliest ids.
    return f"{_PROC_PREFIX}{next(_id_counter):08x}"


class BaseID(str):
    """IDs are interned hex strings; cheap to hash, compare and msgpack."""

    __slots__ = ()

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(_next_id_hex())

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(_NIL)

    def is_nil(self) -> bool:
        return self == _NIL

    def hex(self) -> str:  # type: ignore[override]
        return str(self)

    def __repr__(self) -> str:
        return f"{type(self).__name__}({str(self)})"


class JobID(BaseID):
    __slots__ = ()


class NodeID(BaseID):
    __slots__ = ()


class WorkerID(BaseID):
    __slots__ = ()


class ActorID(BaseID):
    __slots__ = ()


class PlacementGroupID(BaseID):
    __slots__ = ()


class TaskID(BaseID):
    __slots__ = ()


class ObjectID(BaseID):
    """``<task-part>:<index>`` — created by task ``task-part`` as its ``index``-th output.

    Mirrors the reference's ObjectID = TaskID + return-index packing (id.h) which
    makes lineage reconstruction possible: the creating task is recoverable from
    the object id alone.
    """

    __slots__ = ()

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(f"{task_id}:{index}")

    @classmethod
    def from_put(cls, put_counter: int, worker_part: str) -> "ObjectID":
        return cls(f"put-{worker_part}:{put_counter}")

    def task_id(self) -> TaskID:
        return TaskID(str(self).rsplit(":", 1)[0])

    def return_index(self) -> int:
        try:
            return int(str(self).rsplit(":", 1)[1])
        except (IndexError, ValueError):
            return 0


class _Counter:
    """Monotonic per-process counter used for put ids and task attempt numbers."""

    def __init__(self) -> None:
        self._value = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value


put_counter = _Counter()
