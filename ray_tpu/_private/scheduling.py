"""Cluster resource model and scheduling policies.

TPU-native analogue of the reference's distributed scheduler
(ref: src/ray/raylet/scheduling/cluster_resource_scheduler.h:44 and
policy/*.h).  The cluster is modeled as a set of (possibly virtual) nodes
with resource sets; policies pick a node for each resource request:

* ``HybridPolicy``   — pack onto the local/first node until a utilization
  threshold, then spread; top-k random tie-break
  (ref: hybrid_scheduling_policy.h:50).
* ``SpreadPolicy``   — round-robin across feasible nodes
  (ref: spread_scheduling_policy.h:27).
* ``NodeAffinityPolicy`` / ``NodeLabelPolicy`` — pin to a node / label match.
* Placement-group bundle policies PACK / SPREAD / STRICT_PACK / STRICT_SPREAD
  (ref: bundle_scheduling_policy.h:82-106) with a TPU twist: STRICT_PACK
  prefers nodes on the same ICI slice (label ``ici-slice``), the analogue of
  packing along pod ICI axes rather than generic host adjacency.

Execution always happens in this host process (threads / local process pool);
the virtual-node model is what makes multi-node scheduling *semantics*
(placement groups, spread, spillback) real and testable on one machine, the
same way the reference tests them via cluster_utils.Cluster
(ref: python/ray/cluster_utils.py:135).
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.ids import NodeID, PlacementGroupID

Resources = Dict[str, float]

_EPS = 1e-9


def res_fits(avail: Resources, req: Resources) -> bool:
    return all(avail.get(k, 0.0) + _EPS >= v for k, v in req.items())


def res_sub(avail: Resources, req: Resources) -> None:
    for k, v in req.items():
        avail[k] = avail.get(k, 0.0) - v


def res_add(avail: Resources, req: Resources) -> None:
    for k, v in req.items():
        avail[k] = avail.get(k, 0.0) + v


class Node:
    def __init__(self, node_id: NodeID, resources: Resources, labels: Optional[Dict[str, str]] = None):
        self.id = node_id
        self.total: Resources = dict(resources)
        self.available: Resources = dict(resources)
        self.labels = labels or {}
        self.alive = True
        #: Quarantine/scale-down drain: an alive node that accepts NO new
        #: placements (tasks, actors, PG bundles) while existing leases
        #: finish — set via ClusterScheduler.set_node_draining by the
        #: cluster autoscaler's postmortem health gate.
        self.draining = False
        self.start_time = time.time()
        #: Last time a lease touched this node (autoscaler idle detection).
        self.last_busy = time.time()

    def utilization(self) -> float:
        fracs = [
            1.0 - self.available.get(k, 0.0) / v
            for k, v in self.total.items()
            if v > 0
        ]
        return max(fracs) if fracs else 0.0

    def snapshot(self) -> dict:
        return {
            "NodeID": self.id,
            "Alive": self.alive,
            "Draining": self.draining,
            "Resources": dict(self.total),
            "Available": dict(self.available),
            "Labels": dict(self.labels),
        }

    @property
    def schedulable(self) -> bool:
        """Placement eligibility: alive and not draining."""
        return self.alive and not self.draining


class SchedulingStrategy:
    """Base for scheduling strategies attached to tasks/actors via options()
    (ref: python/ray/util/scheduling_strategies.py)."""

    name = "DEFAULT"


class DefaultStrategy(SchedulingStrategy):
    name = "DEFAULT"


class SpreadStrategy(SchedulingStrategy):
    name = "SPREAD"


class NodeAffinitySchedulingStrategy(SchedulingStrategy):
    name = "NODE_AFFINITY"

    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = NodeID(node_id)
        self.soft = soft


class NodeLabelSchedulingStrategy(SchedulingStrategy):
    name = "NODE_LABEL"

    def __init__(self, hard: Optional[Dict[str, str]] = None, soft: Optional[Dict[str, str]] = None):
        self.hard = hard or {}
        self.soft = soft or {}


class PlacementGroupSchedulingStrategy(SchedulingStrategy):
    name = "PLACEMENT_GROUP"

    def __init__(self, placement_group, placement_group_bundle_index: int = -1,
                 placement_group_capture_child_tasks: bool = False):
        self.placement_group = placement_group
        self.bundle_index = placement_group_bundle_index
        self.capture_child_tasks = placement_group_capture_child_tasks


class _Bundle:
    def __init__(self, index: int, resources: Resources):
        self.index = index
        self.resources = dict(resources)
        self.available = dict(resources)
        self.node_id: Optional[NodeID] = None


class PlacementGroupState:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Resources], strategy: str, name: str = ""):
        self.id = pg_id
        self.bundles = [_Bundle(i, b) for i, b in enumerate(bundles)]
        self.strategy = strategy
        self.name = name
        self.state = "PENDING"
        self.ready_event = threading.Event()


class ClusterScheduler:
    """Resource bookkeeping + policy dispatch + wait queue.

    Combines the roles of ClusterResourceManager (cluster view),
    ClusterTaskManager (grant or queue) and the policy set
    (ref: cluster_task_manager.h:42).  ``acquire`` either grants a lease
    immediately or queues the request; ``release`` wakes the queue.
    """

    def __init__(self) -> None:
        self._nodes: Dict[NodeID, Node] = {}
        self._pgs: Dict[PlacementGroupID, PlacementGroupState] = {}
        self._lock = threading.Condition()
        #: Called (outside the lock) after every capacity-adding event —
        #: lease release, add_node, PG commit/removal — so the dispatcher
        #: retries blocked tasks exactly when capacity appears instead of
        #: polling.
        self.on_release: Optional[Callable[[], None]] = None
        self._queue: deque = deque()
        self._rr_counter = 0
        self._pg_queue: deque = deque()
        #: Requests currently blocked in acquire() (autoscaler demand signal).
        self._pending_demand: Dict[object, Resources] = {}
        #: Set by the autoscaler: resource shapes of launchable node types.
        #: Feasibility then means "fits an existing node OR a launchable
        #: type" — requests no type can satisfy still fail fast instead of
        #: hanging on a scale-up that can never come.
        self.autoscaling_enabled = False
        self.autoscaler_node_shapes: List[Resources] = []

    def _fire_on_release(self) -> None:
        cb = self.on_release
        if cb is not None:
            cb()

    # ------------------------------------------------------------- node admin
    def add_node(self, resources: Resources, labels: Optional[Dict[str, str]] = None,
                 node_id: Optional[NodeID] = None) -> NodeID:
        node_id = node_id or NodeID.from_random()
        with self._lock:
            self._nodes[node_id] = Node(node_id, resources, labels)
            self._retry_pending_pgs_locked()
            self._lock.notify_all()
        self._fire_on_release()
        return node_id

    def remove_node(self, node_id: NodeID) -> None:
        with self._lock:
            node = self._nodes.pop(node_id, None)
            if node:
                node.alive = False

    def set_node_draining(self, node_id, draining: bool = True) -> bool:
        """Mark a node draining (no NEW placements; existing leases run to
        completion) — the cluster autoscaler's quarantine/drain primitive.
        Accepts a NodeID or its string form; returns False for an unknown
        node (already removed — the drain raced a termination, fine)."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                wanted = str(node_id)
                node = next((n for nid, n in self._nodes.items()
                             if str(nid) == wanted), None)
            if node is None:
                return False
            node.draining = bool(draining)
        return True

    def nodes(self) -> List[Node]:
        with self._lock:
            return list(self._nodes.values())

    def get_node(self, node_id: NodeID) -> Optional[Node]:
        with self._lock:
            return self._nodes.get(node_id)

    def cluster_resources(self) -> Resources:
        with self._lock:
            total: Resources = {}
            for n in self._nodes.values():
                res_add(total, n.total)
            return total

    def available_resources(self) -> Resources:
        with self._lock:
            total: Resources = {}
            for n in self._nodes.values():
                res_add(total, n.available)
            return total

    # ---------------------------------------------------------------- leasing
    def acquire(self, request: Resources, strategy: Optional[SchedulingStrategy] = None,
                timeout: Optional[float] = None) -> Tuple[NodeID, Callable[[], None]]:
        """Block until resources are granted; returns (node_id, release_fn)."""
        strategy = strategy or DefaultStrategy()
        deadline = None if timeout is None else time.monotonic() + timeout
        demand_key = object()
        with self._lock:
            try:
                while True:
                    node_id = self._try_place_locked(request, strategy)
                    if node_id is not None:
                        self._touch_locked(node_id)
                        return node_id, self._make_release(node_id, request, strategy)
                    # Visible to the autoscaler as unmet demand.
                    self._pending_demand[demand_key] = dict(request)
                    remaining = None if deadline is None else deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        raise TimeoutError(
                            f"Could not acquire {request} within timeout; "
                            f"available={self.available_resources()}")
                    if not self._feasible_anywhere_locked(request, strategy):
                        # (feasibility already counts launchable autoscaler
                        # node types — this is a genuine never-fits.)
                        raise InfeasibleError(
                            f"Resource request {request} is infeasible on this cluster "
                            f"(total={self.cluster_resources()})")
                    self._lock.wait(remaining if remaining is not None else 1.0)
            finally:
                self._pending_demand.pop(demand_key, None)

    def try_acquire(self, request: Resources, strategy: Optional[SchedulingStrategy] = None):
        strategy = strategy or DefaultStrategy()
        with self._lock:
            node_id = self._try_place_locked(request, strategy)
            if node_id is None:
                return None
            self._touch_locked(node_id)
            return node_id, self._make_release(node_id, request, strategy)

    def _touch_locked(self, node_id: NodeID) -> None:
        node = self._nodes.get(node_id)
        if node is not None:
            node.last_busy = time.time()

    def _make_release(self, node_id: NodeID, request: Resources,
                      strategy: SchedulingStrategy) -> Callable[[], None]:
        # Idempotence flag is a plain mutable cell, not a threading.Event —
        # an Event allocates a Condition + lock per lease, measurable at
        # task-throughput rates.  The authoritative test-and-set happens
        # under self._lock, so concurrent double-releases can't double-add.
        released = [False]

        def release() -> None:
            if released[0]:
                return
            with self._lock:
                if released[0]:
                    return
                released[0] = True
                if isinstance(strategy, PlacementGroupSchedulingStrategy):
                    pg = self._pgs.get(strategy.placement_group.id)
                    if pg is not None:
                        bundle = self._find_bundle(pg, strategy.bundle_index, request, for_release=True)
                        if bundle is not None:
                            res_add(bundle.available, request)
                else:
                    node = self._nodes.get(node_id)
                    if node is not None:
                        res_add(node.available, request)
                        node.last_busy = time.time()
                self._lock.notify_all()
            self._fire_on_release()

        return release

    # ------------------------------------------------------- autoscaler view
    def report_task_demand(self, key, request: Resources) -> None:
        """Register a resource shape that couldn't be placed (the runtime's
        dispatcher calls this for blocked tasks; blocking acquire() callers
        register themselves)."""
        with self._lock:
            self._pending_demand[key] = dict(request)

    def clear_task_demand(self, key) -> None:
        with self._lock:
            self._pending_demand.pop(key, None)

    def pending_demand(self) -> List[Resources]:
        """Resource shapes currently blocked waiting for capacity."""
        with self._lock:
            return [dict(r) for r in self._pending_demand.values()]

    def pending_pg_demand(self) -> List[List[Resources]]:
        """Bundle lists of placement groups waiting for resources."""
        with self._lock:
            return [[dict(b.resources) for b in pg.bundles]
                    for pg in self._pg_queue]

    def _feasible_anywhere_locked(self, request: Resources, strategy: SchedulingStrategy) -> bool:
        if isinstance(strategy, PlacementGroupSchedulingStrategy):
            pg = self._pgs.get(strategy.placement_group.id)
            if pg is None or pg.state == "REMOVED":
                return False
            bundles = pg.bundles if strategy.bundle_index < 0 else [pg.bundles[strategy.bundle_index]]
            return any(res_fits(b.resources, request) for b in bundles)
        if any(res_fits(n.total, request)
               for n in self._nodes.values() if n.schedulable):
            return True
        # A node the autoscaler could launch also counts as feasible.
        return self.autoscaling_enabled and any(
            res_fits(shape, request) for shape in self.autoscaler_node_shapes)

    # ---------------------------------------------------------------- policies
    def _try_place_locked(self, request: Resources, strategy: SchedulingStrategy) -> Optional[NodeID]:
        if isinstance(strategy, PlacementGroupSchedulingStrategy):
            pg = self._pgs.get(strategy.placement_group.id)
            if pg is None or not pg.ready_event.is_set():
                return None
            bundle = self._find_bundle(pg, strategy.bundle_index, request)
            if bundle is None:
                return None
            res_sub(bundle.available, request)
            return bundle.node_id

        feasible = [n for n in self._nodes.values()
                    if n.schedulable and res_fits(n.available, request)]
        if not feasible:
            return None

        if isinstance(strategy, NodeAffinitySchedulingStrategy):
            node = self._nodes.get(strategy.node_id)
            if node is not None and node.schedulable \
                    and res_fits(node.available, request):
                res_sub(node.available, request)
                return node.id
            if not strategy.soft:
                return None
        elif isinstance(strategy, NodeLabelSchedulingStrategy):
            hard = [n for n in feasible
                    if all(n.labels.get(k) == v for k, v in strategy.hard.items())]
            if not hard:
                return None
            soft = [n for n in hard
                    if all(n.labels.get(k) == v for k, v in strategy.soft.items())]
            feasible = soft or hard
        elif isinstance(strategy, SpreadStrategy):
            self._rr_counter += 1
            node = feasible[self._rr_counter % len(feasible)]
            res_sub(node.available, request)
            return node.id

        # Hybrid default: pack below threshold, else least-utilized; top-k tie-break.
        threshold = GLOBAL_CONFIG.scheduler_spread_threshold
        below = [n for n in feasible if n.utilization() < threshold]
        pool = below or feasible
        pool.sort(key=lambda n: n.utilization())
        k = max(1, int(len(pool) * GLOBAL_CONFIG.scheduler_top_k_fraction))
        node = random.choice(pool[:k])
        res_sub(node.available, request)
        return node.id

    def _find_bundle(self, pg: PlacementGroupState, index: int, request: Resources,
                     for_release: bool = False) -> Optional[_Bundle]:
        if index >= 0:
            b = pg.bundles[index]
            if for_release or res_fits(b.available, request):
                return b
            return None
        for b in pg.bundles:
            if for_release or res_fits(b.available, request):
                return b
        return None

    # ------------------------------------------------------- placement groups
    def create_placement_group(self, pg_id: PlacementGroupID, bundles: List[Resources],
                               strategy: str, name: str = "") -> PlacementGroupState:
        pg = PlacementGroupState(pg_id, bundles, strategy, name)
        with self._lock:
            self._pgs[pg_id] = pg
            if not self._try_commit_pg_locked(pg):
                self._pg_queue.append(pg)
        return pg

    def _retry_pending_pgs_locked(self) -> None:
        still_pending = deque()
        while self._pg_queue:
            pg = self._pg_queue.popleft()
            if pg.state == "REMOVED":
                continue
            if not self._try_commit_pg_locked(pg):
                still_pending.append(pg)
        self._pg_queue = still_pending

    def _try_commit_pg_locked(self, pg: PlacementGroupState) -> bool:
        """2-phase prepare/commit of all bundles, atomically under the lock
        (ref: gcs_placement_group_scheduler 2PC; placement_group_resource_manager.h)."""
        placement = self._plan_bundles_locked(pg)
        if placement is None:
            return False
        for bundle, node in placement:
            res_sub(node.available, bundle.resources)
            bundle.node_id = node.id
            bundle.available = dict(bundle.resources)
        pg.state = "CREATED"
        pg.ready_event.set()
        self._lock.notify_all()
        self._fire_on_release()
        return True

    def _plan_bundles_locked(self, pg: PlacementGroupState):
        nodes = [n for n in self._nodes.values() if n.schedulable]
        if not nodes:
            return None
        scratch = {n.id: dict(n.available) for n in nodes}
        placement = []
        strategy = pg.strategy

        def fit_on(node: Node, bundle: _Bundle) -> bool:
            if res_fits(scratch[node.id], bundle.resources):
                res_sub(scratch[node.id], bundle.resources)
                placement.append((bundle, node))
                return True
            return False

        if strategy == "STRICT_PACK":
            # Prefer ICI-slice locality: try slice-local nodes first, then any
            # single node (all bundles must land together).
            ordered = sorted(nodes, key=lambda n: (n.labels.get("ici-slice", ""), -sum(n.available.values())))
            for node in ordered:
                placement.clear()
                for nid in scratch:
                    scratch[nid] = dict(self._nodes[nid].available)
                if all(fit_on(node, b) for b in pg.bundles):
                    return placement
            return None
        if strategy == "STRICT_SPREAD":
            if len(nodes) < len(pg.bundles):
                return None
            used = set()
            for bundle in pg.bundles:
                cands = [n for n in nodes if n.id not in used]
                cands.sort(key=lambda n: n.utilization())
                for node in cands:
                    if fit_on(node, bundle):
                        used.add(node.id)
                        break
                else:
                    return None
            return placement
        if strategy == "SPREAD":
            i = 0
            for bundle in pg.bundles:
                for attempt in range(len(nodes)):
                    node = nodes[(i + attempt) % len(nodes)]
                    if fit_on(node, bundle):
                        i += attempt + 1
                        break
                else:
                    return None
            return placement
        # PACK (default): fill nodes in ICI-slice order.
        ordered = sorted(nodes, key=lambda n: (n.labels.get("ici-slice", ""), n.utilization()))
        for bundle in pg.bundles:
            for node in ordered:
                if fit_on(node, bundle):
                    break
            else:
                return None
        return placement

    def remove_placement_group(self, pg_id: PlacementGroupID) -> None:
        with self._lock:
            pg = self._pgs.pop(pg_id, None)
            if pg is None:
                return
            if pg.state == "CREATED":
                for bundle in pg.bundles:
                    if bundle.node_id is not None:
                        node = self._nodes.get(bundle.node_id)
                        if node is not None:
                            res_add(node.available, bundle.resources)
            pg.state = "REMOVED"
            self._lock.notify_all()
        self._fire_on_release()

    def get_placement_group(self, pg_id: PlacementGroupID) -> Optional[PlacementGroupState]:
        with self._lock:
            return self._pgs.get(pg_id)

    def placement_groups(self) -> List[PlacementGroupState]:
        with self._lock:
            return list(self._pgs.values())


class InfeasibleError(RuntimeError):
    pass
