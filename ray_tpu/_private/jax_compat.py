"""Compat shims for jax surfaces that moved or were renamed across releases.

The codebase targets the current spellings — ``jax.shard_map`` (with
``check_vma``/``axis_names``), ``jax.sharding.get_abstract_mesh``,
``lax.axis_size`` — but must degrade to the older ones
(``jax.experimental.shard_map`` with ``check_rep``/``auto``, the
resource-env mesh installed by ``with mesh:``, ``psum(1, axis)``) instead
of dying with an ImportError/AttributeError mid-task on an older install.
Every shim resolves per call so these stay correct across jax reloads in
tests.
"""

from __future__ import annotations

import jax


def get_abstract_mesh():
    """The ambient mesh (jax.set_mesh / `with mesh:`), or None."""
    get = getattr(jax.sharding, "get_abstract_mesh", None)
    if get is not None:
        return get()
    from jax._src.mesh import thread_resources

    phys = thread_resources.env.physical_mesh
    return phys if phys.devices.size else None


def shard_map(f, *, mesh=None, in_specs, out_specs, check_vma=True,
              axis_names=None):
    """jax.shard_map when available; else jax.experimental.shard_map with
    check_vma→check_rep and axis_names→auto (its complement) translated."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma, **kw)
    from jax.experimental.shard_map import shard_map as esm

    if mesh is None:
        # Old shard_map cannot resolve the ambient mesh itself.
        mesh = get_abstract_mesh()
    # axis_names (partial-manual) is deliberately NOT translated to the old
    # `auto=` complement: old partial-auto shard_map miscompiles bodies
    # using axis_index (lowers to PartitionId — an XLA CPU CHECK-abort).
    # Full manual is always correct — axes the specs don't mention just see
    # replicated data — at the cost of intra-stage auto sharding here.
    # check_rep unconditionally off: the old checker lacks replication
    # rules for primitives these bodies use (axis_index among them) and
    # it is a static check only — disabling it never changes results.
    return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False, auto=frozenset())


def set_mesh(mesh):
    """Context manager installing ``mesh`` as the ambient mesh.  jax.set_mesh
    is recent; on older jax the Mesh object itself is the context manager
    (it installs the resource-env mesh that old shard_map resolves)."""
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return mesh


def reshard(x, mesh, spec):
    """Host array -> device array sharded over ``mesh`` by ``spec`` — the
    shard round-trip the checkpoint subsystem's elastic restore uses.
    jax.device_put with an explicit NamedSharding is the one placement
    spelling stable across every jax this repo supports; NamedSharding
    itself moved modules over time, so resolve it defensively."""
    try:
        from jax.sharding import NamedSharding
    except ImportError:  # ancient spelling
        from jax.experimental.sharding import NamedSharding  # type: ignore

    import numpy as np

    return jax.device_put(np.asarray(x), NamedSharding(mesh, spec))


def device_put_batch(batch, sharding=None):
    """Transfer a dict-of-columns batch host->device, asynchronously.

    jax.device_put dispatches and returns immediately, so a caller can
    overlap the copy with the step running on the previous batch (the
    ingest double buffer relies on that).  With a ``sharding`` (a
    NamedSharding, e.g. ``parallel.mesh.batch_sharding``) numeric columns
    land already laid out for the step; non-numeric columns (strings,
    objects) stay on host untouched.  A column of lower rank than the
    sharding spec (1-D labels next to 2-D tokens) shards its leading
    axes and replicates the rest — the spec is truncated per column."""
    import numpy as np

    out = {}
    for key, col in batch.items():
        try:
            arr = col if hasattr(col, "dtype") else np.asarray(col)
        except Exception:
            out[key] = col
            continue
        if not hasattr(arr, "dtype") or arr.dtype.kind not in "biufc":
            out[key] = col
            continue
        out[key] = jax.device_put(arr, _fit_sharding(sharding, arr.ndim)) \
            if sharding is not None else jax.device_put(arr)
    return out


def _fit_sharding(sharding, ndim):
    """Truncate a NamedSharding's PartitionSpec to ``ndim`` axes so one
    batch sharding serves every column rank in a dict batch."""
    spec = getattr(sharding, "spec", None)
    if spec is None or len(spec) <= ndim:
        return sharding
    return jax.sharding.NamedSharding(
        sharding.mesh, jax.sharding.PartitionSpec(*spec[:ndim]))


def axis_size(axis_name):
    """lax.axis_size is recent; psum of a constant 1 folds to a static int
    under every version's shard_map/pmap."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)
