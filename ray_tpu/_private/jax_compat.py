"""Compat shims for jax surfaces that moved or were renamed across releases.

The codebase targets the current spellings — ``jax.shard_map`` (with
``check_vma``/``axis_names``), ``jax.sharding.get_abstract_mesh``,
``lax.axis_size`` — but must degrade to the older ones
(``jax.experimental.shard_map`` with ``check_rep``/``auto``, the
resource-env mesh installed by ``with mesh:``, ``psum(1, axis)``) instead
of dying with an ImportError/AttributeError mid-task on an older install.
Every shim resolves per call so these stay correct across jax reloads in
tests.
"""

from __future__ import annotations

import sys
import time

import jax


def get_abstract_mesh():
    """The ambient mesh (jax.set_mesh / `with mesh:`), or None."""
    get = getattr(jax.sharding, "get_abstract_mesh", None)
    if get is not None:
        return get()
    from jax._src.mesh import thread_resources

    phys = thread_resources.env.physical_mesh
    return phys if phys.devices.size else None


def shard_map(f, *, mesh=None, in_specs, out_specs, check_vma=True,
              axis_names=None):
    """jax.shard_map when available; else jax.experimental.shard_map with
    check_vma→check_rep and axis_names→auto (its complement) translated."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma, **kw)
    from jax.experimental.shard_map import shard_map as esm

    if mesh is None:
        # Old shard_map cannot resolve the ambient mesh itself.
        mesh = get_abstract_mesh()
    # axis_names (partial-manual) is deliberately NOT translated to the old
    # `auto=` complement: old partial-auto shard_map miscompiles bodies
    # using axis_index (lowers to PartitionId — an XLA CPU CHECK-abort).
    # Full manual is always correct — axes the specs don't mention just see
    # replicated data — at the cost of intra-stage auto sharding here.
    # check_rep unconditionally off: the old checker lacks replication
    # rules for primitives these bodies use (axis_index among them) and
    # it is a static check only — disabling it never changes results.
    return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False, auto=frozenset())


def set_mesh(mesh):
    """Context manager installing ``mesh`` as the ambient mesh.  jax.set_mesh
    is recent; on older jax the Mesh object itself is the context manager
    (it installs the resource-env mesh that old shard_map resolves)."""
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return mesh


def reshard(x, mesh, spec):
    """Host array -> device array sharded over ``mesh`` by ``spec`` — the
    shard round-trip the checkpoint subsystem's elastic restore uses.
    jax.device_put with an explicit NamedSharding is the one placement
    spelling stable across every jax this repo supports; NamedSharding
    itself moved modules over time, so resolve it defensively."""
    try:
        from jax.sharding import NamedSharding
    except ImportError:  # ancient spelling
        from jax.experimental.sharding import NamedSharding  # type: ignore

    import numpy as np

    return jax.device_put(np.asarray(x), NamedSharding(mesh, spec))


def _telemetry():
    """The device-telemetry module iff something already imported it —
    the cross-layer probe idiom (train profiler hooks work the same way)
    keeps this compat layer import-free and the no-observer cost at one
    dict miss."""
    return sys.modules.get("ray_tpu.util.device_telemetry")


class InstrumentedJit:
    """``jax.jit`` with a compile tap: every trace/lower/compile is timed
    and recorded into :mod:`ray_tpu.util.device_telemetry` with a
    classified trigger (first_compile / shape_change / sharding_change /
    donation_change).

    Uses the AOT path — ``jitted.lower(*args)`` (trace+lower wall) then
    ``.compile()`` (compile wall) — cached per abstract signature, so the
    steady-state call is one tuple-build + dict hit + compiled dispatch
    (the bench_profiler A/B gates this at <=1% of a GPT-2 train step).
    Positional args only, matching how the repo calls its jitted steps.
    """

    def __init__(self, fn, *, label=None, donate_argnums=(), **jit_kwargs):
        self._jitted = jax.jit(fn, donate_argnums=donate_argnums,
                               **jit_kwargs)
        self.label = label or getattr(fn, "__name__", "jit_fn")
        self._donation = tuple(donate_argnums) if donate_argnums else ()
        self._cache = {}

    @staticmethod
    def _signature(args):
        """(shapes, shardings) abstract signature of positional args:
        array leaves key by shape+dtype (+ the pytree structure), python
        scalars by type (jit traces them — a changed value is not a
        changed signature), shardings by the sharding objects themselves
        (hashable, equality = same committed placement).  Raw objects,
        not reprs — repr of a sharding walks its device list and would
        dominate the steady-state dispatch the bench gates at <=1%."""
        leaves, treedef = jax.tree_util.tree_flatten(args)
        shapes = []
        shardings = []
        for leaf in leaves:
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is None or dtype is None:
                shapes.append(type(leaf).__name__)
                shardings.append(None)
            else:
                shapes.append((tuple(shape), dtype))
                shardings.append(getattr(leaf, "sharding", None))
        return (tuple(shapes), treedef), tuple(shardings)

    def __call__(self, *args):
        shapes, shardings = self._signature(args)
        key = (shapes, shardings)
        compiled = self._cache.get(key)
        if compiled is None:
            t0 = time.perf_counter()
            lowered = self._jitted.lower(*args)
            t1 = time.perf_counter()
            compiled = lowered.compile()
            t2 = time.perf_counter()
            self._cache[key] = compiled
            from ray_tpu.util import device_telemetry

            device_telemetry.record_compile(
                self.label, shapes=shapes, shardings=shardings,
                donation=self._donation, trace_s=t1 - t0,
                compile_s=t2 - t1)
        return compiled(*args)


def instrumented_jit(fn, *, label=None, donate_argnums=(), **jit_kwargs):
    """Drop-in for ``jax.jit(fn, donate_argnums=...)`` that records every
    compile into the device-telemetry plane (see :class:`InstrumentedJit`)."""
    return InstrumentedJit(fn, label=label, donate_argnums=donate_argnums,
                           **jit_kwargs)


def device_put_batch(batch, sharding=None, *, transfer_src="device_put_batch"):
    """Transfer a dict-of-columns batch host->device, asynchronously.

    jax.device_put dispatches and returns immediately, so a caller can
    overlap the copy with the step running on the previous batch (the
    ingest double buffer relies on that).  With a ``sharding`` (a
    NamedSharding, e.g. ``parallel.mesh.batch_sharding``) numeric columns
    land already laid out for the step; non-numeric columns (strings,
    objects) stay on host untouched.  A column of lower rank than the
    sharding spec (1-D labels next to 2-D tokens) shards its leading
    axes and replicates the rest — the spec is truncated per column.

    Numeric columns dispatched are ledgered (direction h2d, bytes,
    ``transfer_src``) into the device-telemetry plane when it is loaded —
    probed, not imported, so the no-observer cost is one dict miss."""
    import numpy as np

    out = {}
    nbytes = 0
    for key, col in batch.items():
        try:
            arr = col if hasattr(col, "dtype") else np.asarray(col)
        except Exception:
            out[key] = col
            continue
        if not hasattr(arr, "dtype") or arr.dtype.kind not in "biufc":
            out[key] = col
            continue
        out[key] = jax.device_put(arr, _fit_sharding(sharding, arr.ndim)) \
            if sharding is not None else jax.device_put(arr)
        nbytes += int(getattr(arr, "nbytes", 0))
    telemetry = _telemetry()
    if telemetry is not None and nbytes:
        telemetry.record_transfer("h2d", nbytes, src=transfer_src)
    return out


def _fit_sharding(sharding, ndim):
    """Truncate a NamedSharding's PartitionSpec to ``ndim`` axes so one
    batch sharding serves every column rank in a dict batch."""
    spec = getattr(sharding, "spec", None)
    if spec is None or len(spec) <= ndim:
        return sharding
    return jax.sharding.NamedSharding(
        sharding.mesh, jax.sharding.PartitionSpec(*spec[:ndim]))


def axis_size(axis_name):
    """lax.axis_size is recent; psum of a constant 1 folds to a static int
    under every version's shard_map/pmap."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)
