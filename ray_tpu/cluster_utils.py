"""Multi-node cluster harness for tests
(ref: python/ray/cluster_utils.py — Cluster:135, add_node:202, remove_node:286).

Two modes:

* **virtual** (default): nodes are scheduler entries; scheduling semantics
  (spread, affinity, placement groups, spillback) are exercised for real
  while execution stays in this process — the single-box multi-node trick
  the reference's test suite is built on.
* **real=True**: each node is a separate OS process (`python -m ray_tpu
  worker --address=...`) that JOINS this process's head over the node
  manager and RECEIVES dispatched tasks/actors, with results riding the
  object plane — the reference's `Cluster(add_node)` spawning raylet
  processes (ref: node_manager.h:117).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Dict, Optional

import ray_tpu
from ray_tpu._private.ids import NodeID
from ray_tpu._private.runtime import get_runtime


def worker_node_cmd(address: str, num_cpus: float,
                    resources: Optional[Dict[str, float]] = None,
                    labels: Optional[Dict[str, str]] = None,
                    node_id: Optional[str] = None) -> list:
    """Command line for a worker-node process joining ``address`` (shared
    by the test harness and node providers, so a new worker flag cannot
    silently drift between them)."""
    import json

    cmd = [sys.executable, "-m", "ray_tpu", "worker",
           "--address", address,
           "--num-cpus", str(num_cpus),
           "--resources", json.dumps(resources or {})]
    if node_id:
        cmd += ["--node-id", str(node_id)]
    if labels:
        cmd += ["--labels"] + [f"{k}={v}" for k, v in labels.items()]
    return cmd


def worker_node_env() -> Dict[str, str]:
    """Environment for a spawned worker-node process on THIS host.

    Forces CPU jax (a second process grabbing the one TPU chip wedges
    both), scrubs the driver host's accelerator-plugin env (node processes
    simulate OTHER hosts; inherited PJRT plugin state silently degrades
    their multi-process jax), and guarantees this ray_tpu checkout is
    importable."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    for key in list(env):
        if key.startswith(("TPU_", "AXON_", "_AXON", "PALLAS_AXON")) \
                or key == "PJRT_LIBRARY_PATH":
            del env[key]
    if "PYTHONPATH" in env:
        # Only the plugin's sitecustomize dir is dropped (exact basename
        # match — a bare substring test would eat unrelated user paths).
        parts = [p for p in env["PYTHONPATH"].split(os.pathsep)
                 if p and os.path.basename(p.rstrip("/")) != ".axon_site"]
        if parts:
            env["PYTHONPATH"] = os.pathsep.join(parts)
        else:
            del env["PYTHONPATH"]
    # Node processes must import THIS ray_tpu even when the driver got it
    # via sys.path (dev checkout driven from a scratch cwd).
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(
        ray_tpu.__file__)))
    existing = env.get("PYTHONPATH", "")
    if pkg_root not in existing.split(os.pathsep):
        env["PYTHONPATH"] = (pkg_root + os.pathsep + existing).rstrip(
            os.pathsep)
    return env


class Cluster:
    def __init__(self, initialize_head: bool = False,
                 head_node_args: Optional[dict] = None,
                 real: bool = False):
        self.real = real
        self.head_node_id: Optional[NodeID] = None
        self._nodes: Dict[NodeID, dict] = {}
        self._procs: Dict[NodeID, subprocess.Popen] = {}
        self.node_address: str = ""
        if initialize_head:
            args = dict(head_node_args or {})
            runtime = ray_tpu.init(ignore_reinit_error=True, **args)
            self.head_node_id = runtime.head_node_id
            self._nodes[self.head_node_id] = args
        if real:
            self.node_address = get_runtime().start_node_server()

    def add_node(self, num_cpus: float = 1, num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 wait: bool = True) -> NodeID:
        runtime = get_runtime()
        node_resources = {"CPU": float(num_cpus)}
        if num_tpus:
            node_resources["TPU"] = float(num_tpus)
        node_resources.update(resources or {})
        if not self.real:
            node_id = runtime.scheduler.add_node(node_resources, labels)
            self._nodes[node_id] = node_resources
            return node_id

        if not self.node_address:
            self.node_address = runtime.start_node_server()
        node_id = NodeID.from_random()
        cmd = worker_node_cmd(
            self.node_address, num_cpus,
            {k: v for k, v in node_resources.items() if k != "CPU"},
            labels, str(node_id))
        env = worker_node_env()
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)
        self._procs[node_id] = proc
        self._nodes[node_id] = node_resources
        if wait:
            self.wait_for_node(node_id)
        return node_id

    def wait_for_node(self, node_id: NodeID, timeout: float = 60.0) -> None:
        """Block until the node registered with the head's scheduler."""
        runtime = get_runtime()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            node = runtime.scheduler.get_node(node_id)
            if node is not None and node.alive:
                return
            proc = self._procs.get(node_id)
            if proc is not None and proc.poll() is not None:
                out, err = proc.communicate()
                raise RuntimeError(
                    f"worker node {node_id} exited rc={proc.returncode}:\n"
                    f"{out}\n{err}")
            time.sleep(0.05)
        raise TimeoutError(f"node {node_id} did not join within {timeout}s")

    def remove_node(self, node_id: NodeID, allow_graceful: bool = True) -> None:
        proc = self._procs.pop(node_id, None)
        if proc is not None:
            # Real node: kill the OS process; the head notices the dropped
            # connection and runs node-death recovery (the point of the
            # chaos tests).
            proc.kill()
            proc.wait(timeout=30)
        else:
            get_runtime().scheduler.remove_node(node_id)
        self._nodes.pop(node_id, None)

    def shutdown(self) -> None:
        for node_id in list(self._procs):
            self.remove_node(node_id)
        ray_tpu.shutdown()
        self._nodes.clear()
