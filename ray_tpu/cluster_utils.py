"""In-process multi-node cluster simulation for tests
(ref: python/ray/cluster_utils.py — Cluster:135, add_node:202, remove_node:286).

Nodes here are virtual scheduler nodes: scheduling semantics (spread,
affinity, placement groups, spillback) are exercised for real while execution
stays on this host — the same single-box multi-node trick the reference's
test suite is built on.
"""

from __future__ import annotations

from typing import Dict, Optional

import ray_tpu
from ray_tpu._private.ids import NodeID
from ray_tpu._private.runtime import get_runtime


class Cluster:
    def __init__(self, initialize_head: bool = False,
                 head_node_args: Optional[dict] = None):
        self.head_node_id: Optional[NodeID] = None
        self._nodes: Dict[NodeID, dict] = {}
        if initialize_head:
            args = dict(head_node_args or {})
            runtime = ray_tpu.init(ignore_reinit_error=True, **args)
            self.head_node_id = runtime.head_node_id
            self._nodes[self.head_node_id] = args

    def add_node(self, num_cpus: float = 1, num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None) -> NodeID:
        runtime = get_runtime()
        node_resources = {"CPU": float(num_cpus)}
        if num_tpus:
            node_resources["TPU"] = float(num_tpus)
        node_resources.update(resources or {})
        node_id = runtime.scheduler.add_node(node_resources, labels)
        self._nodes[node_id] = node_resources
        return node_id

    def remove_node(self, node_id: NodeID) -> None:
        get_runtime().scheduler.remove_node(node_id)
        self._nodes.pop(node_id, None)

    def shutdown(self) -> None:
        ray_tpu.shutdown()
        self._nodes.clear()
