"""ray_tpu.collective — collective communication on XLA/ICI.

API-compatible with the reference's ``ray.util.collective``
(ref: python/ray/util/collective/collective.py — GroupManager:40,
init_collective_group:120, allreduce:258, reduce:311, broadcast:373,
allgather:423, reducescatter:472, send:531, recv:594), with the NCCL/Gloo
backends replaced by a single "xla" backend whose ops compile to ICI
collectives (see xla_group.py).  Rank identity comes from the calling
actor/task's declared rank (passed at init), exactly like the reference.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.collective.xla_group import ReduceOp, XLACollectiveGroup

_local = threading.local()


class GroupManager:
    """(ref: collective.py:40 GroupManager)"""

    def __init__(self) -> None:
        self._groups: Dict[str, XLACollectiveGroup] = {}
        # Rank bindings per (group, actor_id): an actor's methods may run on
        # different threads than its __init__, so rank identity hangs off the
        # actor, with thread-local as the fallback for plain tasks.
        self._actor_ranks: Dict[Tuple[str, str], int] = {}
        self._lock = threading.Lock()

    def bind_actor_rank(self, group_name: str, actor_id: str, rank: int) -> None:
        with self._lock:
            self._actor_ranks[(group_name, actor_id)] = rank

    def actor_rank(self, group_name: str, actor_id: str) -> Optional[int]:
        return self._actor_ranks.get((group_name, actor_id))

    def create_group(self, group_name: str, world_size: int,
                     devices: Optional[List[Any]] = None,
                     timeout_s=None, backend: str = "xla") -> XLACollectiveGroup:
        with self._lock:
            group = self._groups.get(group_name)
            if group is None:
                from ray_tpu.collective.dcn_group import (
                    DCNCollectiveGroup,
                    multiprocess_world,
                )

                # Multi-process rank layout (jax.distributed, ONE rank per
                # process — world_size == process_count): collectives must be
                # global SPMD programs, not in-process rendezvous — the other
                # ranks live in other OS processes.  Any other layout (more
                # ranks than processes = thread-tier workers sharing a
                # process, possibly mesh-joined to a jax.distributed cluster)
                # keeps the in-process tier; backend="xla_local" forces it.
                nproc = multiprocess_world()
                if nproc > 1 and world_size == nproc and backend != "xla_local":
                    group = DCNCollectiveGroup(group_name, world_size, devices,
                                               timeout_s=timeout_s)
                else:
                    group = XLACollectiveGroup(group_name, world_size, devices,
                                               timeout_s=timeout_s)
                self._groups[group_name] = group
            elif group.world_size != world_size:
                raise ValueError(
                    f"Group '{group_name}' exists with world_size={group.world_size}")
            elif timeout_s is not None:
                # Group already materialized by another rank: honor the
                # explicit per-group override anyway instead of silently
                # keeping whatever the first creator got.
                group.timeout_s = float(timeout_s)
            return group

    def get_group(self, group_name: str) -> XLACollectiveGroup:
        group = self._groups.get(group_name)
        if group is None:
            raise ValueError(
                f"Collective group '{group_name}' is not initialized; call "
                f"init_collective_group() in every participating worker first.")
        return group

    def destroy_group(self, group_name: str) -> None:
        with self._lock:
            group = self._groups.pop(group_name, None)
            if group is not None:
                group.destroy()
            for key in [k for k in self._actor_ranks if k[0] == group_name]:
                del self._actor_ranks[key]

    def reform_group(self, group_name: str, world_size: int,
                     backend: str = "xla",
                     timeout_s=None) -> XLACollectiveGroup:
        """Re-form a group at a NEW world size (elastic shrink/grow).

        Atomic under the manager lock: the old group (any size) is
        destroyed — waking every rank blocked in one of its rendezvous
        with a destroyed-group error — its stale actor-rank bindings are
        dropped, and a fresh group of ``world_size`` takes its name.
        Surviving workers re-bind via init_collective_group with their
        new ranks.  A no-op create when the name was never materialized,
        so the trainer can call it unconditionally at attempt start.
        """
        with self._lock:
            group = self._groups.pop(group_name, None)
            if group is not None:
                group.destroy()
            for key in [k for k in self._actor_ranks if k[0] == group_name]:
                del self._actor_ranks[key]
        return self.create_group(group_name, world_size, timeout_s=timeout_s,
                                 backend=backend)


_manager = GroupManager()


def _ctx_rank(group_name: str, rank: Optional[int]) -> int:
    if rank is not None:
        return rank
    from ray_tpu._private.runtime import current_task_context

    ctx = current_task_context()
    if ctx is not None and ctx.actor_id is not None:
        bound = _manager.actor_rank(group_name, str(ctx.actor_id))
        if bound is not None:
            return bound
    ranks = getattr(_local, "ranks", None)
    if ranks is None or group_name not in ranks:
        raise ValueError(
            "No rank bound for this worker. Actors: call init_collective_group "
            "in __init__ (binding is per-actor). Plain tasks: init and use the "
            "collective within the SAME task call, or pass rank= explicitly — "
            "task-thread bindings do not persist across task invocations.")
    return ranks[group_name]


def init_collective_group(world_size: int, rank: int, backend: str = "xla",
                          group_name: str = "default",
                          timeout_s=None) -> None:
    """Declare this worker a member of the group (ref: collective.py:120).

    Unlike the NCCL backend there is no unique-id rendezvous over an actor
    store: the xla backend's group is materialized on first use, and the
    calling thread is bound to ``rank`` for subsequent collective calls.

    When this process is one rank of a jax.distributed cluster (one rank per
    process), the group's ops run as global SPMD programs over DCN/ICI
    (dcn_group.py); ``backend="xla_local"`` opts out, forcing the in-process
    thread-rendezvous tier regardless.
    """
    if backend not in ("xla", "tpu", "ici", "xla_local"):
        raise ValueError(f"Unsupported backend '{backend}'; the TPU-native backend is 'xla'")
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world_size {world_size}")
    _manager.create_group(group_name, world_size, timeout_s=timeout_s,
                          backend=backend)
    from ray_tpu._private.runtime import current_task_context

    ctx = current_task_context()
    if ctx is not None and ctx.actor_id is not None:
        _manager.bind_actor_rank(group_name, str(ctx.actor_id), rank)
    if getattr(_local, "ranks", None) is None:
        _local.ranks = {}
    _local.ranks[group_name] = rank


def create_collective_group(actors: List[Any], world_size: int, ranks: List[int],
                            backend: str = "xla", group_name: str = "default",
                            timeout_s=None) -> None:
    """Driver-side declaration for a set of actors (ref: collective.py:151).

    Binds each actor's identity to its rank directly in the group manager —
    no per-actor RPC needed since ranks are control-plane state here.
    """
    if len(actors) != len(ranks):
        raise ValueError("actors and ranks must have the same length")
    _manager.create_group(group_name, world_size, timeout_s=timeout_s,
                          backend=backend)
    for actor, rank in zip(actors, ranks):
        _manager.bind_actor_rank(group_name, str(actor._ray_actor_id), rank)


def destroy_collective_group(group_name: str = "default") -> None:
    _manager.destroy_group(group_name)


def reform_collective_group(world_size: int, group_name: str = "default",
                            backend: str = "xla", timeout_s=None) -> None:
    """Re-form ``group_name`` at a new world size (ref: elastic training's
    dynamic world — there is no reference analogue; NCCL groups are
    fixed-size, XLA groups here are control-plane state we can rebuild).
    Blocked ranks of the old group are woken with an error; membership
    re-binds through init_collective_group at the new size."""
    _manager.reform_group(group_name, world_size, backend=backend,
                          timeout_s=timeout_s)


def get_collective_group(group_name: str = "default") -> XLACollectiveGroup:
    return _manager.get_group(group_name)


def allreduce(tensor: Any, group_name: str = "default", op: str = ReduceOp.SUM,
              rank: Optional[int] = None) -> Any:
    """(ref: collective.py:258) — lowers to lax.psum over the group mesh."""
    group = _manager.get_group(group_name)
    return group.allreduce(_ctx_rank(group_name, rank), tensor, op)


def reduce(tensor: Any, dst_rank: int = 0, group_name: str = "default",
           op: str = ReduceOp.SUM, rank: Optional[int] = None) -> Any:
    """(ref: collective.py:311) — allreduce then select (ICI allreduce is the
    native primitive; a rooted reduce saves nothing on a ring)."""
    group = _manager.get_group(group_name)
    r = _ctx_rank(group_name, rank)
    out = group.allreduce(r, tensor, op)
    return out if r == dst_rank else tensor


def broadcast(tensor: Any, src_rank: int = 0, group_name: str = "default",
              rank: Optional[int] = None) -> Any:
    """(ref: collective.py:373)"""
    group = _manager.get_group(group_name)
    return group.broadcast(_ctx_rank(group_name, rank), tensor, src_rank)


def allgather(tensor: Any, group_name: str = "default",
              rank: Optional[int] = None) -> Any:
    """(ref: collective.py:423) — returns stacked (world_size, ...) array."""
    group = _manager.get_group(group_name)
    return group.allgather(_ctx_rank(group_name, rank), tensor)


def reducescatter(tensor: Any, group_name: str = "default", op: str = ReduceOp.SUM,
                  rank: Optional[int] = None) -> Any:
    """(ref: collective.py:472) — input dim0 must equal world_size."""
    group = _manager.get_group(group_name)
    return group.reducescatter(_ctx_rank(group_name, rank), tensor, op)


def send(tensor: Any, dst_rank: int, group_name: str = "default",
         rank: Optional[int] = None) -> Any:
    """(ref: collective.py:531) — paired with recv as one ppermute round."""
    group = _manager.get_group(group_name)
    r = _ctx_rank(group_name, rank)
    return group.send_recv(r, tensor, [(r, dst_rank)])


def recv(tensor: Any, src_rank: int, group_name: str = "default",
         rank: Optional[int] = None) -> Any:
    """(ref: collective.py:594)"""
    group = _manager.get_group(group_name)
    r = _ctx_rank(group_name, rank)
    return group.send_recv(r, tensor, [(src_rank, r)])


def barrier(group_name: str = "default", rank: Optional[int] = None) -> None:
    group = _manager.get_group(group_name)
    group.barrier(_ctx_rank(group_name, rank))


__all__ = [
    "ReduceOp", "init_collective_group", "create_collective_group",
    "destroy_collective_group", "reform_collective_group",
    "get_collective_group", "allreduce", "reduce",
    "broadcast", "allgather", "reducescatter", "send", "recv", "barrier",
]
