"""DCN-tier collective group — multi-controller SPMD collectives.

When the ranks of a collective group are separate OS processes joined via
``jax.distributed`` (one rank per process — the multi-host trainer layout),
the in-process rendezvous of ``XLACollectiveGroup`` cannot see the other
ranks.  This group instead runs every op as the SAME compiled SPMD program on
every process: each rank's contribution becomes its process-local shard of a
global array (``jax.make_array_from_process_local_data``) and the op body is
a ``shard_map`` collective (`psum`, `all_gather`, `psum_scatter`,
`ppermute`) over a 1-D ``ranks`` mesh spanning one device per process — XLA
schedules the transfer over ICI within a slice and DCN across hosts.

This is the TPU-native replacement for the reference's *cross-host* backends
(ref: python/ray/util/collective/collective_group/nccl_collective_group.py
multi-node NCCL groups; gloo_collective_group.py CPU tier): no NCCL
communicators, no gloo contexts — one compiled program per (op, shape,
dtype), the same program single-host groups use, just over a multi-process
device set.

SPMD contract (differs from the thread-tier group): every rank must issue
the SAME sequence of collective calls — these are global programs, so a rank
that skips a call deadlocks the others, exactly like raw `jax.distributed`
(and exactly like NCCL).  The exception is ``send_recv``, which moves host
bytes through the jax.distributed KV store so 2-party exchanges don't need
the full group; on TPU the performant path for p2p pipelines is `ppermute`
inside your own jitted step, not this op.
"""

from __future__ import annotations

import base64
import pickle
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.collective.xla_group import ReduceOp, _lax_reduce


def multiprocess_world() -> int:
    """Number of jax.distributed processes, 0 if not a multi-process run.

    Reads jax's distributed global state WITHOUT touching the backend (so
    calling this never triggers device initialization)."""
    try:
        from jax._src import distributed as jdist

        state = jdist.global_state
        if state.client is None:
            return 0
        return int(state.num_processes or 0)
    except Exception:  # pragma: no cover - jax internals moved
        return 0


def _kv_client():
    from jax._src import distributed as jdist

    client = jdist.global_state.client
    if client is None:
        raise RuntimeError("jax.distributed is not initialized")
    return client


class DCNCollectiveGroup:
    """One collective group across jax.distributed processes.

    Mirrors XLACollectiveGroup's (rank, array) call surface so
    ``ray_tpu.collective.*`` works unchanged in multi-host trainer workers.
    """

    def __init__(self, group_name: str, world_size: int,
                 devices: Optional[List[Any]] = None,
                 timeout_s: Optional[float] = None):
        import jax

        from ray_tpu._private.config import GLOBAL_CONFIG

        self.group_name = group_name
        self.world_size = world_size
        self.timeout_s = float(timeout_s if timeout_s is not None
                               else GLOBAL_CONFIG.collective_timeout_s)
        nproc = jax.process_count()
        if world_size != nproc:
            raise ValueError(
                f"multi-process collective group '{group_name}': world_size "
                f"{world_size} must equal jax.process_count() {nproc} (one "
                f"rank per process; for multiple ranks in one process use "
                f"the in-process tier)")
        # One device per process, ordered by process index — the 'ranks' axis.
        per_proc: Dict[int, Any] = {}
        for d in sorted(jax.devices(), key=lambda d: (d.process_index, d.id)):
            per_proc.setdefault(d.process_index, d)
        self.devices = [per_proc[i] for i in range(world_size)]
        self._mesh = jax.sharding.Mesh(np.array(self.devices), ("ranks",))
        self._compiled: Dict[Tuple, Any] = {}
        self._lock = threading.Lock()
        self._p2p_seq: Dict[Tuple, int] = {}

    # ------------------------------------------------------------ helpers
    def _check_rank(self, rank: int) -> None:
        import jax

        if rank != jax.process_index():
            raise ValueError(
                f"rank {rank} called a DCN collective from process "
                f"{jax.process_index()} — in multi-process groups the rank IS "
                f"the process index (one rank per process)")

    def _global(self, local_block: np.ndarray):
        """This process's (1, *shape) block as a (world, *shape) global array."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(self._mesh, P("ranks"))
        global_shape = (self.world_size,) + tuple(local_block.shape[1:])
        return jax.make_array_from_process_local_data(
            sharding, local_block, global_shape)

    def _get_compiled(self, key: Tuple, builder):
        with self._lock:
            fn = self._compiled.get(key)
            if fn is None:
                fn = builder()
                self._compiled[key] = fn
            return fn

    @staticmethod
    def _local(out) -> np.ndarray:
        """This process's shard of a mesh-sharded output."""
        return np.asarray(out.addressable_shards[0].data)

    # --------------------------------------------------------- collectives
    def allreduce(self, rank: int, array: Any, op: str = ReduceOp.SUM) -> Any:
        import jax
        from ray_tpu._private.jax_compat import shard_map
        from jax.sharding import PartitionSpec as P

        self._check_rank(rank)
        if op == ReduceOp.PRODUCT:
            # exp(psum(log)) is wrong for negative/zero inputs — gather and
            # reduce host-side (same policy as the in-process group).
            stacked = self.allgather(rank, array)
            return np.prod(np.asarray(stacked), axis=0)
        x = np.asarray(array)[None]
        key = ("allreduce", op, x.shape, str(x.dtype))

        def build():
            return jax.jit(shard_map(
                lambda b: _lax_reduce(b, op, "ranks"), mesh=self._mesh,
                in_specs=P("ranks"), out_specs=P("ranks")))

        out = self._get_compiled(key, build)(self._global(x))
        return self._local(out)[0]

    def allgather(self, rank: int, array: Any) -> Any:
        import jax
        from jax import lax
        from ray_tpu._private.jax_compat import shard_map
        from jax.sharding import PartitionSpec as P

        self._check_rank(rank)
        x = np.asarray(array)[None]
        key = ("allgather", x.shape, str(x.dtype))

        def build():
            # check_vma=False: the gathered output is replicated by
            # construction, which the static VMA check cannot infer.
            return jax.jit(shard_map(
                lambda b: lax.all_gather(b, "ranks", axis=0, tiled=True),
                mesh=self._mesh, in_specs=P("ranks"), out_specs=P(),
                check_vma=False))

        out = self._get_compiled(key, build)(self._global(x))
        return self._local(out)  # replicated: local copy is the full stack

    def reducescatter(self, rank: int, array: Any, op: str = ReduceOp.SUM) -> Any:
        import jax
        from jax import lax
        from ray_tpu._private.jax_compat import shard_map
        from jax.sharding import PartitionSpec as P

        self._check_rank(rank)
        x = np.asarray(array)
        if x.shape[0] != self.world_size:
            raise ValueError(
                f"reducescatter input dim0 ({x.shape[0]}) must equal "
                f"world_size ({self.world_size})")
        if op == ReduceOp.PRODUCT:
            stacked = self.allgather(rank, x)  # (world, world, *s)
            return np.prod(np.asarray(stacked), axis=0)[rank]
        x = x[None]  # (1, world, *s): this rank's full contribution
        key = ("reducescatter", op, x.shape, str(x.dtype))

        def build():
            def body(b):
                y = b[0]  # (world, *s)
                if op == ReduceOp.SUM:
                    return lax.psum_scatter(
                        y, "ranks", scatter_dimension=0, tiled=True)
                reduced = _lax_reduce(y, op, "ranks")
                idx = lax.axis_index("ranks")
                return lax.dynamic_slice_in_dim(reduced, idx, 1, axis=0)

            return jax.jit(shard_map(
                body, mesh=self._mesh, in_specs=P("ranks"),
                out_specs=P("ranks")))

        out = self._get_compiled(key, build)(self._global(x))
        return self._local(out)[0]

    def broadcast(self, rank: int, array: Any, src_rank: int = 0) -> Any:
        import jax
        import jax.numpy as jnp
        from jax import lax
        from ray_tpu._private.jax_compat import shard_map
        from jax.sharding import PartitionSpec as P

        self._check_rank(rank)
        x = np.asarray(array)[None]
        key = ("broadcast", src_rank, x.shape, str(x.dtype))

        def build():
            def body(b):
                idx = lax.axis_index("ranks")
                contrib = jnp.where(idx == src_rank, b, jnp.zeros_like(b))
                return lax.psum(contrib, "ranks")

            return jax.jit(shard_map(
                body, mesh=self._mesh, in_specs=P("ranks"), out_specs=P(),
                check_vma=False))

        out = self._get_compiled(key, build)(self._global(x))
        return self._local(out)[0]

    def barrier(self, rank: int) -> None:
        self.allreduce(rank, np.zeros((1,), np.float32))

    # ---------------------------------------------------------------- p2p
    def send_recv(self, rank: int, array: Any, perm: List[Tuple[int, int]]) -> Any:
        """Point-to-point exchange through the jax.distributed KV store.

        Host-side by design: only the ranks named in ``perm`` participate, so
        a compiled global program (which needs every process) cannot express
        it.  Bulk p2p on TPU belongs inside jitted steps as `ppermute`; this
        op exists for control-plane exchanges (ref: collective.py:531 send /
        :594 recv semantics)."""
        self._check_rank(rank)
        participants = sorted({r for pair in perm for r in pair})
        if rank not in participants:
            raise ValueError(f"rank {rank} is not part of perm {perm}")
        client = _kv_client()
        timeout_ms = int(self.timeout_s * 1000)
        out: Any = np.zeros_like(np.asarray(array))
        for src, dst in perm:
            with self._lock:
                seq = self._p2p_seq.get((src, dst), 0)
                self._p2p_seq[(src, dst)] = seq + 1
            key = f"ray_tpu/{self.group_name}/p2p/{src}-{dst}/{seq}"
            if rank == src:
                payload = base64.b64encode(
                    pickle.dumps(np.asarray(array))).decode()
                client.key_value_set(key, payload)
            if rank == dst:
                payload = client.blocking_key_value_get(key, timeout_ms)
                out = pickle.loads(base64.b64decode(payload))
                try:
                    client.key_value_delete(key)
                except Exception:
                    pass
        return out

    def destroy(self) -> None:
        self._compiled.clear()
