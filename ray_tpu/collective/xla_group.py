"""XLA collective group — compiled ICI collectives behind the rank-call API.

TPU-native replacement for the reference's NCCL collective group
(ref: python/ray/util/collective/collective_group/nccl_collective_group.py,
830 LoC of cupy-NCCL calls): a group owns a set of JAX devices arranged in a
1-D `jax.sharding.Mesh`; each rank's call contributes its local array, and the
group executes ONE compiled `shard_map` program whose body is the XLA
collective (`psum`, `all_gather`, `psum_scatter`, `ppermute`), riding ICI —
no NCCL, no cupy, no CUDA streams.

Where the reference's ranks rendezvous via a named-actor unique-id store and
then issue runtime NCCL verbs, ranks here rendezvous in-process (threads of
the multi-controller host process) and the "verb" is a cached jitted program
per (op, shape, dtype): the compiler schedules the transfer, overlaps it, and
fuses surrounding elementwise work.  Groups whose ranks are separate OS
processes (jax.distributed) are built as DCNCollectiveGroup instead — same
call surface, ops compiled as global SPMD programs (see dcn_group.py); the
GroupManager picks the tier automatically.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.util import watchdog as _watchdog


def _profiler_record(bucket: str, start: float, end: float) -> None:
    """Attribute an interval to the train step profiler when one is active
    on this thread (each rank's contribute runs on its worker thread).
    Probed via sys.modules — the collective layer must not import the train
    package (the trainer imports collective, not the reverse), and if the
    profiler module was never imported, none can be active."""
    mod = sys.modules.get("ray_tpu.train.profiler")
    if mod is not None:
        mod.record(bucket, start, end)


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


class _Rendezvous:
    """Collects one contribution per rank, runs the op once, fans results out.

    The in-process analogue of the reference's NCCL rendezvous (unique-id via
    a named actor, nccl_util.py) — here a barrier across the ranks' threads.
    """

    def __init__(self, world_size: int, timeout_s: float = 300.0):
        self.world_size = world_size
        self.timeout_s = timeout_s
        self.lock = threading.Lock()
        self.slots: Dict[int, Any] = {}
        self.arrivals = 0  # counted at lookup under the group lock
        self.done = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None

    def contribute(self, rank: int, value: Any, run_fn, participants=None,
                   on_timeout=None) -> Any:
        # Contribute-to-result wall time is this rank's collective-sync
        # cost: waiting for stragglers plus (on the last rank) the compiled
        # op itself — the step profiler's "collective" bucket.  The hang
        # watchdog tracks the same window as a bounded phase: a rank held
        # inside the rendezvous past the stall threshold is a wedge the
        # liveness poll cannot see (the thread is alive, just waiting).
        w0 = time.time()
        _watchdog.phase_enter(f"collective:rank{rank}", "rendezvous")
        try:
            return self._contribute(rank, value, run_fn, participants,
                                    on_timeout)
        finally:
            _watchdog.phase_exit(f"collective:rank{rank}")
            _profiler_record("collective", w0, time.time())

    def _contribute(self, rank: int, value: Any, run_fn, participants=None,
                    on_timeout=None) -> Any:
        members = participants if participants is not None else list(range(self.world_size))
        with self.lock:
            if rank in self.slots:
                raise ValueError(f"rank {rank} contributed twice to collective")
            self.slots[rank] = value
            is_last = len(self.slots) == len(members)
        if is_last:
            try:
                self.result = run_fn({r: self.slots[r] for r in members})
            except BaseException as e:  # noqa: BLE001
                self.error = e
            finally:
                self.done.set()
        else:
            if not self.done.wait(timeout=self.timeout_s):
                # Withdraw our contribution so a retry of this round is clean
                # instead of hitting "contributed twice" on a wedged group.
                with self.lock:
                    self.slots.pop(rank, None)
                if on_timeout is not None:
                    on_timeout(self)
                raise TimeoutError(
                    f"collective rendezvous timed out: {len(self.slots)}/"
                    f"{len(members)} participants arrived")
        if self.error is not None:
            raise self.error
        return self.result


class XLACollectiveGroup:
    def __init__(self, group_name: str, world_size: int,
                 devices: Optional[List[Any]] = None,
                 timeout_s: Optional[float] = None):
        import jax

        from ray_tpu._private.config import GLOBAL_CONFIG

        #: Rendezvous bound: a lost rank fails the OTHERS after this long
        #: instead of holding them hostage (r2 weak #8 — the 300 s constant
        #: was not operator-tunable; elastic trainers want seconds here).
        self.timeout_s = float(timeout_s if timeout_s is not None
                               else GLOBAL_CONFIG.collective_timeout_s)

        all_devices = devices if devices is not None else jax.devices()
        if world_size > len(all_devices):
            # Fewer physical devices than ranks (e.g. 1 real TPU chip, 8-rank
            # group in tests): place multiple ranks per device.  Collectives
            # remain correct but run HOST-SIDE — none of the compiled ICI
            # path is exercised.  Loud, because silently degrading here made
            # 1-chip test hosts "pass" without testing the real programs.
            import warnings

            warnings.warn(
                f"collective group '{group_name}': world_size {world_size} > "
                f"{len(all_devices)} devices — no mesh; ops run host-side, "
                f"the compiled ICI path is NOT exercised",
                RuntimeWarning, stacklevel=2)
            self.devices = [all_devices[i % len(all_devices)] for i in range(world_size)]
            self._oversubscribed = True
        else:
            self.devices = list(all_devices[:world_size])
            self._oversubscribed = False
        self.group_name = group_name
        self.world_size = world_size
        self._mesh = None
        self._compiled: Dict[Tuple, Any] = {}
        self._rendezvous: Dict[Tuple[str, int], _Rendezvous] = {}
        self._rv_lock = threading.Lock()
        self._op_seq: Dict[str, int] = {}

    # ------------------------------------------------------------------ mesh
    def mesh(self):
        """The group's 1-D device mesh (axis name: 'ranks')."""
        import jax

        if self._mesh is None:
            if self._oversubscribed:
                self._mesh = None  # no physical mesh; ops run host-side
            else:
                self._mesh = jax.sharding.Mesh(np.array(self.devices), ("ranks",))
        return self._mesh

    # --------------------------------------------------------------- op cache
    def _get_compiled(self, op_key: Tuple, builder) -> Any:
        fn = self._compiled.get(op_key)
        if fn is None:
            fn = builder()
            self._compiled[op_key] = fn
        return fn

    def _rendezvous_for(self, op: str, n_participants: Optional[int] = None) -> _Rendezvous:
        n = n_participants if n_participants is not None else self.world_size
        with self._rv_lock:
            seq = self._op_seq.get(op, 0)
            key = (op, seq)
            rv = self._rendezvous.get(key)
            if rv is None:
                rv = _Rendezvous(self.world_size, self.timeout_s)
                self._rendezvous[key] = rv
            rv.arrivals += 1
            if rv.arrivals == n:
                # Full round assembled: next lookup starts a fresh round.
                self._op_seq[op] = seq + 1
                self._rendezvous.pop((op, seq - 2), None)  # GC old rounds
            return rv

    def _on_rv_timeout(self, rv: _Rendezvous) -> None:
        with self._rv_lock:
            rv.arrivals = max(0, rv.arrivals - 1)

    # ------------------------------------------------------------ collectives
    def allreduce(self, rank: int, array: Any, op: str = ReduceOp.SUM) -> Any:
        import jax
        import jax.numpy as jnp

        array = jnp.asarray(array)
        rv = self._rendezvous_for(f"allreduce-{op}")

        def run(slots: Dict[int, Any]) -> List[Any]:
            inputs = [slots[r] for r in range(self.world_size)]
            mesh = self.mesh()
            # PRODUCT stays on the host path: the ICI form exp(psum(log)) is
            # wrong for negative/zero inputs.
            if mesh is None or op == ReduceOp.PRODUCT:
                stacked = jnp.stack(inputs)
                out = _host_reduce(stacked, op)
                return [out] * self.world_size
            key = ("allreduce", op, inputs[0].shape, str(inputs[0].dtype))

            def build():
                from ray_tpu._private.jax_compat import shard_map
                from jax.sharding import PartitionSpec as P

                def body(x):
                    # x: (1, *shape) per rank — reduce over the mesh axis.
                    return _lax_reduce(x, op, "ranks")

                return jax.jit(
                    shard_map(
                        body, mesh=mesh,
                        in_specs=P("ranks"), out_specs=P("ranks"),
                    )
                )

            fn = self._get_compiled(key, build)
            out = fn(self._mesh_put(jnp.stack(inputs)))
            return [out[i] for i in range(self.world_size)]

        results = rv.contribute(rank, array, run, on_timeout=self._on_rv_timeout)
        return results[rank]

    def _mesh_put(self, stacked):
        import jax

        return jax.device_put(
            stacked,
            jax.sharding.NamedSharding(
                self.mesh(), jax.sharding.PartitionSpec("ranks")))

    def allgather(self, rank: int, array: Any) -> Any:
        import jax
        import jax.numpy as jnp

        array = jnp.asarray(array)
        rv = self._rendezvous_for("allgather")

        def run(slots: Dict[int, Any]) -> List[Any]:
            inputs = [slots[r] for r in range(self.world_size)]
            mesh = self.mesh()
            if mesh is None:
                out = jnp.stack(inputs)
                return [out] * self.world_size
            key = ("allgather", inputs[0].shape, str(inputs[0].dtype))

            def build():
                from jax import lax
                from ray_tpu._private.jax_compat import shard_map
                from jax.sharding import PartitionSpec as P

                def body(x):
                    # x: (1, *shape) per-rank block; gather the full stack —
                    # identical on every rank, so the output is replicated.
                    return lax.all_gather(x, "ranks", axis=0, tiled=True)

                # check_vma=False: the gather output is replicated by
                # construction, which the static VMA check cannot infer.
                return jax.jit(shard_map(
                    body, mesh=mesh, in_specs=P("ranks"), out_specs=P(),
                    check_vma=False))

            fn = self._get_compiled(key, build)
            out = fn(self._mesh_put(jnp.stack(inputs)))
            return [out] * self.world_size

        results = rv.contribute(rank, array, run, on_timeout=self._on_rv_timeout)
        return results[rank]

    def reducescatter(self, rank: int, array: Any, op: str = ReduceOp.SUM) -> Any:
        """Each rank contributes shape (world, ...); receives its reduced shard."""
        import jax
        import jax.numpy as jnp

        array = jnp.asarray(array)
        if array.shape[0] != self.world_size:
            raise ValueError(
                f"reducescatter input dim0 ({array.shape[0]}) must equal world_size "
                f"({self.world_size})")
        rv = self._rendezvous_for(f"reducescatter-{op}")

        def run(slots: Dict[int, Any]) -> List[Any]:
            inputs = [slots[r] for r in range(self.world_size)]
            mesh = self.mesh()
            if mesh is None or op == ReduceOp.PRODUCT:
                stacked = jnp.stack(inputs)
                reduced = _host_reduce(stacked, op)  # (world, ...)
                return [reduced[i] for i in range(self.world_size)]
            key = ("reducescatter", op, inputs[0].shape, str(inputs[0].dtype))

            def build():
                from jax import lax
                from ray_tpu._private.jax_compat import shard_map
                from jax.sharding import PartitionSpec as P

                def body(x):
                    # x: (1, world, *shape) — this rank's full contribution.
                    y = x[0]
                    if op == ReduceOp.SUM:
                        return lax.psum_scatter(
                            y, "ranks", scatter_dimension=0, tiled=True)
                    # No pmax/pmin-scatter primitive: reduce then keep our row.
                    reduced = _lax_reduce(y, op, "ranks")
                    idx = lax.axis_index("ranks")
                    return lax.dynamic_slice_in_dim(reduced, idx, 1, axis=0)

                return jax.jit(shard_map(
                    body, mesh=mesh, in_specs=P("ranks"), out_specs=P("ranks")))

            fn = self._get_compiled(key, build)
            out = fn(self._mesh_put(jnp.stack(inputs)))  # (world, *shape)
            return [out[i] for i in range(self.world_size)]

        results = rv.contribute(rank, array, run, on_timeout=self._on_rv_timeout)
        return results[rank]

    def broadcast(self, rank: int, array: Any, src_rank: int = 0) -> Any:
        import jax
        import jax.numpy as jnp

        array = jnp.asarray(array)
        rv = self._rendezvous_for(f"broadcast-{src_rank}")

        def run(slots: Dict[int, Any]) -> List[Any]:
            mesh = self.mesh()
            if mesh is None:
                return [slots[src_rank]] * self.world_size
            inputs = [slots[r] for r in range(self.world_size)]
            key = ("broadcast", src_rank, inputs[0].shape, str(inputs[0].dtype))

            def build():
                from jax import lax
                from ray_tpu._private.jax_compat import shard_map
                from jax.sharding import PartitionSpec as P

                def body(x):
                    # Mask all but src's block, then psum — the select+psum
                    # lowering of broadcast (one ICI reduction, replicated out).
                    idx = lax.axis_index("ranks")
                    contrib = jnp.where(idx == src_rank, x, jnp.zeros_like(x))
                    return lax.psum(contrib, "ranks")

                # check_vma=False: psum output is replicated by construction.
                return jax.jit(shard_map(
                    body, mesh=mesh, in_specs=P("ranks"), out_specs=P(),
                    check_vma=False))

            fn = self._get_compiled(key, build)
            out = fn(self._mesh_put(jnp.stack(inputs)))  # (1, *shape) replicated
            return [out[0]] * self.world_size

        results = rv.contribute(rank, array, run, on_timeout=self._on_rv_timeout)
        return results[rank]

    def barrier(self, rank: int) -> None:
        rv = self._rendezvous_for("barrier")
        rv.contribute(rank, 0, lambda slots: [None] * self.world_size,
                      on_timeout=self._on_rv_timeout)

    def send_recv(self, rank: int, array: Any, perm: List[Tuple[int, int]]) -> Any:
        """ppermute-style paired send/recv: perm is [(src, dst), ...].

        Replaces the reference's point-to-point NCCL send/recv
        (collective.py:531,594) with a single collective-permute program —
        the idiomatic ICI form (neighbor exchange rides the ring).
        """
        import jax.numpy as jnp

        array = jnp.asarray(array)
        # Only the ranks named in perm participate — a 2-party send/recv in an
        # 8-rank group must not wait for the other 6.
        participants = sorted({r for pair in perm for r in pair})
        if rank not in participants:
            raise ValueError(f"rank {rank} is not part of perm {perm}")
        rv = self._rendezvous_for(f"sendrecv-{tuple(perm)}", n_participants=len(participants))

        def run(slots: Dict[int, Any]) -> Dict[int, Any]:
            import jax

            template = next(iter(slots.values()))
            mesh = self.mesh()
            if mesh is None:
                out = {r: jnp.zeros_like(template) for r in participants}
                for src, dst in perm:
                    out[dst] = slots[src]
                return out
            # Non-participants contribute zeros; ppermute's non-receivers get
            # zeros back, matching the host-path semantics.
            inputs = [slots.get(r, jnp.zeros_like(template))
                      for r in range(self.world_size)]
            key = ("sendrecv", tuple(perm), template.shape, str(template.dtype))

            def build():
                from jax import lax
                from ray_tpu._private.jax_compat import shard_map
                from jax.sharding import PartitionSpec as P

                def body(x):
                    # The promised single collective-permute program: blocks
                    # move src->dst along the ring in one compiled op.
                    return lax.ppermute(x, "ranks", perm)

                return jax.jit(shard_map(
                    body, mesh=mesh, in_specs=P("ranks"), out_specs=P("ranks")))

            fn = self._get_compiled(key, build)
            out = fn(self._mesh_put(jnp.stack(inputs)))
            return {r: out[r] for r in participants}

        results = rv.contribute(rank, array, run, participants=participants,
                                on_timeout=self._on_rv_timeout)
        return results[rank]

    def destroy(self) -> None:
        # Poison in-flight rounds so blocked participants wake immediately
        # instead of sitting out the 300s rendezvous timeout (matters for
        # elastic restart: the controller destroys the group on failure).
        with self._rv_lock:
            rvs = list(self._rendezvous.values())
            self._rendezvous.clear()
        for rv in rvs:
            if not rv.done.is_set():
                rv.error = RuntimeError(
                    f"collective group '{self.group_name}' was destroyed")
                rv.done.set()
        self._compiled.clear()


def _lax_reduce(x, op: str, axis_name: str):
    from jax import lax

    if op == ReduceOp.SUM:
        return lax.psum(x, axis_name)
    if op == ReduceOp.MAX:
        return lax.pmax(x, axis_name)
    if op == ReduceOp.MIN:
        return lax.pmin(x, axis_name)
    if op == ReduceOp.PRODUCT:
        import jax.numpy as jnp

        return jnp.exp(lax.psum(jnp.log(x), axis_name))
    raise ValueError(f"Unknown reduce op: {op}")


def _host_reduce(stacked, op: str):
    import jax.numpy as jnp

    if op == ReduceOp.SUM:
        return jnp.sum(stacked, axis=0)
    if op == ReduceOp.MAX:
        return jnp.max(stacked, axis=0)
    if op == ReduceOp.MIN:
        return jnp.min(stacked, axis=0)
    if op == ReduceOp.PRODUCT:
        return jnp.prod(stacked, axis=0)
    raise ValueError(f"Unknown reduce op: {op}")
