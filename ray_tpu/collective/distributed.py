"""Multi-process / multi-host bootstrap — the DCN tier.

The reference spans hosts with NCCL/Gloo process groups bootstrapped through
a named-actor rendezvous (ref: python/ray/util/collective/collective_group/
nccl_collective_group.py:40-120 group init; gloo_util.py redis rendezvous).
The TPU-native equivalent is JAX's multi-controller runtime:
``jax.distributed.initialize`` joins this process to a coordinator, after
which ``jax.devices()`` is the GLOBAL device set — meshes built over it span
hosts, and every collective a jitted program contains (psum/all_gather/...)
rides ICI within a slice and DCN across slices, scheduled by XLA.

There is no per-op rendezvous in this tier: all processes run the same SPMD
program (multi-controller), which is the idiomatic JAX scale-out — the
dynamic rank-call API (xla_group.py) remains for intra-process groups.

Env-driven bootstrap (``auto_initialize``) for trainer workers:
  RAY_TPU_COORDINATOR   host:port of process 0
  RAY_TPU_NUM_PROCESSES world size
  RAY_TPU_PROCESS_ID    this process's rank
"""

from __future__ import annotations

import os
from typing import Any, List, Optional

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids: Optional[List[int]] = None) -> None:
    """Join the multi-process runtime.  Must run before any jax backend use.

    On TPU pods the three arguments are inferred from the metadata server
    (jax.distributed's native path); on CPU/test clusters pass them
    explicitly."""
    global _initialized
    import jax

    if _initialized:
        return
    kwargs: dict = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(**kwargs)
    _initialized = True


def auto_initialize() -> bool:
    """Initialize from RAY_TPU_COORDINATOR/... env vars if present (the
    trainer backend's on_start hook calls this on every worker)."""
    addr = os.environ.get("RAY_TPU_COORDINATOR")
    if not addr:
        return False
    initialize(
        coordinator_address=addr,
        num_processes=int(os.environ["RAY_TPU_NUM_PROCESSES"]),
        process_id=int(os.environ["RAY_TPU_PROCESS_ID"]),
    )
    return True


def is_initialized() -> bool:
    return _initialized


def shutdown() -> None:
    global _initialized
    if _initialized:
        import jax

        jax.distributed.shutdown()
        _initialized = False


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def local_batch_to_global(mesh, local_batch: Any, axis: str = "data"):
    """Assemble a process-local batch shard into a global sharded array.

    Each process feeds its slice of the global batch; the result behaves as
    one (global_batch, ...) array sharded over ``axis`` (the multi-host
    input pipeline primitive — ref: the reference's per-worker DataLoader
    feeding DDP ranks)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec(axis))
    return jax.make_array_from_process_local_data(sharding, local_batch)
