"""Public scheduling strategies (ref: python/ray/util/scheduling_strategies.py)."""

from ray_tpu._private.scheduling import (
    DefaultStrategy,
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SpreadStrategy,
)

__all__ = [
    "DefaultStrategy", "NodeAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy", "PlacementGroupSchedulingStrategy",
    "SpreadStrategy",
]
