"""joblib backend over ray_tpu (ref: python/ray/util/joblib/ —
register_ray + RayBackend): `register_ray()` then
`with joblib.parallel_backend("ray_tpu"): ...` runs scikit-learn-style
Parallel() workloads as cluster tasks."""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import ray_tpu


def register_ray() -> None:
    """Register the 'ray_tpu' joblib parallel backend."""
    from joblib.parallel import register_parallel_backend

    register_parallel_backend("ray_tpu", RayTpuBackend)


try:  # joblib is in the image; keep the module importable without it anyway
    from joblib._parallel_backends import (
        AutoBatchingMixin as _AutoBatchingMixin,
        ParallelBackendBase as _ParallelBackendBase,
        PoolManagerMixin as _PoolManagerMixin,
    )
except Exception:  # pragma: no cover
    _ParallelBackendBase = object  # type: ignore[assignment,misc]

    class _AutoBatchingMixin:  # type: ignore[no-redef]
        """Distinct placeholder bases — aliasing all three to ``object``
        would raise 'duplicate base class' at class creation."""

    class _PoolManagerMixin:  # type: ignore[no-redef]
        pass


class RayTpuBackend(_PoolManagerMixin, _AutoBatchingMixin,
                    _ParallelBackendBase):  # type: ignore[valid-type,misc]
    """Each joblib batch becomes one cluster task, dispatched through the
    multiprocessing Pool shim (ref: util/joblib RayBackend, which wraps
    ray.util.multiprocessing.Pool the same way)."""

    supports_timeout = True
    uses_threads = False
    supports_sharedmem = False

    def effective_n_jobs(self, n_jobs: Optional[int]) -> int:
        if n_jobs == 1:
            return 1
        cpus = max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        if n_jobs is None or n_jobs == -1:
            return cpus
        return min(n_jobs, cpus) if n_jobs > 0 else cpus

    def configure(self, n_jobs: int = 1, parallel=None, prefer=None,
                  require=None, **memmapping_args) -> int:
        from ray_tpu.util.multiprocessing import Pool

        ray_tpu.init(ignore_reinit_error=True)
        n_jobs = self.effective_n_jobs(n_jobs)
        self.parallel = parallel
        self._pool = Pool(processes=n_jobs)
        return n_jobs
