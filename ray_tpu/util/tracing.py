"""Distributed tracing: spans around task submit/execute.

Counterpart of the reference's OpenTelemetry integration
(ref: util/tracing/tracing_helper.py — _OpenTelemetryProxy:34,
_is_tracing_enabled:92): opt-in via `enable_tracing()`; when on, every task
submission opens a submit span and every execution opens an execute span
parented on the submitter's span — the trace context rides inside the
TaskSpec exactly like the reference propagates it in its TaskSpec proto.
Spans go to a pluggable exporter (default: in-memory buffer; any callable
taking a span dict works, e.g. one that forwards to an OTLP client).
"""

from __future__ import annotations

import contextvars
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional

from collections import deque

_enabled = False
_exporter: Optional[Callable[[dict], None]] = None
#: Default exporter: bounded ring buffer (2 spans/task would otherwise grow
#: without limit in a long-running driver).
_BUFFER_MAX = 100_000
_buffer: "deque" = deque(maxlen=_BUFFER_MAX)
_buffer_lock = threading.Lock()
_current_span: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_span", default=None)


def is_tracing_enabled() -> bool:
    """(ref: tracing_helper.py:92)."""
    return _enabled


def enable_tracing(exporter: Optional[Callable[[dict], None]] = None) -> None:
    global _enabled, _exporter
    _enabled = True
    _exporter = exporter


def disable_tracing() -> None:
    global _enabled, _exporter
    _enabled = False
    _exporter = None


def exported_spans() -> List[dict]:
    """Spans captured by the default in-memory exporter."""
    with _buffer_lock:
        return list(_buffer)


def clear_spans() -> None:
    with _buffer_lock:
        _buffer.clear()


def _export(span: dict) -> None:
    if _exporter is not None:
        _exporter(span)
    else:
        with _buffer_lock:
            _buffer.append(span)


def current_context() -> Optional[dict]:
    """{"trace_id", "span_id"} of the active span, for propagation."""
    span = _current_span.get()
    if span is None:
        return None
    return {"trace_id": span["trace_id"], "span_id": span["span_id"]}


@contextmanager
def span(name: str, parent: Optional[dict] = None,
         attributes: Optional[Dict[str, Any]] = None):
    """Open a span; nests under the active span unless `parent` is given."""
    if not _enabled:
        yield None
        return
    parent = parent if parent is not None else current_context()
    s = {
        "name": name,
        "trace_id": (parent or {}).get("trace_id") or uuid.uuid4().hex,
        "span_id": uuid.uuid4().hex[:16],
        "parent_id": (parent or {}).get("span_id"),
        "start": time.time(),
        "end": None,
        "attributes": dict(attributes or {}),
        "status": "OK",
    }
    token = _current_span.set(s)
    try:
        yield s
    except BaseException as e:
        s["status"] = f"ERROR: {type(e).__name__}"
        raise
    finally:
        s["end"] = time.time()
        _current_span.reset(token)
        _export(s)


def inject_task_spec(spec) -> None:
    """Called at submit time: stamp the submitter's context onto the spec."""
    if _enabled:
        spec.trace_ctx = current_context()


@contextmanager
def task_execute_span(spec):
    """Execute-side span parented on the submit-side context in the spec
    (the reference wraps the worker's task execution the same way)."""
    if not _enabled:
        yield None
        return
    with span(f"task::{spec.name}",
              parent=getattr(spec, "trace_ctx", None),
              attributes={"task_id": str(spec.task_id),
                          "attempt": spec.attempt}) as s:
        yield s
