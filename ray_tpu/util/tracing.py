"""Distributed tracing: spans around task submit/execute.

Counterpart of the reference's OpenTelemetry integration
(ref: util/tracing/tracing_helper.py — _OpenTelemetryProxy:34,
_is_tracing_enabled:92): opt-in via `enable_tracing()`; when on, every task
submission opens a submit span and every execution opens an execute span
parented on the submitter's span — the trace context rides inside the
TaskSpec exactly like the reference propagates it in its TaskSpec proto.
Spans go to a pluggable exporter (default: in-memory buffer; any callable
taking a span dict works, e.g. one that forwards to an OTLP client).
"""

from __future__ import annotations

import contextvars
import itertools
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from collections import deque

_enabled = False
_exporter: Optional[Callable[[dict], None]] = None
#: Default exporter: bounded ring buffer (2 spans/task would otherwise grow
#: without limit in a long-running driver).
_BUFFER_MAX = 100_000
_buffer: "deque" = deque(maxlen=_BUFFER_MAX)
_buffer_lock = threading.Lock()
_current_span: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_span", default=None)

# Span/trace id generation sits on the serve hot path (several spans per
# request, mostly on the proxy/replica event loops), so uuid4's ~2us of
# os.urandom per id is real QPS: ids here are a random per-process base
# XOR a golden-ratio-mixed atomic counter — ~0.1us, unique within the
# process (odd-constant multiply is a bijection mod 2**64) and across
# processes by the base; the mix spreads the counter into the high bits so
# id prefixes (e.g. the per-trace timeline lanes keyed on trace_id[:8])
# still differ.  Tracing ids need uniqueness, not unpredictability.
_ID_BASE = random.SystemRandom().getrandbits(64)
_id_counter = itertools.count(1)  # next() is atomic under the GIL
_MASK64 = (1 << 64) - 1
_GOLDEN = 0x9E3779B97F4A7C15


#: Canonical registry of span names the framework opens: name -> what the
#: span covers.  Entries ending in ``::`` or ``_`` are prefixes for
#: dynamic names (``f"task::{name}"``, ``f"serve.ttft_{bucket}"``).  The
#: static analyzer (registry-consistency checker) enforces that every
#: span()/record_span call site uses a registered name and that no
#: registered name is dead — dashboards and trace queries key on these
#: strings, so a typo'd name is an invisible gap.
SPAN_REGISTRY: Dict[str, str] = {
    "submit::": "driver-side task submission (suffix: task name)",
    "task::": "worker-side task execution (suffix: task name)",
    "serve.http_request": "proxy: full HTTP request lifetime",
    "serve.route": "router: replica pick + dispatch",
    "serve.compiled_route": "router: compiled-path dispatch -> response "
                            "demux, per request (batch-exported)",
    "serve.compiled_batch": "replica: compiled-loop vectorized execution, "
                            "per request (batch-exported)",
    "serve.replica": "replica: user-handler execution",
    "serve.queue_wait": "batching: enqueue -> batch formation, per request",
    "serve.batch_execute": "batching: vectorized user call, per request",
    "serve.stream_emit": "proxy: one streamed chunk emission",
    "serve.prefill": "llm: prompt prefill into the paged KV cache",
    "serve.decode": "llm: one decode micro-batch pass (single model key)",
    "serve.kv_handoff": "llm: KV-page export/import between prefill and "
                        "decode pools",
    "serve.ttft_": "llm: one TTFT attribution bucket (suffix: queue | "
                   "admission | prefill | handoff | residual)",
    "serve.preempt_recompute": "llm: prefill re-run of already-generated "
                               "tokens after a preemption",
    "serve.slo_burn": "slo: one deployment's burn episode, alert -> clear",
    "checkpoint.save": "writer: shard serialize + persist",
    "checkpoint.commit": "coordinator: commit phase up to atomic rename",
    "checkpoint.restore": "restore_pytree entry",
    "data.ingest": "ingest: one source shard, first pull -> last block out",
    "data.locality_claim": "ingest: one locality-aware shard claim "
                           "(attrs: preferred, local)",
    "data.prefetch": "ingest: host->device transfer dispatch, per batch",
    "train.step": "profiler: one training step, report() to report()",
    "train.data_wait": "profiler: step blocked on the input pipeline",
    "train.h2d": "profiler: host->device batch transfer within a step",
    "train.compute": "profiler: step compute residual (wall - waits)",
    "train.collective": "profiler: gradient-sync rendezvous within a step",
    "train.ckpt_block": "profiler: device->host snapshot blocking a step",
    "train.elastic": "controller: elastic recovery, failure -> resumed",
    "train.stall": "watchdog: detected progress stall, last progress -> "
                   "detection (status ERROR)",
    "forensics.dump": "flight recorder: one postmortem dump, trigger -> "
                      "file written",
    "xla.compile": "device telemetry: one trace/lower/compile through the "
                   "instrumented-jit tap (attrs: label, trigger)",
    "xla.compile_storm": "device telemetry: recompile storm episode, first "
                         "windowed recompile -> detection (status ERROR)",
    "device.transfer": "device telemetry: one timed host<->device "
                       "transfer (attrs: direction, src, bytes)",
    "device.burn": "device telemetry: one device compute burn (a jitted "
                   "step / decode execution) in the Perfetto device lane",
    "cluster.autoscale": "cluster autoscaler: one control tick, signal "
                         "collection -> reconcile",
}


def _new_id64() -> str:
    return f"{_ID_BASE ^ (next(_id_counter) * _GOLDEN & _MASK64):016x}"


def _new_trace_id() -> str:
    return _new_id64() + f"{_ID_BASE:016x}"


def is_tracing_enabled() -> bool:
    """(ref: tracing_helper.py:92)."""
    return _enabled


def enable_tracing(exporter: Optional[Callable[[dict], None]] = None) -> None:
    global _enabled, _exporter
    _enabled = True
    _exporter = exporter


def disable_tracing() -> None:
    global _enabled, _exporter
    _enabled = False
    _exporter = None


def exported_spans() -> List[dict]:
    """Spans captured by the default in-memory exporter."""
    # deque.append is atomic, so the hot path exports lock-free; snapshots
    # just retry the rare "mutated during iteration" race.
    for _ in range(100):
        try:
            return list(_buffer)
        except RuntimeError:
            continue
    return list(_buffer)


def clear_spans() -> None:
    with _buffer_lock:
        _buffer.clear()


#: Passive span tap (flight recorder): sees every span the exporter sees,
#: including ones that outlive their tracing session — the recorder is a
#: black box, not a tracing consumer.  One global load + None check on the
#: hot path when no tap is installed.
_tap: Optional[Callable[[dict], None]] = None


def set_span_tap(fn: Optional[Callable[[dict], None]]) -> None:
    """Install (or clear with None) the passive span tap.  The tap must be
    cheap and must never raise — it runs inline on every span export."""
    global _tap
    _tap = fn


def _export(span: dict) -> None:
    if _tap is not None:
        _tap(span)
    if not _enabled:
        return  # span outlived its tracing session (e.g. a parked long-poll)
    if _exporter is not None:
        _exporter(span)
    else:
        _buffer.append(span)


def current_context() -> Optional[dict]:
    """{"trace_id", "span_id"} of the active span, for propagation."""
    span = _current_span.get()
    if span is None:
        return None
    return {"trace_id": span["trace_id"], "span_id": span["span_id"]}


def active_span() -> Optional[dict]:
    """The active span dict itself (or None) — zero-allocation alternative
    to current_context() for in-process consumers (histogram exemplars,
    batch-span parents).  Treat it as read-only; its trace_id/span_id stay
    valid after the span closes, but cross-process propagation must use
    current_context() (the span dict carries arbitrary attribute objects)."""
    return _current_span.get()


class _NullSpan:
    """Context manager returned when tracing is off — zero per-use cost."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, et, ev, tb):
        return False


_NULL_SPAN = _NullSpan()


class _SpanCtx:
    """Class-based span context manager: ~2x cheaper to enter/exit than a
    generator @contextmanager, which matters at several spans per request."""

    __slots__ = ("_s", "_token")

    def __init__(self, s: dict):
        self._s = s

    def __enter__(self):
        self._token = _current_span.set(self._s)
        return self._s

    def __exit__(self, et, ev, tb):
        s = self._s
        if et is not None:
            s["status"] = f"ERROR: {et.__name__}"
        s["end"] = _now()
        _current_span.reset(self._token)
        _export(s)
        return False


_now = time.time


def span(name: str, parent: Optional[dict] = None,
         attributes: Optional[Dict[str, Any]] = None):
    """Open a span; nests under the active span unless `parent` is given.

    The span takes ownership of `attributes` — callers must not mutate the
    dict afterwards (hot path: no defensive copy)."""
    if not _enabled:
        return _NULL_SPAN
    if parent is None:
        # The active span dict itself carries trace_id/span_id — no need to
        # build the {"trace_id", "span_id"} projection on the hot path.
        parent = _current_span.get()
    if parent is not None:
        trace_id = parent.get("trace_id") or _new_trace_id()
        parent_id = parent.get("span_id")
    else:
        trace_id = _new_trace_id()
        parent_id = None
    s = {
        "name": name,
        "trace_id": trace_id,
        "span_id": _new_id64(),
        "parent_id": parent_id,
        "start": _now(),
        "end": None,
        "attributes": attributes if attributes is not None else {},
        "status": "OK",
    }
    return _SpanCtx(s)


def record_span(name: str, start: float, end: float, *,
                trace_id: Optional[str] = None,
                parent: Optional[dict] = None,
                attributes: Optional[Dict[str, Any]] = None,
                status: str = "OK") -> Optional[dict]:
    """Export a retroactively-timed span (e.g. queue wait measured after the
    fact from an enqueue timestamp). Returns the span dict, or None when
    tracing is off.

    Takes ownership of `attributes` (no defensive copy); passing one shared
    dict for a whole batch of spans is fine as long as nobody mutates it."""
    if not _enabled:
        return None
    if parent is None:
        parent = _current_span.get()
    if parent is not None:
        tid = trace_id or parent.get("trace_id") or _new_trace_id()
        parent_id = parent.get("span_id")
    else:
        tid = trace_id or _new_trace_id()
        parent_id = None
    s = {
        "name": name,
        "trace_id": tid,
        "span_id": _new_id64(),
        "parent_id": parent_id,
        "start": start,
        "end": end,
        "attributes": attributes if attributes is not None else {},
        "status": status,
    }
    _export(s)
    return s


def record_span_batch(name: str, intervals, *,
                      attributes: Optional[Dict[str, Any]] = None) -> None:
    """Export one retroactive span per (start, end, parent_ctx) interval in
    a single tight loop — the serve batching layer attributes queue-wait
    and execute spans to every request of a micro-batch this way, keeping
    per-item call overhead off the replica event loop.  Intervals with a
    None parent are skipped (request wasn't traced); all spans share the
    `attributes` dict (callers must not mutate it afterwards)."""
    if not _enabled:
        return
    attrs = attributes if attributes is not None else {}
    emit = _exporter if _exporter is not None else _buffer.append
    tap = _tap
    for start, end, parent in intervals:
        if parent is None:
            continue
        s = {
            "name": name,
            "trace_id": parent.get("trace_id") or _new_trace_id(),
            "span_id": _new_id64(),
            "parent_id": parent.get("span_id"),
            "start": start,
            "end": end,
            "attributes": attrs,
            "status": "OK",
        }
        if tap is not None:
            tap(s)
        emit(s)


def inject_task_spec(spec) -> None:
    """Called at submit time: stamp the submitter's context onto the spec."""
    if _enabled:
        spec.trace_ctx = current_context()


def task_execute_span(spec):
    """Execute-side span parented on the submit-side context in the spec
    (the reference wraps the worker's task execution the same way)."""
    if not _enabled:
        return _NULL_SPAN
    # task_id is a str subclass — store it directly, no str() copy.
    return span(f"task::{spec.name}",
                parent=getattr(spec, "trace_ctx", None),
                attributes={"task_id": spec.task_id,
                            "attempt": spec.attempt})
