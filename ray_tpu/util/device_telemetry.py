"""Device telemetry plane: XLA compile tracking, HBM pools, transfer ledger.

Every observability layer so far (tracing PR 4, train profiler PR 10, TTFT
attribution PR 12, flight recorder PR 15) measures host-side wall time;
this module watches the XLA/device layer those planes cannot see:

* **Compile tracking** — :func:`record_compile` (fed by
  ``jax_compat.instrumented_jit``) keeps a per-process registry of every
  trace/lower/compile with a function label, abstract shape+sharding
  signature, wall time, and a classified trigger (first_compile /
  shape_change / sharding_change / donation_change / recompile).  Rolled
  up cluster-wide through the PR 10 :class:`TimeSeriesCollector` via
  :func:`publish` — N workers compiling the same signature show up as
  duplicated compile-seconds.  A **recompile-storm detector** (recompiles
  per window over threshold) emits an ``xla.compile_storm`` ERROR span and
  a flight-recorder dump, same seam pattern as the hang watchdog's stall
  report; :func:`storm_tick` is driven from ``HangWatchdog.tick``.
* **HBM pool accounting** — named live-byte pools (``kv_blocks``,
  ``mux_weights``, ``ckpt_staging``, ``dag_channel``) tracked host-side
  via :func:`pool_add`/:func:`pool_sub` with high-water marks, plus real
  per-device ``memory_stats()`` when the backend provides them
  (:func:`device_memory_snapshot` — TPU/GPU; the CPU backend usually
  doesn't, so the tracked pools are the fallback truth).
* **Transfer ledger** — every h2d/d2h path calls
  :func:`record_transfer` with direction+bytes+source; windowed
  bandwidth comes from :func:`transfer_bw` (the accessor
  ``ray_tpu.serve.device.transfer_bw`` — same aggregator idiom as the
  serve rollups) and timed transfers land in the Perfetto "device" lane
  as ``device.transfer`` spans.

All hot-path entry points are a few dict ops + a counter inc; spans are
only built when tracing is enabled.  Hook sites reach this module through
``sys.modules.get`` probes (the cross-layer idiom from the train
profiler) so no data/serve/checkpoint layer gains an import dependency.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ray_tpu._private import fault_injection
from ray_tpu.util import flight_recorder, metrics, tracing
from ray_tpu.util.metrics_agent import get_aggregator

#: Compile-record tail retained per process (the full history is in the
#: counters; the tail is what snapshots/bundles embed).
_COMPILE_TAIL = 512
#: Transfer-record tail retained per process.
_TRANSFER_TAIL = 256

#: Recompiles (non-first-compile) inside the window that trip the storm
#: detector.  Env-overridable so chaos tests can trip it deterministically.
DEFAULT_STORM_THRESHOLD = 8
DEFAULT_STORM_WINDOW_S = 60.0

#: Canonical trigger classifications, in precedence order.
TRIGGER_FIRST = "first_compile"
TRIGGER_SHAPE = "shape_change"
TRIGGER_SHARDING = "sharding_change"
TRIGGER_DONATION = "donation_change"
#: Same signature compiled again (cache eviction, duplicated wrapper).
TRIGGER_RECOMPILE = "recompile"

COMPILES_TOTAL = metrics.Counter(
    "ray_tpu_xla_compiles_total",
    "XLA trace/lower/compile events recorded by the instrumented-jit tap, "
    "by function label and classified trigger.",
    ("label", "trigger"))
COMPILE_SECONDS = metrics.Counter(
    "ray_tpu_xla_compile_seconds_total",
    "Wall seconds spent tracing+compiling, by function label — summed "
    "across workers via the collector, duplicated signatures show up as "
    "duplicated compile-seconds.",
    ("label",))
COMPILE_STORMS = metrics.Counter(
    "ray_tpu_xla_compile_storms_total",
    "Recompile storms detected (recompiles/window over threshold).")
POOL_BYTES = metrics.Gauge(
    "ray_tpu_device_pool_bytes",
    "Live bytes attributed to a named device-memory pool (kv_blocks, "
    "mux_weights, ckpt_staging, dag_channel).",
    ("pool",))
POOL_PEAK_BYTES = metrics.Gauge(
    "ray_tpu_device_pool_peak_bytes",
    "High-water mark of a named device-memory pool since process start "
    "(or the last reset).",
    ("pool",))
HBM_BYTES = metrics.Gauge(
    "ray_tpu_device_hbm_bytes",
    "Device-reported bytes_in_use per device (memory_stats(); absent on "
    "backends that don't report, e.g. CPU).",
    ("device",))
HBM_PEAK_BYTES = metrics.Gauge(
    "ray_tpu_device_hbm_peak_bytes",
    "Device-reported peak_bytes_in_use per device (memory_stats()).",
    ("device",))
TRANSFER_BYTES = metrics.Counter(
    "ray_tpu_device_transfer_bytes_total",
    "Bytes crossing the host<->device boundary, by direction (h2d/d2h) "
    "and source path (ingest_prefetch, ckpt_snapshot, kv_handoff, "
    "kv_tier, dag_channel, ...).",
    ("direction", "src"))
TRANSFERS_TOTAL = metrics.Counter(
    "ray_tpu_device_transfers_total",
    "Host<->device transfer events, by direction and source path.",
    ("direction", "src"))

_lock = threading.Lock()
#: label -> last-seen signature components, for trigger classification.
_last_sig: Dict[str, Dict[str, Any]] = {}  # guarded_by: _lock
#: Bounded tail of compile records (dicts, JSON-serializable).
_compile_tail: "deque" = deque(maxlen=_COMPILE_TAIL)  # guarded_by: _lock
#: Timestamps of recent non-first compiles, for the storm window.
_recompile_ts: "deque" = deque(maxlen=4096)  # guarded_by: _lock
_storms = 0  # guarded_by: _lock
#: pool -> [live_bytes, peak_bytes]
_pools: Dict[str, List[float]] = {}  # guarded_by: _lock
#: Bounded tail of transfer records.
_transfer_tail: "deque" = deque(maxlen=_TRANSFER_TAIL)  # guarded_by: _lock


# ------------------------------------------------------------------ compiles

def classify_trigger(label: str, shapes: Any, shardings: Any,
                     donation: Any) -> str:
    """What changed vs. the last compile of ``label`` (read-only peek —
    :func:`record_compile` is what updates the last-seen signature)."""
    with _lock:
        prev = _last_sig.get(label)
    return _classify(prev, shapes, shardings, donation)


def _classify(prev: Optional[Dict[str, Any]], shapes: Any, shardings: Any,
              donation: Any) -> str:
    """Pure classification against one previous-signature row (callers
    read ``_last_sig`` under the lock themselves)."""
    if prev is None:
        return TRIGGER_FIRST
    if shapes != prev["shapes"]:
        return TRIGGER_SHAPE
    if shardings != prev["shardings"]:
        return TRIGGER_SHARDING
    if donation != prev["donation"]:
        return TRIGGER_DONATION
    return TRIGGER_RECOMPILE


def record_compile(label: str, *, shapes: Any, shardings: Any = None,
                   donation: Any = (), trace_s: float = 0.0,
                   compile_s: float = 0.0,
                   ts: Optional[float] = None) -> str:
    """Record one trace/lower/compile event; returns the classified
    trigger.  ``shapes``/``shardings``/``donation`` are opaque hashable
    signature components — classification only compares them against the
    label's previous compile."""
    t = time.time() if ts is None else ts
    with _lock:
        trigger = _classify(_last_sig.get(label), shapes, shardings,
                            donation)
        _last_sig[label] = {"shapes": shapes, "shardings": shardings,
                            "donation": donation}
        _compile_tail.append({
            "label": label, "trigger": trigger, "ts": t,
            "trace_s": round(float(trace_s), 6),
            "compile_s": round(float(compile_s), 6),
            "signature": repr(shapes)[:200],
        })
        if trigger != TRIGGER_FIRST:
            _recompile_ts.append(t)
    COMPILES_TOTAL.inc(tags={"label": label, "trigger": trigger})
    COMPILE_SECONDS.inc(trace_s + compile_s, tags={"label": label})
    wall = trace_s + compile_s
    tracing.record_span("xla.compile", t - wall, t,
                        attributes={"label": label, "trigger": trigger,
                                    "trace_s": trace_s,
                                    "compile_s": compile_s})
    if trigger != TRIGGER_FIRST:
        storm_tick(now=t)
    return trigger


def compile_records(label: Optional[str] = None) -> List[dict]:
    """Retained compile-record tail (optionally one label's), oldest
    first."""
    with _lock:
        rows = list(_compile_tail)
    if label is not None:
        rows = [r for r in rows if r["label"] == label]
    return rows


def compile_totals() -> Dict[str, Any]:
    """{"compiles", "compile_seconds", "by_trigger", "storms"} summed over
    the retained tail (tests and snapshots; the counters hold lifetime
    totals)."""
    with _lock:
        rows = list(_compile_tail)
        storms = _storms
    by_trigger: Dict[str, int] = {}
    for r in rows:
        by_trigger[r["trigger"]] = by_trigger.get(r["trigger"], 0) + 1
    return {"compiles": len(rows),
            "compile_seconds": round(
                sum(r["trace_s"] + r["compile_s"] for r in rows), 6),
            "by_trigger": by_trigger,
            "storms": storms}


def storm_tick(now: Optional[float] = None) -> bool:
    """One storm-detection pass (called inline after every recompile and
    from ``HangWatchdog.tick`` via a module probe): True when recompiles
    inside the window crossed the threshold.  Firing drains the window so
    the detector re-arms only after a fresh burst — a sustained storm
    reports once per threshold-worth of recompiles, not per tick."""
    t = time.time() if now is None else now
    threshold = int(os.environ.get("RAY_TPU_COMPILE_STORM_THRESHOLD",
                                   DEFAULT_STORM_THRESHOLD))
    window_s = float(os.environ.get("RAY_TPU_COMPILE_STORM_WINDOW_S",
                                    DEFAULT_STORM_WINDOW_S))
    with _lock:
        while _recompile_ts and _recompile_ts[0] < t - window_s:
            _recompile_ts.popleft()
        if threshold <= 0 or len(_recompile_ts) < threshold:
            return False
        since = _recompile_ts[0]
        count = len(_recompile_ts)
        _recompile_ts.clear()
        global _storms
        _storms += 1
    _report_storm(since, t, count, threshold, window_s)
    return True


def _report_storm(since: float, detected: float, count: int,
                  threshold: int, window_s: float) -> None:
    """Same seam pattern as the watchdog's stall report: metrics + a ring
    event + a retroactive ERROR span + a postmortem dump, all best-effort
    — forensics must never worsen the storm being recorded."""
    COMPILE_STORMS.inc()
    detail = {"recompiles": count, "threshold": threshold,
              "window_s": window_s, "since": since}
    rec = flight_recorder.get_recorder()
    if rec is not None:
        try:
            rec.record_event("xla.compile_storm", detail, now=detected,
                             kind="storm", status="ERROR")
        except Exception:
            pass
    tracing.record_span("xla.compile_storm", since, detected,
                        attributes=detail, status="ERROR: CompileStorm")
    flight_recorder.trigger_dump("compile_storm", detail)


# --------------------------------------------------------------------- pools

def pool_add(pool: str, nbytes: float) -> None:
    """Attribute ``nbytes`` more live bytes to a named pool."""
    _pool_delta(pool, float(nbytes))


def pool_sub(pool: str, nbytes: float) -> None:
    """Release ``nbytes`` from a named pool (floored at zero — release
    paths may run on state an earlier failure already partially freed)."""
    _pool_delta(pool, -float(nbytes))


def _pool_delta(pool: str, delta: float) -> None:
    with _lock:
        row = _pools.get(pool)
        if row is None:
            row = _pools[pool] = [0.0, 0.0]
        row[0] = max(0.0, row[0] + delta)
        row[1] = max(row[1], row[0])
        cur, peak = row
    POOL_BYTES.set(cur, tags={"pool": pool})
    POOL_PEAK_BYTES.set(peak, tags={"pool": pool})


def pool_set(pool: str, nbytes: float) -> None:
    """Set a pool's live bytes absolutely (rebuild-from-scratch callers)."""
    with _lock:
        row = _pools.get(pool)
        if row is None:
            row = _pools[pool] = [0.0, 0.0]
        row[0] = max(0.0, float(nbytes))
        row[1] = max(row[1], row[0])
        cur, peak = row
    POOL_BYTES.set(cur, tags={"pool": pool})
    POOL_PEAK_BYTES.set(peak, tags={"pool": pool})


def pool_bytes() -> Dict[str, Dict[str, float]]:
    """{pool: {"bytes": live, "peak": high-water}} for every tracked pool."""
    with _lock:
        return {p: {"bytes": row[0], "peak": row[1]}
                for p, row in _pools.items()}


def device_memory_snapshot() -> List[Dict[str, Any]]:
    """Per-device ``memory_stats()`` rows where the backend reports them
    (TPU/GPU); devices without stats (CPU) are skipped — the tracked
    pools above are the host-side fallback.  Updates the HBM gauges."""
    rows: List[Dict[str, Any]] = []
    try:
        import jax

        devices = jax.devices()
    except Exception:
        return rows
    for d in devices:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            continue
        if not stats:
            continue
        dev = str(d.id)
        in_use = float(stats.get("bytes_in_use", 0.0))
        peak = float(stats.get("peak_bytes_in_use", in_use))
        rows.append({"device": dev,
                     "platform": getattr(d, "platform", "unknown"),
                     "bytes_in_use": in_use, "peak_bytes_in_use": peak,
                     "bytes_limit": float(stats.get("bytes_limit", 0.0))})
        HBM_BYTES.set(in_use, tags={"device": dev})
        HBM_PEAK_BYTES.set(peak, tags={"device": dev})
    return rows


def tree_nbytes(tree: Any) -> int:
    """Best-effort payload bytes of a nested list/tuple/dict of array
    leaves (trusts real ``nbytes``, including 0; leaves without one count
    0 — toy-payload tests keep working, numpy/jax arrays are exact)."""
    total = 0
    stack = [tree]
    while stack:
        obj = stack.pop()
        nbytes = getattr(obj, "nbytes", None)
        if nbytes is not None:
            try:
                total += int(nbytes)
            except Exception:
                pass
            continue
        if isinstance(obj, dict):
            stack.extend(obj.values())
        elif isinstance(obj, (list, tuple)):
            stack.extend(obj)
    return total


# ------------------------------------------------------------------ transfers

def record_transfer(direction: str, nbytes: float, *, src: str = "",
                    start: Optional[float] = None,
                    end: Optional[float] = None) -> None:
    """Ledger one host<->device transfer (``direction`` is "h2d"/"d2h").
    When ``start``/``end`` are given the transfer also lands in the
    Perfetto device lane as a ``device.transfer`` span."""
    t = time.time() if end is None else end
    tags = {"direction": direction, "src": src}
    TRANSFER_BYTES.inc(max(0.0, float(nbytes)), tags=tags)
    TRANSFERS_TOTAL.inc(tags=tags)
    with _lock:
        _transfer_tail.append({"ts": t, "direction": direction, "src": src,
                               "bytes": int(nbytes)})
    if start is not None and tracing.is_tracing_enabled():
        tracing.record_span("device.transfer", start, t,
                            attributes={"direction": direction, "src": src,
                                        "bytes": int(nbytes)})


def transfer_records() -> List[dict]:
    """Retained transfer-ledger tail, oldest first."""
    with _lock:
        return list(_transfer_tail)


def transfer_bw(direction: Optional[str] = None, *, src: Optional[str] = None,
                window_s: float = 60.0,
                now: Optional[float] = None) -> float:
    """Windowed host<->device bandwidth (bytes/s) over the trailing
    window, optionally filtered by direction and/or source path — the
    same sample-then-query aggregator idiom as the serve accessors."""
    agg = get_aggregator()
    agg.sample_registry(ts=now)
    tags: Dict[str, str] = {}
    if direction is not None:
        tags["direction"] = direction
    if src is not None:
        tags["src"] = src
    return agg.window_rate("ray_tpu_device_transfer_bytes_total",
                           tags or None, window_s, now)


# ---------------------------------------------------------------------- burns

def record_burn(label: str, start: float, end: float,
                attributes: Optional[Dict[str, Any]] = None) -> None:
    """Timeline a device compute burn (one jitted step execution, a decode
    burn) into the Perfetto device lane.  Pure span sugar — cheap no-op
    when tracing is off."""
    if not tracing.is_tracing_enabled():
        return
    attrs = dict(attributes or {})
    attrs["label"] = label
    tracing.record_span("device.burn", start, end, attributes=attrs)


# ------------------------------------------------------------------- snapshot

def snapshot(*, transfer_window_s: float = 60.0,
             now: Optional[float] = None) -> Dict[str, Any]:
    """JSON-serializable device-telemetry snapshot: compile registry tail
    + totals, pool high-water, transfer window + tail, device memory.
    What forensics bundles embed and ``serve.status()`` / the train run
    registry surface.  Consults the ``device_telemetry_snapshot`` fault
    point — chaos proves every embedding site absorbs a telemetry
    failure."""
    fault_injection.check("device_telemetry_snapshot")
    t = time.time() if now is None else now
    totals = compile_totals()
    return {
        "ts": t,
        "compiles": {
            "totals": totals,
            "tail": compile_records()[-50:],
        },
        "pools": pool_bytes(),
        "transfers": {
            "tail": transfer_records()[-50:],
            "window_s": transfer_window_s,
            "bytes_per_s": {
                "h2d": transfer_bw("h2d", window_s=transfer_window_s,
                                   now=now),
                "d2h": transfer_bw("d2h", window_s=transfer_window_s,
                                   now=now),
            },
        },
        "device_memory": device_memory_snapshot(),
    }


def publish(collector: Any, source: str = "", *,
            since: Optional[float] = None,
            now: Optional[float] = None) -> Any:
    """Roll this process's metric window up to a
    :class:`~ray_tpu.util.metrics_agent.TimeSeriesCollector` (plain
    instance or named actor handle): sample the registry, snapshot the
    aggregator, push tagged with ``source`` so per-worker compile-seconds
    stay distinct series that cluster queries sum."""
    agg = get_aggregator()
    agg.sample_registry(ts=now)
    snap = agg.snapshot(since=since)
    push = collector.push
    if hasattr(push, "remote"):  # actor handle
        return push.remote(snap, source)
    return push(snap, source)


def reset() -> None:
    """Drop all retained state (tests / bench arms): compile registry,
    storm window, pools (gauges cleared), transfer tail."""
    with _lock:
        _last_sig.clear()
        _compile_tail.clear()
        _recompile_ts.clear()
        _transfer_tail.clear()
        _pools.clear()
        global _storms
        _storms = 0
    POOL_BYTES.clear()
    POOL_PEAK_BYTES.clear()
    HBM_BYTES.clear()
    HBM_PEAK_BYTES.clear()
