"""Application metrics API: Counter / Gauge / Histogram.

TPU-native counterpart of the reference's `ray.util.metrics`
(ref: python/ray/util/metrics.py — Counter:137, Histogram:187, Gauge:262):
the same three metric types with tag support, backed by a process-local
registry that the metrics agent (_private/metrics_agent.py) exports in
Prometheus text exposition format — replacing the reference's
OpenCensus-proto → agent → Prometheus pipeline with a direct scrape
endpoint (no sidecar protos needed in a single-runtime process model).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

TagMap = Dict[str, str]
_key = Tuple[Tuple[str, str], ...]
#: (labels, observed value, unix ts) attached to one histogram bucket —
#: OpenMetrics exemplars (the reference attaches trace-id exemplars to its
#: Prometheus histograms the same way).
Exemplar = Tuple[TagMap, float, float]


def _tag_key(tags: Optional[TagMap]) -> _key:
    return tuple(sorted((tags or {}).items()))


class Metric:
    """Base: name, help text, declared tag keys, default tags."""

    _type = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name or any(c in name for c in " \n"):
            raise ValueError(f"invalid metric name: {name!r}")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: TagMap = {}
        self._lock = threading.Lock()
        self._declared_at = _declaration_site()
        _REGISTRY.register(self)

    @property
    def name(self) -> str:
        return self._name

    @property
    def info(self) -> dict:
        return {"name": self._name, "description": self._description,
                "tag_keys": self._tag_keys, "type": self._type,
                "default_tags": dict(self._default_tags)}

    def set_default_tags(self, tags: TagMap) -> "Metric":
        self._check_tags(tags)
        self._default_tags = dict(tags)
        return self

    def _check_tags(self, tags: Optional[TagMap]) -> TagMap:
        merged = dict(self._default_tags)
        merged.update(tags or {})
        for k in merged:
            if k not in self._tag_keys:
                raise ValueError(
                    f"tag {k!r} not in declared tag_keys {self._tag_keys}")
        return merged

    # Subclasses: samples() -> [(suffix, tags, value)]
    def samples(self) -> List[Tuple[str, TagMap, float]]:
        raise NotImplementedError


class Counter(Metric):
    """Monotonically increasing count (ref: util/metrics.py:137)."""

    _type = "counter"

    def __init__(self, name, description="", tag_keys=None):
        self._values: Dict[_key, float] = {}
        super().__init__(name, description, tag_keys)

    def inc(self, value: float = 1.0, tags: Optional[TagMap] = None) -> None:
        if value < 0:
            raise ValueError("Counter.inc requires value >= 0")
        if value == 0:
            # No-op, not an error: natural zero increments (an empty block,
            # a batch of zero retries) shouldn't force callers to guard or
            # lie with max(1, x).  The series is not created either — a
            # counter that never counted anything has nothing to export.
            return
        merged = self._check_tags(tags)
        k = _tag_key(merged)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value

    def get(self, tags: Optional[TagMap] = None) -> float:
        """Current count for a tag set (0.0 if never incremented) — for
        tests and in-process introspection; scraping goes through samples()."""
        k = _tag_key(self._check_tags(tags))
        with self._lock:
            return self._values.get(k, 0.0)

    def samples(self):
        with self._lock:
            return [("", dict(k), v) for k, v in self._values.items()]


class Gauge(Metric):
    """Point-in-time value (ref: util/metrics.py:262)."""

    _type = "gauge"

    def __init__(self, name, description="", tag_keys=None):
        self._values: Dict[_key, float] = {}
        super().__init__(name, description, tag_keys)

    def set(self, value: float, tags: Optional[TagMap] = None) -> None:
        merged = self._check_tags(tags)
        with self._lock:
            self._values[_tag_key(merged)] = float(value)

    def get(self, tags: Optional[TagMap] = None) -> float:
        """Last set value for a tag set (0.0 if never set) — for tests and
        in-process introspection."""
        k = _tag_key(self._check_tags(tags))
        with self._lock:
            return self._values.get(k, 0.0)

    def clear(self) -> None:
        """Drop all tagged series (for samplers that rebuild state counts —
        without this, a series whose population drops to 0 would report its
        stale last value forever)."""
        with self._lock:
            self._values.clear()

    def samples(self):
        with self._lock:
            return [("", dict(k), v) for k, v in self._values.items()]


DEFAULT_BOUNDARIES = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)


class Histogram(Metric):
    """Bucketed distribution (ref: util/metrics.py:187)."""

    _type = "histogram"

    def __init__(self, name, description="", boundaries=None, tag_keys=None):
        bounds = tuple(boundaries if boundaries is not None else DEFAULT_BOUNDARIES)
        if any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])) or not bounds:
            raise ValueError(f"boundaries must be sorted/non-empty: {bounds}")
        self.boundaries = bounds
        self._counts: Dict[_key, List[int]] = {}
        self._sums: Dict[_key, float] = {}
        self._totals: Dict[_key, int] = {}
        #: tag set -> bucket index -> last exemplar landing in that bucket
        self._exemplars: Dict[_key, Dict[int, Exemplar]] = {}
        super().__init__(name, description, tag_keys)

    def observe(self, value: float, tags: Optional[TagMap] = None,
                exemplar: Optional[TagMap] = None) -> None:
        merged = self._check_tags(tags)
        k = _tag_key(merged)
        with self._lock:
            counts = self._counts.setdefault(k, [0] * (len(self.boundaries) + 1))
            i = 0
            while i < len(self.boundaries) and value > self.boundaries[i]:
                i += 1
            counts[i] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._totals[k] = self._totals.get(k, 0) + 1
            if exemplar:
                # Last exemplar per bucket (the Prometheus client keeps one
                # per bucket the same way) — e.g. {"trace_id": ...} linking
                # this observation back to its distributed trace.  Takes
                # ownership of the dict (hot path: no defensive copy).
                self._exemplars.setdefault(k, {})[i] = (
                    exemplar, float(value), time.time())

    def observe_batch(self, values: Sequence[float],
                      tags: Optional[TagMap] = None,
                      exemplar: Optional[TagMap] = None) -> None:
        """Record many observations for ONE tag set under a single lock
        round-trip — the serve batching layer records a whole micro-batch's
        queue waits this way so per-item locking stays off the replica
        event loop.  `exemplar` (if any) is attached to the first value's
        bucket."""
        if not values:
            return
        merged = self._check_tags(tags)
        k = _tag_key(merged)
        bounds = self.boundaries
        nb = len(bounds)
        with self._lock:
            counts = self._counts.setdefault(k, [0] * (nb + 1))
            total = 0.0
            for value in values:
                i = 0
                while i < nb and value > bounds[i]:
                    i += 1
                counts[i] += 1
                total += value
            self._sums[k] = self._sums.get(k, 0.0) + total
            self._totals[k] = self._totals.get(k, 0) + len(values)
            if exemplar:
                value = values[0]
                i = 0
                while i < nb and value > bounds[i]:
                    i += 1
                self._exemplars.setdefault(k, {})[i] = (
                    exemplar, float(value), time.time())

    def get(self, tags: Optional[TagMap] = None) -> dict:
        """Snapshot for one tag set: count/sum/per-bucket counts — the
        in-process view tests and the serve rollups read (Counter/Gauge
        grew .get in PR 2; this is the Histogram counterpart)."""
        k = _tag_key(self._check_tags(tags))
        with self._lock:
            counts = list(self._counts.get(k, ()))
            return {
                "boundaries": list(self.boundaries),
                "counts": counts or [0] * (len(self.boundaries) + 1),
                "count": self._totals.get(k, 0),
                "sum": self._sums.get(k, 0.0),
            }

    def percentile(self, q: float, tags: Optional[TagMap] = None) -> float:
        """Estimate the q-th percentile (q in [0, 100]) for a tag set from
        the bucket counts; 0.0 if nothing was observed."""
        snap = self.get(tags)
        return percentile_from_buckets(snap["boundaries"], snap["counts"], q)

    def exemplars(self) -> Dict[Tuple[_key, str], Exemplar]:
        """{(tag set, le label) -> exemplar} for the scrape path."""
        out: Dict[Tuple[_key, str], Exemplar] = {}
        with self._lock:
            for k, per_bucket in self._exemplars.items():
                for i, ex in per_bucket.items():
                    le = ("+Inf" if i >= len(self.boundaries)
                          else repr(float(self.boundaries[i])))
                    out[(k, le)] = ex
        return out

    def samples(self):
        out = []
        with self._lock:
            for k, counts in self._counts.items():
                tags = dict(k)
                cum = 0
                for b, c in zip(self.boundaries, counts):
                    cum += c
                    out.append(("_bucket", {**tags, "le": repr(float(b))}, cum))
                out.append(("_bucket", {**tags, "le": "+Inf"}, self._totals[k]))
                out.append(("_sum", tags, self._sums[k]))
                out.append(("_count", tags, self._totals[k]))
        return out


class MetricsRegistry:
    """Process-local registry; the agent scrapes it.

    Same-name metrics from independent call sites are legal (the reference
    aggregates them through OpenCensus): all instances are kept and their
    samples merged at scrape time — summed for counters/histograms,
    last-writer-wins for gauges — so no instance's data is silently lost.
    """

    def __init__(self) -> None:
        self._metrics: Dict[str, List[Metric]] = {}
        self._lock = threading.Lock()

    def register(self, metric: Metric) -> None:
        with self._lock:
            group = self._metrics.setdefault(metric.name, [])
            if group and type(group[0]) is not type(metric):
                raise ValueError(
                    f"metric {metric.name!r} already registered with type "
                    f"{group[0]._type}")
            group.append(metric)

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()

    def collect(self) -> List[List[Metric]]:
        with self._lock:
            return [list(g) for g in self._metrics.values()]

    def prometheus_text(self) -> str:
        """Prometheus text exposition format (what /metrics serves).

        Histogram ``_bucket`` lines carry OpenMetrics-style exemplars
        (``# {trace_id="..."} value ts``) when observations recorded them —
        the hook Grafana/Tempo use to jump from a latency bucket straight
        to one exemplifying distributed trace.
        """
        lines: List[str] = []
        for group in self.collect():
            lead = group[0]
            lines.append(f"# HELP {lead.name} {lead._description}")
            lines.append(f"# TYPE {lead.name} {lead._type}")
            merged: Dict[Tuple[str, _key], float] = {}
            for m in group:
                for suffix, tags, value in m.samples():
                    k = (suffix, _tag_key(tags))
                    if lead._type == "gauge":
                        merged[k] = value
                    else:
                        merged[k] = merged.get(k, 0.0) + value
            exemplars: Dict[Tuple[_key, str], Exemplar] = {}
            for m in group:
                if isinstance(m, Histogram):
                    exemplars.update(m.exemplars())
            for (suffix, tag_items), value in merged.items():
                if tag_items:
                    body = ",".join(
                        f'{k}="{_escape(v)}"' for k, v in tag_items)
                    line = f"{lead.name}{suffix}{{{body}}} {_fmt(value)}"
                else:
                    line = f"{lead.name}{suffix} {_fmt(value)}"
                if suffix == "_bucket":
                    tags = dict(tag_items)
                    le = tags.pop("le", None)
                    ex = exemplars.get((_tag_key(tags), le))
                    if ex is not None:
                        ex_labels, ex_value, ex_ts = ex
                        ex_body = ",".join(
                            f'{k}="{_escape(v)}"'
                            for k, v in sorted(ex_labels.items()))
                        line += (f" # {{{ex_body}}} {_fmt(ex_value)}"
                                 f" {ex_ts:.3f}")
                lines.append(line)
        return "\n".join(lines) + "\n"


def percentile_from_buckets(boundaries: Sequence[float],
                            counts: Sequence[int], q: float) -> float:
    """Estimate the q-th percentile (q in [0, 100]) from per-bucket counts.

    ``counts`` has one entry per boundary plus the overflow bucket, exactly
    as Histogram records them.  Linear interpolation inside the target
    bucket (the same estimate Prometheus's histogram_quantile makes); the
    overflow bucket clamps to the top boundary — a bucketed histogram
    cannot resolve beyond its largest bound.
    """
    if not 0 <= q <= 100:
        raise ValueError(f"q must be in [0, 100], got {q}")
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = (q / 100.0) * total
    cum = 0.0
    for i, c in enumerate(counts):
        prev_cum = cum
        cum += c
        if cum >= rank and c > 0:
            if i >= len(boundaries):  # overflow: clamp to the top bound
                return float(boundaries[-1])
            lo = boundaries[i - 1] if i > 0 else 0.0
            hi = boundaries[i]
            frac = (rank - prev_cum) / c
            return float(lo + (hi - lo) * min(1.0, max(0.0, frac)))
    return float(boundaries[-1])


def _declaration_site() -> str:
    """``file:line`` of the code declaring a metric (skipping this module)
    — lets scripts/check_metrics.py tell internal declarations from user
    ones sharing the process registry."""
    import sys

    f = sys._getframe(1)
    here = __file__
    while f is not None and f.f_code.co_filename == here:
        f = f.f_back
    if f is None:
        return "<unknown>"
    return f"{f.f_code.co_filename}:{f.f_lineno}"


def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _fmt(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() else repr(float(v))


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY
