"""Application metrics API: Counter / Gauge / Histogram.

TPU-native counterpart of the reference's `ray.util.metrics`
(ref: python/ray/util/metrics.py — Counter:137, Histogram:187, Gauge:262):
the same three metric types with tag support, backed by a process-local
registry that the metrics agent (_private/metrics_agent.py) exports in
Prometheus text exposition format — replacing the reference's
OpenCensus-proto → agent → Prometheus pipeline with a direct scrape
endpoint (no sidecar protos needed in a single-runtime process model).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

TagMap = Dict[str, str]
_key = Tuple[Tuple[str, str], ...]


def _tag_key(tags: Optional[TagMap]) -> _key:
    return tuple(sorted((tags or {}).items()))


class Metric:
    """Base: name, help text, declared tag keys, default tags."""

    _type = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name or any(c in name for c in " \n"):
            raise ValueError(f"invalid metric name: {name!r}")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: TagMap = {}
        self._lock = threading.Lock()
        _REGISTRY.register(self)

    @property
    def name(self) -> str:
        return self._name

    @property
    def info(self) -> dict:
        return {"name": self._name, "description": self._description,
                "tag_keys": self._tag_keys, "type": self._type,
                "default_tags": dict(self._default_tags)}

    def set_default_tags(self, tags: TagMap) -> "Metric":
        self._check_tags(tags)
        self._default_tags = dict(tags)
        return self

    def _check_tags(self, tags: Optional[TagMap]) -> TagMap:
        merged = dict(self._default_tags)
        merged.update(tags or {})
        for k in merged:
            if k not in self._tag_keys:
                raise ValueError(
                    f"tag {k!r} not in declared tag_keys {self._tag_keys}")
        return merged

    # Subclasses: samples() -> [(suffix, tags, value)]
    def samples(self) -> List[Tuple[str, TagMap, float]]:
        raise NotImplementedError


class Counter(Metric):
    """Monotonically increasing count (ref: util/metrics.py:137)."""

    _type = "counter"

    def __init__(self, name, description="", tag_keys=None):
        self._values: Dict[_key, float] = {}
        super().__init__(name, description, tag_keys)

    def inc(self, value: float = 1.0, tags: Optional[TagMap] = None) -> None:
        if value <= 0:
            raise ValueError("Counter.inc requires value > 0")
        merged = self._check_tags(tags)
        k = _tag_key(merged)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value

    def get(self, tags: Optional[TagMap] = None) -> float:
        """Current count for a tag set (0.0 if never incremented) — for
        tests and in-process introspection; scraping goes through samples()."""
        k = _tag_key(self._check_tags(tags))
        with self._lock:
            return self._values.get(k, 0.0)

    def samples(self):
        with self._lock:
            return [("", dict(k), v) for k, v in self._values.items()]


class Gauge(Metric):
    """Point-in-time value (ref: util/metrics.py:262)."""

    _type = "gauge"

    def __init__(self, name, description="", tag_keys=None):
        self._values: Dict[_key, float] = {}
        super().__init__(name, description, tag_keys)

    def set(self, value: float, tags: Optional[TagMap] = None) -> None:
        merged = self._check_tags(tags)
        with self._lock:
            self._values[_tag_key(merged)] = float(value)

    def get(self, tags: Optional[TagMap] = None) -> float:
        """Last set value for a tag set (0.0 if never set) — for tests and
        in-process introspection."""
        k = _tag_key(self._check_tags(tags))
        with self._lock:
            return self._values.get(k, 0.0)

    def clear(self) -> None:
        """Drop all tagged series (for samplers that rebuild state counts —
        without this, a series whose population drops to 0 would report its
        stale last value forever)."""
        with self._lock:
            self._values.clear()

    def samples(self):
        with self._lock:
            return [("", dict(k), v) for k, v in self._values.items()]


DEFAULT_BOUNDARIES = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)


class Histogram(Metric):
    """Bucketed distribution (ref: util/metrics.py:187)."""

    _type = "histogram"

    def __init__(self, name, description="", boundaries=None, tag_keys=None):
        bounds = tuple(boundaries if boundaries is not None else DEFAULT_BOUNDARIES)
        if any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])) or not bounds:
            raise ValueError(f"boundaries must be sorted/non-empty: {bounds}")
        self.boundaries = bounds
        self._counts: Dict[_key, List[int]] = {}
        self._sums: Dict[_key, float] = {}
        self._totals: Dict[_key, int] = {}
        super().__init__(name, description, tag_keys)

    def observe(self, value: float, tags: Optional[TagMap] = None) -> None:
        merged = self._check_tags(tags)
        k = _tag_key(merged)
        with self._lock:
            counts = self._counts.setdefault(k, [0] * (len(self.boundaries) + 1))
            i = 0
            while i < len(self.boundaries) and value > self.boundaries[i]:
                i += 1
            counts[i] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._totals[k] = self._totals.get(k, 0) + 1

    def samples(self):
        out = []
        with self._lock:
            for k, counts in self._counts.items():
                tags = dict(k)
                cum = 0
                for b, c in zip(self.boundaries, counts):
                    cum += c
                    out.append(("_bucket", {**tags, "le": repr(float(b))}, cum))
                out.append(("_bucket", {**tags, "le": "+Inf"}, self._totals[k]))
                out.append(("_sum", tags, self._sums[k]))
                out.append(("_count", tags, self._totals[k]))
        return out


class MetricsRegistry:
    """Process-local registry; the agent scrapes it.

    Same-name metrics from independent call sites are legal (the reference
    aggregates them through OpenCensus): all instances are kept and their
    samples merged at scrape time — summed for counters/histograms,
    last-writer-wins for gauges — so no instance's data is silently lost.
    """

    def __init__(self) -> None:
        self._metrics: Dict[str, List[Metric]] = {}
        self._lock = threading.Lock()

    def register(self, metric: Metric) -> None:
        with self._lock:
            group = self._metrics.setdefault(metric.name, [])
            if group and type(group[0]) is not type(metric):
                raise ValueError(
                    f"metric {metric.name!r} already registered with type "
                    f"{group[0]._type}")
            group.append(metric)

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()

    def collect(self) -> List[List[Metric]]:
        with self._lock:
            return [list(g) for g in self._metrics.values()]

    def prometheus_text(self) -> str:
        """Prometheus text exposition format (what /metrics serves)."""
        lines: List[str] = []
        for group in self.collect():
            lead = group[0]
            lines.append(f"# HELP {lead.name} {lead._description}")
            lines.append(f"# TYPE {lead.name} {lead._type}")
            merged: Dict[Tuple[str, _key], float] = {}
            for m in group:
                for suffix, tags, value in m.samples():
                    k = (suffix, _tag_key(tags))
                    if lead._type == "gauge":
                        merged[k] = value
                    else:
                        merged[k] = merged.get(k, 0.0) + value
            for (suffix, tag_items), value in merged.items():
                if tag_items:
                    body = ",".join(
                        f'{k}="{_escape(v)}"' for k, v in tag_items)
                    lines.append(
                        f"{lead.name}{suffix}{{{body}}} {_fmt(value)}")
                else:
                    lines.append(f"{lead.name}{suffix} {_fmt(value)}")
        return "\n".join(lines) + "\n"


def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _fmt(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() else repr(float(v))


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY
