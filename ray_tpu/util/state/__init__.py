"""State API: list/get/summarize cluster entities.

Counterpart of the reference's `ray.util.state` (ref: python/ray/util/state/
api.py + dashboard/modules/state/state_head.py:47): `ray list
tasks/actors/objects/nodes/placement-groups` and `ray summary`, fed by the
task-event store the runtime keeps (the role of the C++ `GcsTaskManager`,
gcs_task_manager.h:86).  Single-runtime model: reads go straight to the
runtime's in-process tables instead of over gRPC to the GCS.
"""

from __future__ import annotations

from collections import Counter as _Counter
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

Filter = Tuple[str, str, Any]  # (key, "="|"!=", value)


def _runtime():
    from ray_tpu._private import runtime as _rt

    rt = _rt.runtime_or_none()
    if rt is None:
        raise RuntimeError("ray_tpu is not initialized; call ray_tpu.init()")
    return rt


def _apply_filters(rows: List[dict], filters: Optional[Sequence[Filter]],
                   limit: int) -> List[dict]:
    if filters:
        for key, op, value in filters:
            if op not in ("=", "!="):
                raise ValueError(f"unsupported filter op {op!r} (use = or !=)")
            rows = [r for r in rows
                    if (str(r.get(key)) == str(value)) == (op == "=")]
    return rows[:limit]


# ------------------------------------------------------------------- tasks
def _task_table() -> List[dict]:
    """Fold the event log into one row per task attempt (latest state wins)."""
    rt = _runtime()
    events = rt.list_task_events()
    rows: Dict[str, dict] = {}
    for ev in events:
        if ev.get("state", "").startswith("PROFILE"):
            continue
        tid = ev["task_id"]
        row = rows.setdefault(tid, {
            "task_id": tid, "name": ev.get("name", ""), "state": "",
            "start_time": None, "end_time": None, "error_type": "",
            "node_id": "", "actor_id": "",
        })
        row["state"] = ev["state"]
        for k in ("node_id", "actor_id"):
            if ev.get(k):
                row[k] = str(ev[k])
        if ev.get("error"):
            row["error_type"] = str(ev["error"])
        if ev["state"] == "RUNNING":
            row["start_time"] = ev["time"]
        if ev["state"] in ("FINISHED", "FAILED"):
            row["end_time"] = ev["time"]
    return list(rows.values())


def list_tasks(filters: Optional[Sequence[Filter]] = None,
               limit: int = 10_000) -> List[dict]:
    return _apply_filters(_task_table(), filters, limit)


def get_task(task_id: str) -> Optional[dict]:
    for row in _task_table():
        if row["task_id"] == str(task_id):
            return row
    return None


def summarize_tasks() -> dict:
    """Counts by (name, state) — `ray summary tasks`."""
    by_func: Dict[str, _Counter] = {}
    total = 0
    for row in _task_table():
        by_func.setdefault(row["name"], _Counter())[row["state"]] += 1
        total += 1
    return {"total": total,
            "by_func": {k: dict(v) for k, v in sorted(by_func.items())}}


# ------------------------------------------------------------------- actors
def list_actors(filters: Optional[Sequence[Filter]] = None,
                limit: int = 10_000) -> List[dict]:
    return _apply_filters(_runtime().list_actor_states(), filters, limit)


def get_actor(actor_id: str) -> Optional[dict]:
    for row in _runtime().list_actor_states():
        if row["actor_id"] == str(actor_id):
            return row
    return None


def summarize_actors() -> dict:
    by_class: Dict[str, _Counter] = {}
    rows = _runtime().list_actor_states()
    for row in rows:
        by_class.setdefault(row["class_name"], _Counter())[row["state"]] += 1
    return {"total": len(rows),
            "by_class": {k: dict(v) for k, v in sorted(by_class.items())}}


# ------------------------------------------------------------------ objects
def list_objects(filters: Optional[Sequence[Filter]] = None,
                 limit: int = 10_000) -> List[dict]:
    return _apply_filters(_runtime().store.object_summaries(), filters, limit)


def summarize_objects() -> dict:
    rows = _runtime().store.object_summaries()
    by_state: _Counter = _Counter()
    total_bytes = 0
    for row in rows:
        by_state[row["state"]] += 1
        total_bytes += row["size"]
    return {"total": len(rows), "total_bytes": total_bytes,
            "by_state": dict(by_state)}


# -------------------------------------------------------------------- nodes
def list_nodes(filters: Optional[Sequence[Filter]] = None,
               limit: int = 10_000) -> List[dict]:
    rows = []
    for node in _runtime().scheduler.nodes():
        snap = node.snapshot()
        rows.append({
            "node_id": str(snap["NodeID"]), "alive": snap["Alive"],
            "resources": snap["Resources"], "available": snap["Available"],
            "labels": snap["Labels"],
        })
    return _apply_filters(rows, filters, limit)


# -------------------------------------------------------------------- serve
def _serve_controller():
    """The detached serve controller, or None when serve never started."""
    import ray_tpu

    try:
        return ray_tpu.get_actor("SERVE_CONTROLLER")
    except Exception:
        return None


def list_deployments(filters: Optional[Sequence[Filter]] = None,
                     limit: int = 10_000) -> List[dict]:
    """Deployment rows (controller state + RED rollups) — the serve
    counterpart of list_actors (ref: `ray list deployments` via the serve
    state API).  Empty when serve is not running."""
    import ray_tpu

    controller = _serve_controller()
    if controller is None:
        return []
    rows = ray_tpu.get(controller.list_deployments.remote(), timeout=30.0)
    return _apply_filters(rows, filters, limit)


def list_replicas(filters: Optional[Sequence[Filter]] = None,
                  limit: int = 10_000) -> List[dict]:
    """Per-replica FSM rows (state, version, uptime, health bookkeeping).
    Empty when serve is not running."""
    import ray_tpu

    controller = _serve_controller()
    if controller is None:
        return []
    rows = ray_tpu.get(controller.list_replicas.remote(), timeout=30.0)
    return _apply_filters(rows, filters, limit)


# --------------------------------------------------------- train runs
def list_train_runs(filters: Optional[Sequence[Filter]] = None,
                    limit: int = 10_000) -> List[dict]:
    """Train-run rows from the controller's run registry: name, status,
    live world size vs target, last committed checkpoint step, elastic
    shrink/grow events (docs/observability.md).  The training counterpart
    of list_deployments.  Probed via sys.modules — importing the train
    package here would drag the trainer (and collective) into every state
    query; if it was never imported, no run can exist.  Works without a
    runtime: rows live in this process, not in runtime tables."""
    import sys

    registry = sys.modules.get("ray_tpu.train.run_registry")
    if registry is None:
        return []
    return _apply_filters(registry.list_runs(), filters, limit)


def get_train_run(name: str) -> Optional[dict]:
    import sys

    registry = sys.modules.get("ray_tpu.train.run_registry")
    if registry is None:
        return None
    return registry.get_run(str(name))


# --------------------------------------------------------- postmortems
def list_postmortems(filters: Optional[Sequence[Filter]] = None,
                     limit: int = 10_000) -> List[dict]:
    """Flight-recorder postmortem dumps in this session (one row per dump:
    id, pid, trigger reason, timestamp, ring/stall counts) — the index
    ``scripts/postmortem.py list`` and ``/api/postmortems`` print.  Works
    without a runtime: rows are files under ``<session>/postmortems``."""
    from ray_tpu.util import forensics

    return _apply_filters(forensics.list_postmortems(), filters, limit)


def get_postmortem(pm_id: str) -> Optional[dict]:
    """Full dump payload (ring, stacks, heap when traced) for one id."""
    from ray_tpu.util import forensics

    return forensics.load_postmortem(str(pm_id))


# --------------------------------------------------------- placement groups
def list_placement_groups(filters: Optional[Sequence[Filter]] = None,
                          limit: int = 10_000) -> List[dict]:
    rt = _runtime()
    rows = []
    with rt.scheduler._lock:
        pgs = list(rt.scheduler._pgs.values())
    for pg in pgs:
        rows.append({
            "placement_group_id": str(pg.id), "name": pg.name,
            "state": pg.state, "strategy": pg.strategy,
            "bundles": [dict(b.resources) for b in pg.bundles],
        })
    return _apply_filters(rows, filters, limit)


__all__ = [
    "list_tasks", "get_task", "summarize_tasks",
    "list_actors", "get_actor", "summarize_actors",
    "list_objects", "summarize_objects",
    "list_nodes", "list_placement_groups",
    "list_deployments", "list_replicas",
    "list_train_runs", "get_train_run",
    "list_postmortems", "get_postmortem",
]
