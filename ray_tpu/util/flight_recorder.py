"""Always-on flight recorder: a black-box ring of the recent past.

The tracing / metrics planes (PR 4/10/12) observe a process *while it is
alive and someone is asking*; when a replica dies under chaos, a trainer
wedges in a collective, or the SLO watchdog trips, the spans that explain
it are already gone.  The recorder keeps a bounded, always-on record that
survives the event:

* a fixed-size, lock-free per-process ring buffer of recent spans
  (passively tapped from :mod:`ray_tpu.util.tracing`'s exporter via
  ``set_span_tap``), serve/train state transitions (``record_event``) and
  coarse metric deltas (``sample_metric_deltas``, driven by the hang
  watchdog's tick);
* ``dump(reason)``: snapshot the ring plus all-thread stacks (reusing
  :mod:`~ray_tpu._private.stack_profiler`) to
  ``<session>/postmortems/<pid>-<reason>.json`` — triggered where
  failures already surface (actor death, elastic preemption, SLO breach,
  compiled-router fallback) and via the explicit API.

Cost discipline matches the PR 4 span export: slots are preallocated
fixed-width lists mutated in place (no per-event allocation), the write
index is an ``itertools.count`` (``next()`` is atomic under the GIL), and
readers detect torn slots with a seqlock stamp — a writer marks the slot
in-progress (negative seq), fills the fields, then publishes the final
seq.  ``snapshot()`` skips in-progress slots and re-checks the stamp
after copying, so concurrent recording never blocks and never yields a
half-written row.  Disable with ``RAY_TPU_FLIGHT_RECORDER=0``.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import tracemalloc
from typing import Any, Dict, List, Optional

from ray_tpu.util import metrics, tracing

#: Ring capacity (events).  ~120 bytes/slot -> the default is ~1 MB of
#: bounded memory per process; override with RAY_TPU_FLIGHT_RECORDER_SLOTS.
DEFAULT_SLOTS = 8192

#: Per-reason dump flood control: a crash loop must not turn the
#: postmortem dir into a disk leak.  Override (seconds) with
#: RAY_TPU_POSTMORTEM_MIN_INTERVAL_S.
DEFAULT_MIN_DUMP_INTERVAL_S = 5.0

#: Slot layout (fixed width, mutated in place): seq is written twice by
#: the seqlock protocol — negative while the row is being filled, the
#: final positive value when published.
_F_SEQ, _F_KIND, _F_NAME, _F_T0, _F_T1, _F_STATUS, _F_DETAIL = range(7)

DUMPS_TOTAL = metrics.Counter(
    "ray_tpu_forensics_dumps_total",
    "Postmortem dumps written, by trigger reason.", ("reason",))
DUMPS_SUPPRESSED_TOTAL = metrics.Counter(
    "ray_tpu_forensics_dumps_suppressed_total",
    "Postmortem dumps skipped by per-reason flood control.", ("reason",))
DUMP_SECONDS = metrics.Histogram(
    "ray_tpu_forensics_dump_seconds",
    "Wall time of one postmortem dump (ring snapshot + stacks + write).",
    boundaries=[0.001, 0.01, 0.1, 0.5, 2.0])
RING_EVENTS_TOTAL = metrics.Counter(
    "ray_tpu_forensics_ring_events_total",
    "Events recorded into the flight-recorder ring, by kind.", ("kind",))


def postmortem_dir(export: bool = False) -> str:
    """``<session>/postmortems`` (same env-override pattern as the stack
    profiler's dump dir)."""
    from ray_tpu._private.config import session_subdir

    return session_subdir("postmortems", "RAY_TPU_POSTMORTEM_DIR",
                          export=export)


class FlightRecorder:
    """Fixed-size lock-free event ring + postmortem dump writer."""

    def __init__(self, slots: int = DEFAULT_SLOTS):
        self._n = max(16, int(slots))
        # Preallocated fixed-width rows; recording mutates fields in place.
        self._ring: List[list] = [
            [-1, "", "", 0.0, 0.0, "", None] for _ in range(self._n)]
        self._seq = itertools.count()  # next() is atomic under the GIL
        self._last_dump: Dict[str, float] = {}  # guarded_by: _dump_lock
        self._dump_lock = threading.Lock()
        # Metric-delta baseline (sampled from the watchdog thread only).
        self._metric_base: Dict[str, float] = {}  # owned_by_thread: watchdog tick caller

    # ------------------------------------------------------------ recording
    def _record(self, kind: str, name: str, t0: float, t1: float,
                status: str, detail: Any) -> None:
        seq = next(self._seq)
        slot = self._ring[seq % self._n]
        slot[_F_SEQ] = -seq - 1          # mark in-progress (seqlock)
        slot[_F_KIND] = kind
        slot[_F_NAME] = name
        slot[_F_T0] = t0
        slot[_F_T1] = t1
        slot[_F_STATUS] = status
        slot[_F_DETAIL] = detail
        slot[_F_SEQ] = seq               # publish

    def tap_span(self, span: dict) -> None:
        """Passive tracing tap — called inline on every span export; must
        stay allocation-free beyond the strings the span already owns."""
        self._record("span", span["name"], span["start"],
                     span["end"] if span["end"] is not None else span["start"],
                     span["status"], None)

    def record_event(self, name: str, detail: Any = None,
                     now: Optional[float] = None, *,
                     kind: str = "event", status: str = "OK") -> None:
        """Record a state transition (actor death, elastic shrink, SLO
        alert, stall, ...) into the ring."""
        ts = time.time() if now is None else now
        self._record(kind, name, ts, ts, status, detail)
        RING_EVENTS_TOTAL.inc(tags={"kind": kind})

    def sample_metric_deltas(self, now: Optional[float] = None) -> int:
        """Record coarse deltas of every counter-style metric since the
        last sample (called from the watchdog tick — one caller thread, so
        the baseline dict needs no lock).  Returns the number of deltas
        recorded."""
        ts = time.time() if now is None else now
        recorded = 0
        for group in metrics.registry().collect():
            if group[0]._type != "counter":
                continue
            name = group[0].name
            total = sum(v for m in group for _, _, v in m.samples())
            base = self._metric_base.get(name, 0.0)
            if total != base:
                self._metric_base[name] = total
                self._record("metric", name, ts, ts, "OK", total - base)
                recorded += 1
        return recorded

    # ------------------------------------------------------------- snapshot
    def snapshot(self) -> List[dict]:
        """Ordered copy of the ring's published events.  Lock-free: torn
        slots (overwritten mid-copy) are detected by the seq stamp and
        skipped — a snapshot racing heavy recording loses a few events at
        the wrap boundary, never yields a half-written row."""
        rows = []
        for idx, slot in enumerate(self._ring):
            seq = slot[_F_SEQ]
            if seq < 0 or seq % self._n != idx:
                continue  # empty or mid-write
            row = list(slot)
            if slot[_F_SEQ] != seq:
                continue  # overwritten while copying
            rows.append(row)
        rows.sort(key=lambda r: r[_F_SEQ])
        return [{"seq": r[_F_SEQ], "kind": r[_F_KIND], "name": r[_F_NAME],
                 "start": r[_F_T0], "end": r[_F_T1], "status": r[_F_STATUS],
                 "detail": r[_F_DETAIL]} for r in rows]

    def events_recorded(self) -> int:
        """Lifetime event count (>= ring capacity means it has wrapped)."""
        # Peek without consuming: count() has no peek, so derive from the
        # newest published slot instead.
        newest = max((s[_F_SEQ] for s in self._ring), default=-1)
        return newest + 1

    # ----------------------------------------------------------------- dump
    def dump(self, reason: str, extra: Optional[dict] = None,
             now: Optional[float] = None) -> Optional[str]:
        """Snapshot ring + all-thread stacks (+ heap, iff tracemalloc was
        already tracing) to ``<session>/postmortems/<pid>-<reason>.json``.

        Returns the file path, or None when flood control suppressed the
        dump.  Raises on write failure (and at the ``forensics_dump``
        chaos point) — trigger sites absorb via :func:`trigger_dump`.
        """
        from ray_tpu._private import fault_injection

        fault_injection.check("forensics_dump")
        ts = time.time() if now is None else now
        with self._dump_lock:
            last = self._last_dump.get(reason)
            min_gap = float(os.environ.get(
                "RAY_TPU_POSTMORTEM_MIN_INTERVAL_S",
                DEFAULT_MIN_DUMP_INTERVAL_S))
            if last is not None and ts - last < min_gap:
                DUMPS_SUPPRESSED_TOTAL.inc(tags={"reason": reason})
                return None
            self._last_dump[reason] = ts
        from ray_tpu._private import heap_profiler, stack_profiler

        t0 = time.time()
        tracing_active = tracemalloc.is_tracing()
        payload: Dict[str, Any] = {
            "schema": 1,
            "pid": os.getpid(),
            "reason": reason,
            "ts": ts,
            "hostname": os.uname().nodename,
            "ring": self.snapshot(),
            "events_recorded": self.events_recorded(),
            "stacks": stack_profiler.current_process_stacks(),
            # S2: tracemalloc snapshot only when a real window was open —
            # an empty-window snapshot is the trap the heap profiler's
            # docstring warns about.
            "tracing_active": tracing_active,
        }
        if tracing_active:
            payload["heap"] = heap_profiler.heap_summary()
        if extra:
            payload["extra"] = extra
        d = postmortem_dir()
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in reason)
        path = os.path.join(d, f"{os.getpid()}-{safe}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, default=str)
        os.replace(tmp, path)
        t1 = time.time()
        DUMPS_TOTAL.inc(tags={"reason": reason})
        DUMP_SECONDS.observe(t1 - t0)
        tracing.record_span("forensics.dump", t0, t1,
                            attributes={"reason": reason, "path": path})
        return path


# ------------------------------------------------------------------ singleton
_recorder: Optional[FlightRecorder] = None  # guarded_by: _recorder_lock
_recorder_lock = threading.Lock()


def enabled() -> bool:
    return os.environ.get("RAY_TPU_FLIGHT_RECORDER", "1") != "0"


def get_recorder() -> Optional[FlightRecorder]:
    """The process-wide recorder (installs the tracing tap on first use);
    None when disabled via RAY_TPU_FLIGHT_RECORDER=0."""
    global _recorder
    with _recorder_lock:
        if _recorder is None and enabled():
            slots = int(os.environ.get(
                "RAY_TPU_FLIGHT_RECORDER_SLOTS", DEFAULT_SLOTS))
            rec = FlightRecorder(slots)
            tracing.set_span_tap(rec.tap_span)
            _recorder = rec
        return _recorder


def reset_recorder() -> None:
    """Tear down the singleton + tap (tests)."""
    global _recorder
    with _recorder_lock:
        tracing.set_span_tap(None)
        _recorder = None


def record_event(name: str, detail: Any = None, *, kind: str = "event",
                 status: str = "OK") -> None:
    """Module-level convenience: record a state transition if the recorder
    is enabled (cheap no-op otherwise)."""
    rec = get_recorder()
    if rec is not None:
        rec.record_event(name, detail, kind=kind, status=status)


def trigger_dump(reason: str, extra: Optional[dict] = None) -> Optional[str]:
    """Best-effort dump for failure-path trigger sites: records the
    trigger as a ring event, dumps, and absorbs every error (a forensics
    failure must never worsen the failure being recorded).  Returns the
    dump path, or None (disabled / suppressed / failed)."""
    rec = get_recorder()
    if rec is None:
        return None
    try:
        rec.record_event(reason, extra, kind="trigger")
        return rec.dump(reason, extra)
    except Exception:
        return None
