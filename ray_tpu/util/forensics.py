"""Head-side crash forensics: postmortem bundles from flight-recorder dumps.

Per-process :mod:`~ray_tpu.util.flight_recorder` dumps capture what one
process saw in its final seconds; this module assembles the cluster-level
story.  ``build_bundle()`` merges every dump under
``<session>/postmortems/`` with the head's recent
:class:`~ray_tpu.util.metrics_agent.TimeSeriesAggregator` window and the
:mod:`~ray_tpu.train.run_registry` state into one postmortem bundle —
served by ``/api/postmortems`` and :func:`ray_tpu.util.state.list_postmortems`,
rendered by ``scripts/postmortem.py``, and exportable as a fused
Perfetto timeline (one lane per dumped process, instant markers at
deaths, stalls and dump triggers — see
:func:`ray_tpu._private.profiling.postmortem_chrome_events`).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

from ray_tpu.util.flight_recorder import postmortem_dir


def list_postmortems() -> List[Dict[str, Any]]:
    """Index rows for every dump in the session's postmortem dir, newest
    first: ``{"id", "pid", "reason", "ts", "ring_events", "stalls",
    "tracing_active", "path"}``.  The id is the filename stem and is what
    :func:`load_postmortem` / the CLI / ``/api/postmortems`` key on."""
    d = postmortem_dir()
    rows: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return rows
    for fn in names:
        if not fn.endswith(".json"):
            continue
        path = os.path.join(d, fn)
        try:
            with open(path) as f:
                dump = json.load(f)
        except (OSError, ValueError):
            continue  # torn write from a dying process; skip, don't fail
        ring = dump.get("ring", [])
        extra = dump.get("extra") or {}
        rows.append({
            "id": fn[:-len(".json")],
            "pid": dump.get("pid"),
            "reason": dump.get("reason"),
            "ts": dump.get("ts"),
            "ring_events": len(ring),
            "stalls": sum(1 for r in ring if r.get("kind") == "stall"),
            "tracing_active": dump.get("tracing_active", False),
            # Node attribution when the trigger recorded one (actor_death
            # etc.) — what the cluster autoscaler's quarantine gate keys on.
            "node": str(extra.get("node") or "") or None,
            "path": path,
        })
    rows.sort(key=lambda r: r.get("ts") or 0.0, reverse=True)
    return rows


def load_postmortem(pm_id: str) -> Optional[Dict[str, Any]]:
    """Full dump payload for one id (filename stem), or None."""
    if os.sep in pm_id or pm_id.startswith("."):
        return None  # ids are filename stems, not paths
    path = os.path.join(postmortem_dir(), pm_id + ".json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def build_bundle(*, window_s: float = 300.0,
                 now: Optional[float] = None) -> Dict[str, Any]:
    """Merge every per-process dump with the head's recent aggregator
    window and the run registry into one postmortem bundle."""
    t = time.time() if now is None else now
    dumps = []
    for row in list_postmortems():
        dump = load_postmortem(row["id"])
        if dump is not None:
            dump["id"] = row["id"]
            dumps.append(dump)
    bundle: Dict[str, Any] = {
        "schema": 1,
        "generated_ts": t,
        "window_s": window_s,
        "dumps": dumps,
        "stalls": [r for d in dumps for r in d.get("ring", [])
                   if r.get("kind") == "stall"],
    }
    # Head-side recent time series (the cluster view the dying process
    # could not see) — fold live counters in first so the window is fresh.
    from ray_tpu.util.metrics_agent import get_aggregator

    agg = get_aggregator()
    agg.sample_registry(ts=t)
    bundle["timeseries"] = agg.snapshot(since=t - window_s)
    # Run registry: probe sys.modules instead of importing — if the train
    # package was never imported, there are no runs to report (same idiom
    # as util.state.list_train_runs).
    reg = sys.modules.get("ray_tpu.train.run_registry")
    bundle["train_runs"] = reg.list_runs() if reg is not None else []
    # Device telemetry snapshot (compile registry tail, pool high-water,
    # transfer window) next to the ring/stacks/heap sections.  Absorbed:
    # a telemetry failure (incl. the device_telemetry_snapshot chaos
    # point) must never cost the bundle its other sections.
    try:
        from ray_tpu.util import device_telemetry

        bundle["device_telemetry"] = device_telemetry.snapshot(now=t)
    except Exception:
        bundle["device_telemetry"] = None
    return bundle


def bundle_chrome_trace(bundle: Dict[str, Any]) -> List[dict]:
    """Fused Perfetto timeline for a bundle (one lane per dumped process,
    death/stall markers) — load at ui.perfetto.dev."""
    from ray_tpu._private.profiling import postmortem_chrome_events

    return postmortem_chrome_events(bundle)
