"""multiprocessing.Pool API over ray_tpu tasks
(ref: python/ray/util/multiprocessing/pool.py — drop-in Pool whose work
items run as cluster tasks instead of local forked processes)."""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class AsyncResult:
    def __init__(self, refs: List[Any], single: bool,
                 callback: Optional[Callable] = None,
                 error_callback: Optional[Callable] = None):
        self._refs = refs
        self._single = single
        self._callback = callback
        self._error_callback = error_callback
        self._fired = False

    def get(self, timeout: Optional[float] = None):
        try:
            vals = ray_tpu.get(self._refs, timeout=timeout)
        except Exception as e:
            if self._error_callback is not None and not self._fired:
                self._fired = True
                self._error_callback(e)
            raise
        value = vals[0] if self._single else vals
        if self._callback is not None and not self._fired:
            self._fired = True
            self._callback(value)
        return value

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")  # multiprocessing contract
        try:
            ray_tpu.get(self._refs, timeout=0)
            return True
        except Exception:
            return False


class Pool:
    """Cluster-backed process pool.  ``processes`` bounds concurrent chunks
    (defaults to cluster CPUs); tasks inherit the usual scheduling."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = ()):
        ray_tpu.init(ignore_reinit_error=True)
        if processes is None:
            cpus = ray_tpu.cluster_resources().get("CPU", 1)
            processes = max(1, int(cpus))
        self._processes = processes
        self._initializer = initializer
        self._initargs = initargs
        self._closed = False

        import threading

        init = initializer
        iargs = initargs
        init_lock = threading.Lock()  # thread-tier workers share the process
        init_done = [False]

        @ray_tpu.remote
        def run_chunk(fn, chunk, star):
            if init is not None:
                with init_lock:  # once-guard: no check-then-set race
                    if not init_done[0]:
                        init(*iargs)
                        init_done[0] = True
            if star:
                return [fn(*a) for a in chunk]
            return [fn(a) for a in chunk]

        self._run_chunk = run_chunk

    def _check_open(self) -> None:
        if self._closed:
            raise ValueError("Pool not running")

    def _chunks(self, iterable: Iterable, chunksize: Optional[int]):
        """Lazy chunking — never materializes the full iterable (matters for
        imap over large/endless streams)."""
        if chunksize is None:
            # Without len() we cannot derive the multiprocessing heuristic;
            # a modest fixed chunk keeps tasks coarse enough.
            chunksize = 8
        it = iter(iterable)
        while True:
            chunk = list(itertools.islice(it, chunksize))
            if not chunk:
                return
            yield chunk

    # ------------------------------------------------------------- map APIs
    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn, iterable, chunksize: Optional[int] = None,
                  _star: bool = False):
        self._check_open()
        refs = [self._run_chunk.remote(fn, c, _star)
                for c in self._chunks(iterable, chunksize)]
        return _ChunkedResult(refs)

    def starmap(self, fn: Callable, iterable: Iterable[tuple],
                chunksize: Optional[int] = None) -> List[Any]:
        # Items star-unpack ONLY here; map passes each item as one argument
        # even when it is a tuple (the multiprocessing contract).
        return self.map_async(fn, [tuple(a) for a in iterable], chunksize,
                              _star=True).get()

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: Optional[int] = None):
        """Lazily yields in order with a window of chunks in flight — real
        pipelining, unlike submit-then-wait per chunk."""
        self._check_open()
        window = max(2, self._processes)
        pending: List[Any] = []
        chunks = self._chunks(iterable, chunksize)
        done = False
        while not done or pending:
            while not done and len(pending) < window:
                try:
                    chunk = next(chunks)
                except StopIteration:
                    done = True
                    break
                pending.append(self._run_chunk.remote(fn, chunk, False))
            if pending:
                for v in ray_tpu.get(pending.pop(0)):
                    yield v

    imap_unordered = imap  # chunk-granular ordering is close enough here

    # ------------------------------------------------------------ apply APIs
    def apply(self, fn: Callable, args: tuple = (), kwds: Optional[dict] = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: Optional[dict] = None,
                    callback: Optional[Callable] = None,
                    error_callback: Optional[Callable] = None):
        self._check_open()
        kwds = kwds or {}

        @ray_tpu.remote
        def run_one():
            return fn(*args, **kwds)

        return AsyncResult([run_one.remote()], single=True,
                           callback=callback, error_callback=error_callback)

    # ------------------------------------------------------------- lifecycle
    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _ChunkedResult(AsyncResult):
    def __init__(self, refs: List[Any]):
        super().__init__(refs, single=False)

    def get(self, timeout: Optional[float] = None):
        out: List[Any] = []
        for chunk in ray_tpu.get(self._refs, timeout=timeout):
            out.extend(chunk)
        return out
