"""multiprocessing.Pool API over ray_tpu tasks
(ref: python/ray/util/multiprocessing/pool.py — drop-in Pool whose work
items run as cluster tasks instead of local forked processes)."""

from __future__ import annotations

import itertools
import threading
import uuid
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu

# Per-executing-process once-guard for pool initializers.  Module-level so
# the remote chunk function below pickles by reference (a closure over a
# threading.Lock is unpicklable and would break process/client-mode pools).
_INIT_LOCK = threading.Lock()
_INITIALIZED_POOLS: set = set()


def _run_chunk_impl(pool_id, init, iargs, fn, chunk, star):
    if init is not None:
        with _INIT_LOCK:  # once-guard per pool per process, no races
            if pool_id not in _INITIALIZED_POOLS:
                init(*iargs)
                _INITIALIZED_POOLS.add(pool_id)
    if star:
        return [fn(*a) for a in chunk]
    return [fn(a) for a in chunk]


# Wrapped separately (not via decorator) so `_run_chunk_impl` stays reachable
# under its own module attribute: cloudpickle then serializes it BY REFERENCE;
# a decorator would shadow the name and force by-value pickling, dragging the
# module-global lock above into the payload (unpicklable).
_run_chunk = ray_tpu.remote(_run_chunk_impl)


def _default_processes() -> int:
    """Cluster CPU count, degrading gracefully in ray:// client mode where
    the proxy runtime has no local scheduler view."""
    try:
        return max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
    except Exception:
        import os

        return max(1, os.cpu_count() or 1)


class AsyncResult:
    def __init__(self, refs: List[Any], single: bool,
                 callback: Optional[Callable] = None,
                 error_callback: Optional[Callable] = None):
        self._refs = refs
        self._single = single
        self._callback = callback
        self._error_callback = error_callback
        self._fired = False

    def get(self, timeout: Optional[float] = None):
        from ray_tpu.exceptions import GetTimeoutError

        try:
            vals = ray_tpu.get(self._refs, timeout=timeout)
        except GetTimeoutError:
            raise  # a timeout is not a task failure: callbacks stay unfired
        except Exception as e:
            if self._error_callback is not None and not self._fired:
                self._fired = True
                self._error_callback(e)
            raise
        value = vals[0] if self._single else vals
        if self._callback is not None and not self._fired:
            self._fired = True
            self._callback(value)
        return value

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")  # multiprocessing contract
        try:
            ray_tpu.get(self._refs, timeout=0)
            return True
        except Exception:
            return False


class Pool:
    """Cluster-backed process pool.  ``processes`` bounds concurrent chunks
    (defaults to cluster CPUs); tasks inherit the usual scheduling."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = ()):
        ray_tpu.init(ignore_reinit_error=True)
        if processes is None:
            processes = _default_processes()
        self._processes = processes
        self._initializer = initializer
        self._initargs = initargs
        self._closed = False
        self._pool_id = uuid.uuid4().hex

    def _check_open(self) -> None:
        if self._closed:
            raise ValueError("Pool not running")

    def _submit_chunk(self, fn, chunk, star):
        return _run_chunk.remote(self._pool_id, self._initializer,
                                 self._initargs, fn, chunk, star)

    def _chunks(self, iterable: Iterable, chunksize: Optional[int]):
        """Lazy chunking — never materializes the full iterable (matters for
        imap over large/endless streams)."""
        if chunksize is None:
            # Without len() we cannot derive the multiprocessing heuristic;
            # a modest fixed chunk keeps tasks coarse enough.
            chunksize = 8
        it = iter(iterable)
        while True:
            chunk = list(itertools.islice(it, chunksize))
            if not chunk:
                return
            yield chunk

    # ------------------------------------------------------------- map APIs
    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn, iterable, chunksize: Optional[int] = None,
                  callback: Optional[Callable] = None,
                  error_callback: Optional[Callable] = None,
                  _star: bool = False):
        self._check_open()
        refs = [self._submit_chunk(fn, c, _star)
                for c in self._chunks(iterable, chunksize)]
        return _ChunkedResult(refs, callback=callback,
                              error_callback=error_callback)

    def starmap(self, fn: Callable, iterable: Iterable[tuple],
                chunksize: Optional[int] = None) -> List[Any]:
        # Items star-unpack ONLY here; map passes each item as one argument
        # even when it is a tuple (the multiprocessing contract).
        return self.starmap_async(fn, iterable, chunksize).get()

    def starmap_async(self, fn: Callable, iterable: Iterable[tuple],
                      chunksize: Optional[int] = None,
                      callback: Optional[Callable] = None,
                      error_callback: Optional[Callable] = None):
        return self.map_async(fn, [tuple(a) for a in iterable], chunksize,
                              callback=callback, error_callback=error_callback,
                              _star=True)

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: Optional[int] = None):
        """Lazily yields in order with a window of chunks in flight — real
        pipelining, unlike submit-then-wait per chunk."""
        self._check_open()
        window = max(2, self._processes)
        pending: List[Any] = []
        chunks = self._chunks(iterable, chunksize)
        done = False
        while not done or pending:
            while not done and len(pending) < window:
                try:
                    chunk = next(chunks)
                except StopIteration:
                    done = True
                    break
                pending.append(self._submit_chunk(fn, chunk, False))
            if pending:
                for v in ray_tpu.get(pending.pop(0)):
                    yield v

    imap_unordered = imap  # chunk-granular ordering is close enough here

    # ------------------------------------------------------------ apply APIs
    def apply(self, fn: Callable, args: tuple = (), kwds: Optional[dict] = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: Optional[dict] = None,
                    callback: Optional[Callable] = None,
                    error_callback: Optional[Callable] = None):
        self._check_open()
        kwds = kwds or {}

        @ray_tpu.remote
        def run_one():
            return fn(*args, **kwds)

        return AsyncResult([run_one.remote()], single=True,
                           callback=callback, error_callback=error_callback)

    # ------------------------------------------------------------- lifecycle
    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _ChunkedResult(AsyncResult):
    def __init__(self, refs: List[Any],
                 callback: Optional[Callable] = None,
                 error_callback: Optional[Callable] = None):
        super().__init__(refs, single=False, callback=callback,
                         error_callback=error_callback)

    def get(self, timeout: Optional[float] = None):
        from ray_tpu.exceptions import GetTimeoutError

        try:
            chunks = ray_tpu.get(self._refs, timeout=timeout)
        except GetTimeoutError:
            raise  # a timeout is not a task failure: callbacks stay unfired
        except Exception as e:
            if self._error_callback is not None and not self._fired:
                self._fired = True
                self._error_callback(e)
            raise
        out: List[Any] = []
        for chunk in chunks:
            out.extend(chunk)
        if self._callback is not None and not self._fired:
            self._fired = True
            self._callback(out)
        return out
