"""Hang/straggler watchdog: detects the failures liveness polls cannot.

The runtime's 250 ms liveness poll answers "is the process alive" — a
trainer wedged inside a collective rendezvous, a replica lane that stopped
draining, or a worker 3× slower than its peers is *alive* and invisible to
it.  This watchdog tracks **progress** instead:

* ``beat(source, wall=...)`` — periodic progress heartbeats: step closure
  from :mod:`ray_tpu.train.profiler`, channel-drain ticks from the
  compiled router's lanes;
* ``phase_enter(source, phase)`` / ``phase_exit(source)`` — bounded-phase
  tracking: collective rendezvous entry/exit in
  :mod:`ray_tpu.collective.xla_group` (a phase held open past the stall
  threshold is a wedge even while beats from other threads continue).

``tick()`` (driven by a lazily-started daemon thread, or called directly
with a deterministic clock in tests) flags a **stall** when a source's
last progress — beat or open phase — is older than the threshold: it
captures all-thread stacks into the flight-recorder ring, emits the
``ray_tpu_stall_*`` metrics and a retroactive ``train.stall`` ERROR span
(so the wedge renders in the Perfetto train lane), and samples coarse
metric deltas into the ring.  **Stragglers** are flagged from cross-worker
step-time dispersion: a source whose recent median step wall exceeds
``straggler_factor ×`` the cluster median.  Disable the background thread
with ``RAY_TPU_HANG_WATCHDOG=0``; ``tick()`` still works for tests.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ray_tpu.util import flight_recorder, metrics, tracing

DEFAULT_STALL_THRESHOLD_S = 30.0
DEFAULT_TICK_INTERVAL_S = 5.0
DEFAULT_STRAGGLER_FACTOR = 2.0
#: Recent step walls kept per source for the dispersion check.
_WALL_WINDOW = 32
#: A beat-quiet source retires (drops out of stall accounting) after this
#: many stall thresholds — a finished worker is not a permanent wedge.
_RETIRE_FACTOR = 10.0

STALL_EVENTS_TOTAL = metrics.Counter(
    "ray_tpu_stall_events_total",
    "Progress stalls detected by the hang watchdog, by kind "
    "(phase = wedged inside a bounded phase, beat = heartbeats stopped).",
    ("kind", "source"))
STALLED_SOURCES = metrics.Gauge(
    "ray_tpu_stall_active",
    "Sources currently considered stalled by the hang watchdog.")
STRAGGLER_SOURCES = metrics.Gauge(
    "ray_tpu_stall_stragglers",
    "Sources whose recent median step wall exceeds the cluster median by "
    "the straggler dispersion factor.")


class HangWatchdog:
    """Progress tracking + stall/straggler detection for one process."""

    def __init__(self, *,
                 stall_threshold_s: Optional[float] = None,
                 straggler_factor: Optional[float] = None):
        self.stall_threshold_s = float(
            stall_threshold_s if stall_threshold_s is not None
            else os.environ.get("RAY_TPU_STALL_THRESHOLD_S",
                                DEFAULT_STALL_THRESHOLD_S))
        self.straggler_factor = float(
            straggler_factor if straggler_factor is not None
            else os.environ.get("RAY_TPU_STRAGGLER_FACTOR",
                                DEFAULT_STRAGGLER_FACTOR))
        self._lock = threading.Lock()
        #: source -> progress row {"last_beat", "phase", "phase_t0",
        #: "walls", "stalled", "straggler"}
        self._sources: Dict[str, Dict[str, Any]] = {}  # guarded_by: _lock
        self._thread: Optional[threading.Thread] = None  # guarded_by: _lock

    # ------------------------------------------------------------ progress
    def _row_locked(self, source: str, now: float) -> Dict[str, Any]:
        row = self._sources.get(source)
        if row is None:
            row = {"last_beat": now, "phase": None, "phase_t0": 0.0,
                   "walls": deque(maxlen=_WALL_WINDOW), "stalled": False,
                   "straggler": False}
            self._sources[source] = row
        return row

    def beat(self, source: str, wall: Optional[float] = None,
             now: Optional[float] = None) -> None:
        """Progress heartbeat; ``wall`` (seconds) feeds the straggler
        dispersion check.  Cheap: one lock round-trip, no allocation after
        the source's first beat."""
        t = time.time() if now is None else now
        with self._lock:
            row = self._row_locked(source, t)
            row["last_beat"] = t
            if wall is not None:
                row["walls"].append(wall)

    def phase_enter(self, source: str, phase: str,
                    now: Optional[float] = None) -> None:
        """Mark entry into a bounded phase (collective rendezvous, channel
        drain) — held open past the threshold it is a stall even while the
        process stays responsive."""
        t = time.time() if now is None else now
        with self._lock:
            row = self._row_locked(source, t)
            row["phase"] = phase
            row["phase_t0"] = t
            row["last_beat"] = t

    def phase_exit(self, source: str, now: Optional[float] = None) -> None:
        t = time.time() if now is None else now
        with self._lock:
            row = self._sources.get(source)
            if row is not None:
                row["phase"] = None
                row["last_beat"] = t

    def forget(self, source: str) -> None:
        """Drop a source (worker retired/descaled) so it cannot stall."""
        with self._lock:
            self._sources.pop(source, None)

    # ----------------------------------------------------------- detection
    def tick(self, now: Optional[float] = None) -> List[dict]:
        """One detection pass; returns the stall records found this pass
        (new stalls only — a wedge is reported once, then armed again when
        progress resumes).  Deterministic under an injected clock."""
        t = time.time() if now is None else now
        new_stalls: List[dict] = []
        stalled_count = 0
        straggler_count = 0
        with self._lock:
            medians = {}
            for source, row in self._sources.items():
                walls = sorted(row["walls"])
                if walls:
                    medians[source] = walls[len(walls) // 2]
            cluster = sorted(medians.values())
            cluster_median = (cluster[len(cluster) // 2] if cluster else 0.0)
            for source, row in list(self._sources.items()):
                if row["phase"] is None and t - row["last_beat"] \
                        > _RETIRE_FACTOR * self.stall_threshold_s:
                    # Source went quiet long ago (worker retired, lane
                    # closed without forget()): stop reporting it as
                    # stalled — its one-shot stall record already fired.
                    self._sources.pop(source)
                    continue
                if row["phase"] is not None \
                        and t - row["phase_t0"] > self.stall_threshold_s:
                    kind, since = "phase", row["phase_t0"]
                elif t - row["last_beat"] > self.stall_threshold_s:
                    kind, since = "beat", row["last_beat"]
                else:
                    row["stalled"] = False
                    kind = None
                if kind is not None:
                    stalled_count += 1
                    if not row["stalled"]:
                        row["stalled"] = True
                        new_stalls.append({
                            "source": source, "kind": kind, "since": since,
                            "phase": row["phase"], "detected": t})
                m = medians.get(source)
                row["straggler"] = bool(
                    m is not None and len(medians) >= 2
                    and cluster_median > 0.0
                    and m > self.straggler_factor * cluster_median)
                straggler_count += row["straggler"]
        STALLED_SOURCES.set(stalled_count)
        STRAGGLER_SOURCES.set(straggler_count)
        for stall in new_stalls:
            self._report_stall(stall)
        rec = flight_recorder.get_recorder()
        if rec is not None:
            rec.sample_metric_deltas(now=t)
        # Drive the recompile-storm detector on the same cadence (probed,
        # not imported — a process that never loaded the device-telemetry
        # plane pays one dict miss per tick).
        telemetry = sys.modules.get("ray_tpu.util.device_telemetry")
        if telemetry is not None:
            try:
                telemetry.storm_tick(now=t)
            except Exception:
                pass  # detection is best-effort, same as the loop's ticks
        return new_stalls

    def _report_stall(self, stall: dict) -> None:
        """Stacks into the black box + metrics + a timeline span — outside
        the watchdog lock (stack capture walks every thread's frames)."""
        STALL_EVENTS_TOTAL.inc(tags={"kind": stall["kind"],
                                     "source": stall["source"]})
        rec = flight_recorder.get_recorder()
        if rec is not None:
            try:
                from ray_tpu._private import stack_profiler

                rec.record_event(
                    f"stall:{stall['source']}",
                    {"kind": stall["kind"], "phase": stall["phase"],
                     "since": stall["since"],
                     "stacks": stack_profiler.current_process_stacks()},
                    now=stall["detected"], kind="stall", status="ERROR")
            except Exception:
                pass  # forensics must never worsen the stall
        tracing.record_span(
            "train.stall", stall["since"], stall["detected"],
            attributes={"source": stall["source"], "kind": stall["kind"],
                        "phase": stall["phase"]},
            status="ERROR: Stall")

    def straggler_report(self) -> Dict[str, dict]:
        """source -> {"median_wall", "straggler"} as of the last tick."""
        with self._lock:
            out = {}
            for source, row in self._sources.items():
                walls = sorted(row["walls"])
                out[source] = {
                    "median_wall": walls[len(walls) // 2] if walls else None,
                    "straggler": row["straggler"],
                    "stalled": row["stalled"],
                }
            return out

    # ----------------------------------------------------- background loop
    def ensure_started(self) -> None:
        """Start the detection thread once (no-op when disabled via
        RAY_TPU_HANG_WATCHDOG=0, or already running)."""
        if os.environ.get("RAY_TPU_HANG_WATCHDOG", "1") == "0":
            return
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            t = threading.Thread(target=self._run_loop,
                                 name="ray_tpu_hang_watchdog", daemon=True)
            self._thread = t
        t.start()  # detached_ok: daemon detection loop, dies with the process

    def _run_loop(self) -> None:
        interval = float(os.environ.get("RAY_TPU_WATCHDOG_TICK_S",
                                        DEFAULT_TICK_INTERVAL_S))
        while True:
            time.sleep(interval)
            try:
                self.tick()
            except Exception:
                pass  # detection is best-effort; never kill the thread


# ------------------------------------------------------------------ singleton
_watchdog: Optional[HangWatchdog] = None  # guarded_by: _watchdog_lock
_watchdog_lock = threading.Lock()


def get_watchdog() -> HangWatchdog:
    global _watchdog
    with _watchdog_lock:
        if _watchdog is None:
            _watchdog = HangWatchdog()
        return _watchdog


def reset_watchdog() -> None:
    """Test hook: drop all progress state (the detection thread, if
    started, keeps running against the new instance on its next tick)."""
    global _watchdog
    with _watchdog_lock:
        _watchdog = None


def beat(source: str, wall: Optional[float] = None) -> None:
    """Hook entry for heartbeat sites (step closure, lane drain): records
    progress and lazily starts the detection thread."""
    wd = get_watchdog()
    wd.beat(source, wall)
    wd.ensure_started()


def phase_enter(source: str, phase: str) -> None:
    """Hook entry for bounded-phase sites (rendezvous enter)."""
    wd = get_watchdog()
    wd.phase_enter(source, phase)
    wd.ensure_started()


def phase_exit(source: str) -> None:
    get_watchdog().phase_exit(source)


def forget(source: str) -> None:
    """Hook entry for retirement sites (a stopped autoscaler monitor, a
    descaled worker): drop the source so it cannot be flagged as a stall."""
    get_watchdog().forget(source)
