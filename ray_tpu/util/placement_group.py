"""Placement groups (ref: python/ray/util/placement_group.py — PlacementGroup:41,
placement_group():145, strategies PACK/SPREAD/STRICT_PACK/STRICT_SPREAD:162).

Bundles are atomically reserved across (virtual) nodes by the scheduler's
2-phase commit (ref: gcs_placement_group_scheduler); STRICT_PACK is
ICI-slice-aware (see scheduling.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private.ids import PlacementGroupID
from ray_tpu._private.runtime import get_runtime
from ray_tpu._private.scheduling import PlacementGroupSchedulingStrategy  # re-export
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.ids import ObjectID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID):
        self.id = pg_id

    def ready(self) -> ObjectRef:
        """ObjectRef resolving when all bundles are reserved (ref: pg.ready())."""
        runtime = get_runtime()
        state = runtime.scheduler.get_placement_group(self.id)
        ref_id = ObjectID(f"pgready-{self.id}:0")

        def waiter():
            state.ready_event.wait()
            runtime.store.put(ref_id, self)

        import threading

        threading.Thread(target=waiter, daemon=True).start()
        return ObjectRef(ref_id, owner="driver")

    def wait(self, timeout_seconds: Optional[float] = None) -> bool:
        state = get_runtime().scheduler.get_placement_group(self.id)
        if state is None:
            return False
        return state.ready_event.wait(timeout_seconds)

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        state = get_runtime().scheduler.get_placement_group(self.id)
        return [dict(b.resources) for b in state.bundles]

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def bundle_node_ids(self) -> List[Optional[str]]:
        state = get_runtime().scheduler.get_placement_group(self.id)
        return [str(b.node_id) if b.node_id else None for b in state.bundles]

    def __reduce__(self):
        return (PlacementGroup, (self.id,))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    runtime = get_runtime()
    pg_id = PlacementGroupID.from_random()
    runtime.scheduler.create_placement_group(pg_id, bundles, strategy, name)
    return PlacementGroup(pg_id)


def remove_placement_group(pg: PlacementGroup) -> None:
    get_runtime().scheduler.remove_placement_group(pg.id)


def get_current_placement_group() -> Optional[PlacementGroup]:
    return None  # populated for tasks captured into a PG in a later round


def placement_group_table() -> Dict[str, dict]:
    runtime = get_runtime()
    out = {}
    for state in runtime.scheduler.placement_groups():
        out[str(state.id)] = {
            "name": state.name,
            "strategy": state.strategy,
            "state": state.state,
            "bundles": [dict(b.resources) for b in state.bundles],
        }
    return out
