"""Time-series rollups over the metrics registry.

The Counter/Gauge/Histogram registry (`ray_tpu.util.metrics`) answers
"what is the value now"; this module answers "what happened over the last
N seconds" — the question autoscalers and dashboards actually ask.  A
:class:`TimeSeriesAggregator` keeps a per-series sliding window of
timestamped points (bounded: old points are pruned as new ones land) and
derives windowed sums, rates and percentiles from them:

* ``sample_registry()`` snapshots every counter/gauge/histogram series in
  the process registry into the window — call it on a cadence (the
  metrics agent's ``/timeseries`` route does this per scrape).
* ``window_rate(name, tags, window_s)`` is the query the serve
  autoscaler consumes (ROADMAP: utilization-aware autoscaling needs
  request *rates*, not cumulative totals).  Counter series rate by
  positive deltas — process restarts (a total falling back toward zero)
  never produce negative rates.
* ``snapshot()`` / ``merge_snapshot()`` move windows between processes:
  each node's aggregator ships its recent points to the head-side
  :class:`TimeSeriesCollector` actor, which answers cluster-wide queries
  and serves the merged window as OpenMetrics text.

Timestamps are caller-suppliable everywhere (``ts=``/``now=``) so tests
drive a fully deterministic feed.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu.util import metrics as _metrics

#: Series key: (metric name, sorted tag items).
_SeriesKey = Tuple[str, Tuple[Tuple[str, str], ...]]

#: Default retention: queries beyond this window see a truncated view.
DEFAULT_MAX_WINDOW_S = 600.0
#: Per-series point cap — a mis-cadenced sampler cannot grow one series
#: without bound inside the retention window.
_MAX_POINTS = 4096


class _Series:
    __slots__ = ("name", "tags", "kind", "ts", "values")

    def __init__(self, name: str, tags: Dict[str, str], kind: str):
        self.name = name
        self.tags = dict(tags)
        self.kind = kind  # "counter" (cumulative total) | "value" | "gauge"
        self.ts: List[float] = []
        self.values: List[float] = []

    def add(self, ts: float, value: float, horizon: float) -> None:
        # Points may arrive slightly out of order across threads; keep the
        # arrays sorted so window queries can bisect.
        if self.ts and ts < self.ts[-1]:
            i = bisect.bisect_right(self.ts, ts)
            self.ts.insert(i, ts)
            self.values.insert(i, value)
        else:
            self.ts.append(ts)
            self.values.append(value)
        # Prune past the horizon, keeping ONE point before it: counter
        # rates need a baseline sample older than the window start.
        cut = bisect.bisect_left(self.ts, horizon)
        if cut > 1:
            del self.ts[: cut - 1]
            del self.values[: cut - 1]
        if len(self.ts) > _MAX_POINTS:
            drop = len(self.ts) - _MAX_POINTS
            del self.ts[:drop]
            del self.values[:drop]

    def window(self, start: float) -> Tuple[List[float], List[float]]:
        """(ts, values) at or after ``start``, plus one baseline point
        before it when available (index 0 then predates the window)."""
        i = bisect.bisect_left(self.ts, start)
        if i > 0:
            i -= 1
        return self.ts[i:], self.values[i:]


class TimeSeriesAggregator:
    """Per-process sliding-window store of metric points (see module doc)."""

    def __init__(self, max_window_s: float = DEFAULT_MAX_WINDOW_S):
        self.max_window_s = float(max_window_s)
        self._series: Dict[_SeriesKey, _Series] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------- ingest
    def observe(self, name: str, value: float,
                tags: Optional[Dict[str, str]] = None, *,
                kind: str = "value", ts: Optional[float] = None) -> None:
        """Add one point.  ``kind`` is sticky per series (first wins):
        "counter" marks ``value`` as a cumulative total (rates come from
        deltas), "value" a per-event quantity (rates come from sums),
        "gauge" a level (windows average it)."""
        if kind not in ("counter", "value", "gauge"):
            raise ValueError(f"kind must be counter|value|gauge, got {kind!r}")
        t = time.time() if ts is None else float(ts)
        key = (name, tuple(sorted((tags or {}).items())))
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = _Series(name, dict(tags or {}),
                                                     kind)
            series.add(t, float(value), t - self.max_window_s)

    def sample_registry(self, registry: Optional[_metrics.MetricsRegistry] = None,
                        ts: Optional[float] = None) -> int:
        """Snapshot every series in the metrics registry into the window;
        returns how many points landed.  Counters and histogram
        ``_sum``/``_count`` components ingest as cumulative "counter"
        series; gauges as "gauge"."""
        reg = registry if registry is not None else _metrics.registry()
        t = time.time() if ts is None else float(ts)
        n = 0
        for group in reg.collect():
            lead = group[0]
            # Merge same-name instances exactly like the scrape path does.
            merged: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
            for m in group:
                for suffix, tags, value in m.samples():
                    if suffix == "_bucket":
                        continue  # windows re-derive percentiles themselves
                    k = (suffix, tuple(sorted(tags.items())))
                    if lead._type == "gauge":
                        merged[k] = value
                    else:
                        merged[k] = merged.get(k, 0.0) + value
            kind = "gauge" if lead._type == "gauge" else "counter"
            for (suffix, tag_items), value in merged.items():
                self.observe(lead.name + suffix, value, dict(tag_items),
                             kind=kind, ts=t)
                n += 1
        return n

    # ------------------------------------------------------------ queries
    def _get(self, name: str,
             tags: Optional[Dict[str, str]]) -> Optional[_Series]:
        key = (name, tuple(sorted((tags or {}).items())))
        with self._lock:
            return self._series.get(key)

    def _match(self, name: str,
               tags: Optional[Dict[str, str]]) -> List[_Series]:
        """Series answering a ``(name, tags)`` query: the exact tag-set
        when one exists, else every series of that name whose tags are a
        superset of the query (so ``{"pool": "prefill"}`` rolls up the
        per-``(pool, deployment)`` LLM gauges, and no tags at all means
        "all tag-sets" instead of silently missing)."""
        key = (name, tuple(sorted((tags or {}).items())))
        with self._lock:
            exact = self._series.get(key)
            if exact is not None:
                return [exact]
            return [s for (n, _), s in self._series.items()
                    if n == name and _subset(tags, s.tags)]

    def _rate_locked(self, series: _Series, start: float,
                     window_s: float) -> float:
        ts, values = series.window(start)
        if not ts:
            return 0.0
        if series.kind == "counter":
            total = 0.0
            for i in range(1, len(ts)):
                if ts[i] >= start:
                    total += max(0.0, values[i] - values[i - 1])
            return total / float(window_s)
        in_win = [v for t, v in zip(ts, values) if t >= start]
        if not in_win:
            return 0.0
        if series.kind == "gauge":
            return sum(in_win) / len(in_win)
        return sum(in_win) / float(window_s)

    def window_rate(self, name: str, tags: Optional[Dict[str, str]] = None,
                    window_s: float = 60.0,
                    now: Optional[float] = None) -> float:
        """Per-second rate over the trailing window — THE autoscaler query.

        counter: sum of positive deltas between consecutive samples whose
        later point falls in the window, over ``window_s`` (a reset — the
        total dropping — contributes 0, not a negative spike).
        value: sum of in-window points over ``window_s``.
        gauge: the windowed mean (a level has no meaningful rate; the mean
        is what "utilization over the last minute" asks for).

        Queries whose tag-set has no exact series aggregate every series
        carrying a superset of the tags: counters/values sum (total rate),
        gauges average (mean level across tag-sets).
        """
        matches = self._match(name, tags)
        if not matches:
            return 0.0
        t1 = time.time() if now is None else float(now)
        start = t1 - float(window_s)
        with self._lock:
            rates = [self._rate_locked(s, start, window_s) for s in matches]
        if matches[0].kind == "gauge":
            return sum(rates) / len(rates)
        return sum(rates)

    def window_sum(self, name: str, tags: Optional[Dict[str, str]] = None,
                   window_s: float = 60.0,
                   now: Optional[float] = None) -> float:
        """Total over the trailing window: counter → increase, value →
        sum of points, gauge → windowed mean (summing levels is noise).
        Subset-tag queries aggregate like :meth:`window_rate`."""
        matches = self._match(name, tags)
        if not matches:
            return 0.0
        rate = self.window_rate(name, tags, window_s, now)
        return rate if matches[0].kind == "gauge" else rate * float(window_s)

    def window_values(self, name: str,
                      tags: Optional[Dict[str, str]] = None,
                      window_s: float = 60.0,
                      now: Optional[float] = None) -> List[float]:
        """All in-window point values across every matching series (the
        SLO watchdog's bad-fraction input: each point is one request's
        latency, so "fraction over threshold" is exact, not bucketed)."""
        matches = self._match(name, tags)
        t1 = time.time() if now is None else float(now)
        start = t1 - float(window_s)
        out: List[float] = []
        with self._lock:
            for series in matches:
                ts, values = series.window(start)
                out.extend(v for t, v in zip(ts, values) if t >= start)
        return out

    def window_percentile(self, name: str, q: float,
                          tags: Optional[Dict[str, str]] = None,
                          window_s: float = 60.0,
                          now: Optional[float] = None) -> float:
        """q-th percentile (q in [0, 100]) of in-window point values —
        exact over the retained points, unlike bucketed estimates.
        Subset-tag queries pool points across matching series."""
        if not 0 <= q <= 100:
            raise ValueError(f"q must be in [0, 100], got {q}")
        in_win = sorted(self.window_values(name, tags, window_s, now))
        if not in_win:
            return 0.0
        rank = min(len(in_win) - 1, int(round((q / 100.0) * (len(in_win) - 1))))
        return in_win[rank]

    def latest(self, name: str,
               tags: Optional[Dict[str, str]] = None) -> Optional[float]:
        """Most recent point of the EXACT tag-set (no subset rollup — a
        "latest" across tag-sets has no single meaningful value)."""
        series = self._get(name, tags)
        if series is None or not series.values:
            return None
        with self._lock:
            return series.values[-1]

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted({name for name, _ in self._series})

    # --------------------------------------------- cross-process movement
    def snapshot(self, since: Optional[float] = None) -> Dict[str, Any]:
        """Serializable copy of retained points (optionally only those at
        or after ``since``) — what a node ships to the head collector."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            for series in self._series.values():
                i = (bisect.bisect_left(series.ts, float(since))
                     if since is not None else 0)
                if i >= len(series.ts):
                    continue
                out.append({"name": series.name, "tags": dict(series.tags),
                            "kind": series.kind,
                            "points": list(zip(series.ts[i:],
                                               series.values[i:]))})
        return {"series": out}

    def merge_snapshot(self, snap: Dict[str, Any],
                       extra_tags: Optional[Dict[str, str]] = None) -> int:
        """Fold another aggregator's snapshot in; ``extra_tags`` (e.g.
        ``{"node": <id>}``) keep per-source series distinct so counter
        deltas never mix totals from different processes."""
        n = 0
        for series in snap.get("series", ()):
            tags = dict(series.get("tags") or {})
            if extra_tags:
                tags.update(extra_tags)
            for ts, value in series.get("points", ()):
                self.observe(series["name"], value, tags,
                             kind=series.get("kind", "value"), ts=ts)
                n += 1
        return n

    def openmetrics_text(self, windows: Sequence[float] = (60.0,),
                         now: Optional[float] = None) -> str:
        """OpenMetrics exposition of the window state: for every series,
        its last sample (``<name>_last``) and per-window rollups
        (``<name>_roll{window_s="..."}`` — rate for counters/values, mean
        for gauges).  Ends with ``# EOF`` per the OpenMetrics spec."""
        with self._lock:
            keys = sorted(self._series)
        lines: List[str] = []
        seen_help = set()
        for name, tag_items in keys:
            series = self._get(name, dict(tag_items))
            if series is None or not series.values:
                continue
            if name not in seen_help:
                seen_help.add(name)
                lines.append(f"# TYPE {name}_last gauge")
                lines.append(f"# TYPE {name}_roll gauge")
            body = ",".join(f'{k}="{_metrics._escape(v)}"'
                            for k, v in tag_items)
            base = f"{name}_last{{{body}}}" if body else f"{name}_last"
            lines.append(f"{base} {_metrics._fmt(series.values[-1])}")
            for w in windows:
                rate = self.window_rate(name, dict(tag_items), w, now)
                wbody = body + ("," if body else "") + f'window_s="{w:g}"'
                lines.append(f"{name}_roll{{{wbody}}} {_metrics._fmt(rate)}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"


class TimeSeriesCollector:
    """Head-side collector: nodes push snapshots, queries see the cluster.

    A plain class so tests can drive it in-process; wrap it in an actor
    with :func:`start_collector` for the cluster deployment.  Per-source
    series stay distinct via a ``node`` tag; ``window_rate`` without tags
    sums the per-node rates (counter/value kinds) so "cluster request
    rate" is one call.
    """

    def __init__(self, max_window_s: float = DEFAULT_MAX_WINDOW_S):
        self._agg = TimeSeriesAggregator(max_window_s)

    def push(self, snapshot: Dict[str, Any], source: str = "") -> int:
        extra = {"node": str(source)} if source else None
        return self._agg.merge_snapshot(snapshot, extra_tags=extra)

    def window_rate(self, name: str, tags: Optional[Dict[str, str]] = None,
                    window_s: float = 60.0,
                    now: Optional[float] = None) -> float:
        # Cluster view (no/partial tags, e.g. missing ``node``) falls out
        # of the aggregator's own subset rollup: per-source series sum
        # (counter/value kinds) or average (gauges).
        return self._agg.window_rate(name, tags, window_s, now)

    def openmetrics_text(self, windows: Sequence[float] = (60.0,),
                         now: Optional[float] = None) -> str:
        return self._agg.openmetrics_text(windows, now)

    def series_names(self) -> List[str]:
        return self._agg.series_names()


def _subset(want: Optional[Dict[str, str]], have: Dict[str, str]) -> bool:
    return all(have.get(k) == v for k, v in (want or {}).items())


COLLECTOR_NAME = "TIMESERIES_COLLECTOR"


def start_collector(max_window_s: float = DEFAULT_MAX_WINDOW_S):
    """Get-or-create the named head-side collector actor."""
    import ray_tpu

    try:
        return ray_tpu.get_actor(COLLECTOR_NAME)
    except Exception:
        pass
    return ray_tpu.remote(TimeSeriesCollector).options(
        name=COLLECTOR_NAME).remote(max_window_s)


_aggregator: Optional[TimeSeriesAggregator] = None
_aggregator_lock = threading.Lock()


def get_aggregator() -> TimeSeriesAggregator:
    """The process-wide aggregator (what ``/timeseries`` samples into)."""
    global _aggregator
    with _aggregator_lock:
        if _aggregator is None:
            _aggregator = TimeSeriesAggregator()
        return _aggregator
