"""ActorPool: load-balance tasks over a fixed set of actors
(ref: python/ray/util/actor_pool.py ActorPool)."""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class ActorPool:
    """Tasks are dispatched to idle actors; excess submissions queue and
    drain as actors free up.  Results come back ordered (``get_next``/
    ``map``) or in completion order (``get_next_unordered``/
    ``map_unordered``)."""

    def __init__(self, actors: Iterable[Any]):
        self._idle: List[Any] = list(actors)
        if not self._idle:
            raise ValueError("ActorPool needs at least one actor")
        self._future_to_actor: dict = {}   # ref -> (index, actor)
        self._index_to_future: dict = {}   # submission index -> ref
        self._pending: List[tuple] = []    # (fn, value) waiting for an actor
        self._next_task_index = 0
        self._next_return_index = 0

    # ------------------------------------------------------------ map APIs
    def map(self, fn: Callable[[Any, Any], Any], values: Iterable[Any]):
        """Lazily yield ordered results; `fn(actor, value)` returns an
        ObjectRef.  Results stream as the pipeline drains — nothing is
        eagerly ray_tpu.get()'d up front."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]):
        """Results in completion order."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # ------------------------------------------------------- submit/get APIs
    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        if self._idle:
            self._dispatch(fn, self._idle.pop(), value)
        else:
            self._pending.append((fn, value))

    def _dispatch(self, fn, actor, value) -> None:
        ref = fn(actor, value)
        i = self._next_task_index
        self._next_task_index += 1
        self._future_to_actor[ref] = (i, actor)
        self._index_to_future[i] = ref

    def _free(self, actor) -> None:
        if self._pending:
            fn, value = self._pending.pop(0)
            self._dispatch(fn, actor, value)
        else:
            self._idle.append(actor)

    def has_next(self) -> bool:
        return bool(self._index_to_future or self._pending)

    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Next result in submission order (ref: ActorPool.get_next)."""
        # Skip indices already consumed by get_next_unordered.
        while (self._next_return_index < self._next_task_index
               and self._next_return_index not in self._index_to_future):
            self._next_return_index += 1
        if self._next_return_index not in self._index_to_future:
            if self._pending:
                # Tasks queued but nothing in flight and no idle actor to
                # dispatch to (actors were pop_idle()'d away) — deadlock,
                # not end-of-stream.
                raise RuntimeError(
                    f"{len(self._pending)} submitted task(s) can never run: "
                    "the pool has no in-flight work and no idle actors")
            raise StopIteration("no pending results")
        ref = self._index_to_future[self._next_return_index]
        ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next timed out")
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        _, actor = self._future_to_actor.pop(ref)
        # Free BEFORE get: a raising task must still return its actor to the
        # pool (ref: Ray's ActorPool does the same).
        self._free(actor)
        return ray_tpu.get(ref)

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        if not self._future_to_actor:
            if self._pending:
                raise RuntimeError(
                    f"{len(self._pending)} submitted task(s) can never run: "
                    "the pool has no in-flight work and no idle actors")
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(list(self._future_to_actor),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        ref = ready[0]
        i, actor = self._future_to_actor.pop(ref)
        del self._index_to_future[i]
        self._free(actor)
        return ray_tpu.get(ref)

    def push(self, actor: Any) -> None:
        """Add an actor to the pool (ref: ActorPool.push)."""
        self._free(actor)

    def pop_idle(self) -> Optional[Any]:
        return self._idle.pop() if self._idle else None

    def has_free(self) -> bool:
        return bool(self._idle)
