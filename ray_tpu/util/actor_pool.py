"""ActorPool: load-balance tasks over a fixed set of actors
(ref: python/ray/util/actor_pool.py ActorPool)."""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class ActorPool:
    def __init__(self, actors: Iterable[Any]):
        self._idle: List[Any] = list(actors)
        if not self._idle:
            raise ValueError("ActorPool needs at least one actor")
        self._future_to_actor: dict = {}
        self._pending: List[tuple] = []  # (fn, value) waiting for an actor
        self._unordered_results: List[Any] = []

    # ------------------------------------------------------------ map APIs
    def map(self, fn: Callable[[Any, Any], Any], values: Iterable[Any]):
        """Ordered results; `fn(actor, value)` returns an ObjectRef."""
        refs = []
        values = list(values)
        submitted = 0
        # Prime every idle actor, then pipeline: wait for the oldest ref
        # before submitting the next value to its actor.
        inflight: List[tuple] = []  # (ref, actor)
        for v in values:
            if self._idle:
                actor = self._idle.pop()
                inflight.append((fn(actor, v), actor))
                submitted += 1
            else:
                break
        next_i = submitted
        results = []
        while inflight:
            ref, actor = inflight.pop(0)
            results.append(ray_tpu.get(ref))
            if next_i < len(values):
                inflight.append((fn(actor, values[next_i]), actor))
                next_i += 1
            else:
                self._idle.append(actor)
        return iter(results)

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]):
        """Results in completion order."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # ------------------------------------------------------- submit/get APIs
    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = (fn, actor)
        else:
            self._pending.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor or self._pending
                    or self._unordered_results)

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        if self._unordered_results:
            return self._unordered_results.pop(0)
        if not self._future_to_actor:
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(list(self._future_to_actor),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        ref = ready[0]
        fn, actor = self._future_to_actor.pop(ref)
        result = ray_tpu.get(ref)
        if self._pending:
            next_fn, value = self._pending.pop(0)
            new_ref = next_fn(actor, value)
            self._future_to_actor[new_ref] = (next_fn, actor)
        else:
            self._idle.append(actor)
        return result

    def push(self, actor: Any) -> None:
        """Add an actor to the pool (ref: ActorPool.push)."""
        if self._pending:
            fn, value = self._pending.pop(0)
            ref = fn(actor, value)
            self._future_to_actor[ref] = (fn, actor)
        else:
            self._idle.append(actor)

    def pop_idle(self) -> Optional[Any]:
        return self._idle.pop() if self._idle else None

    def has_free(self) -> bool:
        return bool(self._idle)
