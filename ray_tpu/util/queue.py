"""Distributed FIFO queue backed by an actor
(ref: python/ray/util/queue.py Queue — an actor-hosted asyncio.Queue)."""

from __future__ import annotations

import time
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote
class _QueueActor:
    def __init__(self, maxsize: int):
        from collections import deque

        self._maxsize = maxsize
        self._items: deque = deque()

    def qsize(self) -> int:
        return len(self._items)

    def put_nowait(self, item) -> bool:
        if self._maxsize > 0 and len(self._items) >= self._maxsize:
            return False
        self._items.append(item)
        return True

    def put_nowait_batch(self, items: List[Any]) -> bool:
        if self._maxsize > 0 and len(self._items) + len(items) > self._maxsize:
            return False
        self._items.extend(items)
        return True

    def get_nowait(self):
        if not self._items:
            return False, None
        return True, self._items.popleft()

    def get_nowait_batch(self, n: int):
        got = []
        while self._items and len(got) < n:
            got.append(self._items.popleft())
        return got


class Queue:
    """(ref: util/queue.py Queue).  Poll-based blocking: callers retry the
    actor's nowait ops until the deadline — no driver-side locks, any number
    of producer/consumer tasks or actors can share the handle."""

    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        opts = actor_options or {}
        self.maxsize = maxsize
        self.actor = _QueueActor.options(**opts).remote(maxsize)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self.maxsize > 0 and self.qsize() >= self.maxsize

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray_tpu.get(self.actor.put_nowait.remote(item)):
                return
            if not block:
                raise Full
            if deadline is not None and time.monotonic() > deadline:
                raise Full
            time.sleep(0.005)

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        if not ray_tpu.get(self.actor.put_nowait_batch.remote(list(items))):
            raise Full

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if ok:
                return item
            if not block:
                raise Empty
            if deadline is not None and time.monotonic() > deadline:
                raise Empty
            time.sleep(0.005)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        return ray_tpu.get(self.actor.get_nowait_batch.remote(num_items))

    def shutdown(self) -> None:
        ray_tpu.kill(self.actor)
