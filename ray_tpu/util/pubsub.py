"""Publisher/subscriber channels with long-poll delivery.

TPU-native analogue of the reference's pubsub module (ref: src/ray/pubsub/
— Publisher publisher.h:297 buffers per-channel messages and answers
subscribers' long-poll requests; Subscriber subscriber.h:329 re-polls and
dispatches callbacks).  The reference uses this for GCS broadcast and
worker-to-worker object-eviction signals; here channels back in-process
control-plane fanout (the serve long-poll is a specialized sibling) and are
reachable cross-process through the nested-API backchannel like every other
driver-side facility.

Semantics kept from the reference:
- per-channel sequence numbers; a subscriber polls "give me everything
  after seq N" and blocks until something newer arrives (long-poll);
- bounded per-channel history — a subscriber that lags past the buffer gets
  the oldest retained message next (the reference drops to newest-snapshot
  the same way for GCS channels);
- subscriptions are per-key or whole-channel.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple


class Publisher:
    """Per-channel buffered fanout with long-poll wakeups."""

    def __init__(self, max_buffer: int = 1024):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._max_buffer = max_buffer
        #: channel -> deque of (seq, key, message)
        self._channels: Dict[str, deque] = {}
        self._seq: Dict[str, int] = {}

    def publish(self, channel: str, message: Any, key: str = "") -> int:
        """Append; wakes every parked poll.  Returns the message's seq."""
        with self._cv:
            seq = self._seq.get(channel, 0) + 1
            self._seq[channel] = seq
            buf = self._channels.setdefault(
                channel, deque(maxlen=self._max_buffer))
            buf.append((seq, key, message))
            self._cv.notify_all()
            return seq

    def poll(self, channel: str, after_seq: int = 0,
             key: Optional[str] = None,
             timeout: Optional[float] = None) -> List[Tuple[int, str, Any]]:
        """Long-poll: block until messages newer than ``after_seq`` exist
        (optionally filtered by key); returns [] on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                buf = self._channels.get(channel, ())
                out = [(s, k, m) for (s, k, m) in buf
                       if s > after_seq and (key is None or k == key)]
                if out:
                    return out
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return []
                self._cv.wait(remaining)

    def latest_seq(self, channel: str) -> int:
        with self._lock:
            return self._seq.get(channel, 0)


class Subscriber:
    """Callback-dispatching poll loop (ref: subscriber.h:329).

    ``subscribe(channel, callback, key=...)`` registers interest; a single
    daemon thread long-polls the publisher and dispatches new messages in
    order.  ``unsubscribe``/``close`` stop delivery.
    """

    def __init__(self, publisher: Publisher):
        self._pub = publisher
        self._lock = threading.Lock()
        #: (channel, key-or-None) -> list of callbacks
        self._subs: Dict[Tuple[str, Optional[str]], List[Callable]] = {}
        self._cursor: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def subscribe(self, channel: str, callback: Callable[[str, Any], None],
                  key: Optional[str] = None) -> None:
        with self._lock:
            self._subs.setdefault((channel, key), []).append(callback)
            self._cursor.setdefault(channel, self._pub.latest_seq(channel))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, name="pubsub-subscriber", daemon=True)
                self._thread.start()

    def unsubscribe(self, channel: str, key: Optional[str] = None) -> None:
        with self._lock:
            self._subs.pop((channel, key), None)

    def close(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                channels = {c for (c, _k) in self._subs}
            if not channels:
                time.sleep(0.05)
                continue
            for channel in channels:
                msgs = self._pub.poll(channel,
                                      after_seq=self._cursor.get(channel, 0),
                                      timeout=0.1)
                if not msgs:
                    continue
                self._cursor[channel] = msgs[-1][0]
                with self._lock:
                    subs = {k: list(cbs) for k, cbs in self._subs.items()
                            if k[0] == channel}
                for seq, key, message in msgs:
                    for (c, filt), cbs in subs.items():
                        if filt is not None and filt != key:
                            continue
                        for cb in cbs:
                            try:
                                cb(key, message)
                            except Exception:  # noqa: BLE001 — isolate subscribers
                                pass


_global_publisher: Optional[Publisher] = None
_global_lock = threading.Lock()


def global_publisher() -> Publisher:
    """The process-wide control-plane publisher (ref: the GCS publisher —
    one per head)."""
    global _global_publisher
    with _global_lock:
        if _global_publisher is None:
            _global_publisher = Publisher()
        return _global_publisher
