"""Ray Client equivalent: a remote-driver mode over TCP.

(ref: python/ray/util/client/ — server/server.py RayletServicer:96 converts
client RPCs into real calls; proto ray_client.proto.)  Here the server
reuses the nested-API request handler that already powers process-worker
backchannels (_private/client_runtime._handle): each TCP connection is one
remote driver, served by its own thread with borrowed-ref tracking, and the
client side installs the same ClientRuntime proxy over a socket transport —
so `ray_tpu.init(address="ray://host:port")` gives the full task/actor/
object API against a cluster running elsewhere.

Wire framing: u32 little-endian length prefix per message, same
serialization as the in-process pipes.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Optional, Tuple


class _SocketConn:
    """Pipe-shaped adapter (send_bytes/recv_bytes) over a TCP socket."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._rfile = sock.makefile("rb")

    def send_bytes(self, data: bytes) -> None:
        self._sock.sendall(struct.pack("<I", len(data)) + data)

    def recv_bytes(self) -> bytes:
        header = self._rfile.read(4)
        if len(header) < 4:
            raise EOFError("client connection closed")
        (n,) = struct.unpack("<I", header)
        data = self._rfile.read(n)
        if len(data) < n:
            raise EOFError("client connection closed mid-message")
        return data

    def close(self) -> None:
        try:
            self._rfile.close()
        finally:
            self._sock.close()


class ClientServer:
    """Accepts remote drivers; one serve thread per connection
    (ref: server/server.py:96 — the server side of ray://)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        from ray_tpu._private.runtime import get_runtime

        get_runtime()  # fail fast if no runtime to serve
        self._listener = socket.create_server((host, port))
        self.host, self.port = self._listener.getsockname()[:2]
        self.address = f"ray://{self.host}:{self.port}"
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, name="ray_tpu_client_server", daemon=True)
        self._thread.start()

    def _accept_loop(self) -> None:
        from ray_tpu._private.client_runtime import serve_backchannel

        while not self._stop.is_set():
            try:
                sock, addr = self._listener.accept()
            except OSError:
                # A client aborting mid-handshake must not kill the listener;
                # sleep so persistent errors (fd exhaustion) don't busy-spin.
                if self._stop.is_set() or self._listener.fileno() < 0:
                    return
                import time

                time.sleep(0.02)
                continue
            conn = _SocketConn(sock)
            threading.Thread(
                target=serve_backchannel, args=(conn,),
                name=f"ray_tpu_client_conn_{addr[1]}", daemon=True).start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass


def connect(address: str):
    """Connect this process to a remote cluster; installs a ClientRuntime
    so the whole ray_tpu API proxies over the wire (client side of ray://)."""
    from ray_tpu._private.client_runtime import ClientRuntime
    from ray_tpu._private.runtime import install_runtime

    host, port = parse_address(address)
    sock = socket.create_connection((host, port), timeout=30)
    sock.settimeout(None)
    conn = _SocketConn(sock)
    runtime = ClientRuntime(conn, worker_id=f"ray-client-{sock.getsockname()[1]}")
    runtime._client_conn = conn  # keep for disconnect
    install_runtime(runtime)
    return runtime


def parse_address(address: str) -> Tuple[str, int]:
    if not address.startswith("ray://"):
        raise ValueError(f"client address must look like ray://host:port, "
                         f"got {address!r}")
    hostport = address[len("ray://"):]
    host, _, port_s = hostport.rpartition(":")
    if not host or not port_s.isdigit():
        raise ValueError(f"client address must look like ray://host:port, "
                         f"got {address!r}")
    return host, int(port_s)
