"""Developer tooling that ships inside the package but never runs on a
hot path: the static analyzer (``ray_tpu.devtools.analysis``) lives here
so its checkers can be imported by tests and the ``scripts/analyze.py``
CLI without a separate install."""
