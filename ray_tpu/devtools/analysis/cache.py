"""Incremental analysis cache — ``scripts/analyze.py --changed-only``.

Per-module memoisation keyed by (mtime, sha256): an unchanged module's
findings *and* its cross-module scratch contributions (collect-phase
``# pairs_with:`` declarations, registry-usage sets) are replayed from
``.analysis_cache.json`` instead of re-parsed and re-checked, so the
tier-1 analysis gate stays <10s as the repo grows.  Cross-module
*aggregate* checks (``Checker.finalize``) always re-run — they are pure
functions of the merged scratch, which the cache reconstructs exactly.

Invalidation, broadest first:

* analyzer fingerprint — any change to ``devtools/analysis/**`` sources,
  the enabled-checker list, or the three registry source files
  (fault_injection / tracing / slo) drops the whole cache;
* collect fingerprint — when the merged cross-module declarations (e.g.
  a ``# pairs_with:`` added in one file) differ from what the cached
  findings were computed under, every module is re-checked: a
  declaration in file A changes what is a violation in file B;
* per-file (mtime, sha256) — a matching mtime skips even the read; a
  changed mtime with an unchanged hash refreshes the mtime only.

The cache file is an implementation detail (gitignored, atomically
replaced); a corrupt or version-skewed cache silently degrades to a
full run.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

from . import core

CACHE_VERSION = 3
CACHE_BASENAME = ".analysis_cache.json"

#: registry sources whose content feeds every module's checks
_REGISTRY_FILES = (
    os.path.join("_private", "fault_injection.py"),
    os.path.join("util", "tracing.py"),
    os.path.join("serve", "slo.py"),
)


# ------------------------------------------------------------------- codec
# ctx.scratch holds sets and tuples; JSON has neither.  Tag them.

def _encode(obj):
    if isinstance(obj, (set, frozenset)):
        return {"__set__": sorted((_encode(v) for v in obj), key=repr)}
    if isinstance(obj, tuple):
        return {"__tuple__": [_encode(v) for v in obj]}
    if isinstance(obj, dict):
        return {"__dict__": [[_encode(k), _encode(v)]
                             for k, v in sorted(obj.items(), key=repr)]}
    if isinstance(obj, list):
        return [_encode(v) for v in obj]
    return obj


def _decode(obj):
    if isinstance(obj, dict):
        if "__set__" in obj:
            return set(_decode(v) for v in obj["__set__"])
        if "__tuple__" in obj:
            return tuple(_decode(v) for v in obj["__tuple__"])
        if "__dict__" in obj:
            return {_decode(k): _decode(v) for k, v in obj["__dict__"]}
    if isinstance(obj, list):
        return [_decode(v) for v in obj]
    return obj


def _merge_scratch(dst: dict, src: dict) -> None:
    for key, value in src.items():
        if key not in dst:
            dst[key] = value
            continue
        cur = dst[key]
        if isinstance(cur, set) and isinstance(value, (set, frozenset)):
            cur |= value
        elif isinstance(cur, dict) and isinstance(value, dict):
            for k, v in value.items():
                if k in cur and isinstance(cur[k], list) \
                        and isinstance(v, list):
                    cur[k].extend(v)
                elif k in cur and isinstance(cur[k], (set, frozenset)) \
                        and isinstance(v, (set, frozenset)):
                    cur[k] = set(cur[k]) | set(v)
                else:
                    cur.setdefault(k, v)
        elif isinstance(cur, list) and isinstance(value, list):
            cur.extend(value)
        # scalars: first writer wins (collect contributions are per-file
        # disjoint in practice)


def _sha(data: str) -> str:
    return hashlib.sha256(data.encode("utf-8")).hexdigest()


def _file_sha(path: str) -> Optional[str]:
    try:
        with open(path, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()
    except OSError:
        return None


def analyzer_fingerprint(checkers: Sequence[core.Checker],
                         package_dir: Optional[str]) -> str:
    """Hash of everything that changes analysis results besides the
    analyzed files themselves."""
    h = hashlib.sha256()
    h.update(str(CACHE_VERSION).encode())
    h.update(",".join(sorted(c.name for c in checkers)).encode())
    analysis_dir = os.path.dirname(os.path.abspath(__file__))
    for dirpath, dirnames, filenames in os.walk(analysis_dir):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                sha = _file_sha(os.path.join(dirpath, fn))
                h.update(f"{fn}:{sha}".encode())
    if package_dir:
        for rel in _REGISTRY_FILES:
            sha = _file_sha(os.path.join(package_dir, rel))
            h.update(f"{rel}:{sha}".encode())
    return h.hexdigest()


def _finding_to_dict(f: core.Finding) -> dict:
    return {"check": f.check, "path": f.path, "line": f.line,
            "symbol": f.symbol, "message": f.message, "detail": f.detail}


def _finding_from_dict(d: dict) -> core.Finding:
    return core.Finding(check=d["check"], path=d["path"], line=d["line"],
                        symbol=d["symbol"], message=d["message"],
                        detail=d["detail"])


def _package_dir(files: List[str], root: str) -> Optional[str]:
    for f in files:
        if f.replace(os.sep, "/").endswith(
                "ray_tpu/_private/fault_injection.py"):
            return os.path.dirname(os.path.dirname(f))
    candidate = os.path.join(root, "ray_tpu")
    return candidate if os.path.isdir(candidate) else None


def run_cached(paths: Sequence[str], checkers: Sequence[core.Checker],
               root: Optional[str] = None, exclude: Sequence[str] = (),
               ctx: Optional[core.AnalysisContext] = None,
               cache_path: Optional[str] = None
               ) -> Tuple[List[core.Finding], dict]:
    """Drop-in for :func:`core.run` with per-module memoisation."""
    root = root or os.getcwd()
    cache_path = cache_path or os.path.join(root, CACHE_BASENAME)
    t0 = time.monotonic()
    files = list(core.iter_python_files(paths, exclude))
    package_dir = _package_dir(files, root)
    fingerprint = analyzer_fingerprint(checkers, package_dir)

    cache: dict = {}
    try:
        with open(cache_path, encoding="utf-8") as f:
            loaded = json.load(f)
        if loaded.get("version") == CACHE_VERSION \
                and loaded.get("fingerprint") == fingerprint:
            cache = loaded
    except (OSError, ValueError):
        cache = {}
    cached_files: Dict[str, dict] = cache.get("files", {})

    ctx = ctx or core.AnalysisContext(root=root)
    ctx.full_package = any(
        f.replace(os.sep, "/").endswith("_private/fault_injection.py")
        for f in files)
    if package_dir is not None:
        core.load_registries(ctx, package_dir)

    # ---------------------------------------------- classify changed files
    entries: Dict[str, dict] = {}   # relpath -> new cache entry
    changed: Dict[str, core.SourceModule] = {}
    hits = 0
    for abspath in files:
        rel = os.path.relpath(abspath, root).replace(os.sep, "/")
        try:
            mtime = os.stat(abspath).st_mtime_ns
        except OSError:
            continue
        old = cached_files.get(rel)
        if old is not None and old.get("mtime") == mtime:
            entries[rel] = old
            hits += 1
            continue
        sha = _file_sha(abspath)
        if sha is None:
            continue
        if old is not None and old.get("sha") == sha:
            old["mtime"] = mtime
            entries[rel] = old
            hits += 1
            continue
        module = core.parse_module(abspath, root)
        if module is None:
            continue
        changed[rel] = module
        entries[rel] = {"mtime": mtime, "sha": sha}

    # ------------------------------------------------------ collect phase
    for rel, module in changed.items():
        cctx = core.AnalysisContext(
            root=root, fault_points=ctx.fault_points,
            span_names=ctx.span_names, span_prefixes=ctx.span_prefixes,
            slo_objectives=ctx.slo_objectives,
            metric_prefixes=ctx.metric_prefixes)
        for checker in checkers:
            checker.collect(module, cctx)
        entries[rel]["collect"] = _encode(cctx.scratch)
    merged_collect: dict = {}
    for rel in sorted(entries):
        _merge_scratch(merged_collect, _decode(entries[rel].get("collect",
                                                                {})))
    collect_fp = _sha(json.dumps(_encode(merged_collect), sort_keys=True))
    if cache.get("collect_fingerprint") not in (None, collect_fp):
        # Cross-module declarations changed: every cached finding may be
        # stale.  Re-check everything (parses only what wasn't parsed yet).
        for rel in list(entries):
            if rel in changed:
                continue
            abspath = os.path.join(root, rel.replace("/", os.sep))
            module = core.parse_module(abspath, root)
            if module is None:
                entries.pop(rel)
                continue
            changed[rel] = module
            hits -= 1
    have_findings = all("findings" in entries[rel] for rel in entries
                        if rel not in changed)
    if not have_findings:  # pragma: no cover — defensive vs corrupt cache
        for rel in list(entries):
            if rel not in changed and "findings" not in entries[rel]:
                abspath = os.path.join(root, rel.replace("/", os.sep))
                module = core.parse_module(abspath, root)
                if module is not None:
                    changed[rel] = module

    # -------------------------------------------------------- check phase
    collect_keys = set(merged_collect)
    for rel in sorted(changed):
        module = changed[rel]
        mctx = core.AnalysisContext(
            root=root, fault_points=ctx.fault_points,
            span_names=ctx.span_names, span_prefixes=ctx.span_prefixes,
            slo_objectives=ctx.slo_objectives,
            metric_prefixes=ctx.metric_prefixes,
            full_package=ctx.full_package)
        mctx.scratch = {k: v for k, v in merged_collect.items()}
        module_findings: List[core.Finding] = []
        for checker in checkers:
            for finding in checker.check_module(module, mctx):
                if checker.name in module.ignored_checks(finding.line):
                    continue
                module_findings.append(finding)
        entries[rel]["findings"] = [_finding_to_dict(f)
                                    for f in module_findings]
        entries[rel]["scratch"] = _encode(
            {k: v for k, v in mctx.scratch.items()
             if k not in collect_keys})

    # ------------------------------------------------------ finalize phase
    findings: List[core.Finding] = []
    ctx.scratch = dict(merged_collect)
    for rel in sorted(entries):
        entry = entries[rel]
        findings.extend(_finding_from_dict(d)
                        for d in entry.get("findings", ()))
        _merge_scratch(ctx.scratch, _decode(entry.get("scratch", {})))
    if ctx.full_package:
        for checker in checkers:
            findings.extend(checker.finalize(ctx))

    # --------------------------------------------------------------- save
    payload = {"version": CACHE_VERSION, "fingerprint": fingerprint,
               "collect_fingerprint": collect_fp, "files": entries}
    try:
        tmp = cache_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        os.replace(tmp, cache_path)
    except OSError:  # pragma: no cover — read-only checkout is fine
        pass

    stats = {"files": len(entries), "seconds": time.monotonic() - t0,
             "checks": [c.name for c in checkers],
             "cache_hits": max(hits, 0), "cache_misses": len(changed)}
    findings.sort(key=lambda f: (f.path, f.line, f.check))
    return findings, stats
