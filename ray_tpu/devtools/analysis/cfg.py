"""Flow-sensitive exit-path analysis for the paired-effect family.

Not a literal control-flow graph: an abstract interpreter over the
statement tree.  A checker classifies interesting call sites as *events*
(``+1`` forward effect / ``-1`` reversal, keyed by an opaque token such as
``("acquire_slot", "lane.req")``); :func:`function_exits` then walks every
explicit control path through the function — branches, loops,
``try/except/finally``, ``with``, early ``return``/``raise``/``break`` —
and reports, per exit, how many forward effects are still pending.

Modelling decisions (all favour under-reporting, the analyzer's bias):

* States merge at join points and saturate (pending caps at
  :data:`MAX_PENDING`, at most :data:`MAX_STATES` abstract states per
  program point), so path count never explodes.
* Loops are evaluated twice (zero, one and two iterations are
  distinguished; more iterations only re-saturate).
* Events in a ``for`` statement's iterator are charged *per iteration*:
  ``for slot in chan.read_ready(n): ...`` models the drain idiom where
  each drained item carries its own obligation.  The zero-iteration path
  consequently carries no event — a deliberate under-report.
* A forward event appearing in a ``with`` item is auto-reversed when the
  block is left *by any path* (context managers run ``__exit__`` on
  exceptions too).
* ``finally`` bodies are re-run against every exit that crosses them, so
  a reversal in ``finally`` covers all paths.
* Implicit exception edges (any call may raise) are **not** modelled;
  only explicit ``raise`` statements produce raise exits.  Leaks that
  need a mid-path exception to manifest are out of scope — use
  ``try/finally`` and the analyzer will verify it.
* Nested ``def``/``lambda`` bodies do not execute here and are skipped.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, Hashable, Iterator, List, Optional, Tuple

#: saturation bound for pending forward effects on one token
MAX_PENDING = 3
#: abstract-state cap per program point (overflow keeps the first N)
MAX_STATES = 24

Token = Hashable
#: token -> (pending forward effects, reversal seen on a normal path)
State = Dict[Token, Tuple[int, bool]]
Events = Dict[int, List[Tuple[Token, int]]]  # id(ast.Call) -> deltas


@dataclass(frozen=True)
class ExitPath:
    """One (exit site, abstract state) pair."""

    kind: str          # "return" | "raise" | "fallthrough"
    line: int
    in_handler: bool   # exit happens inside an except handler
    state: Tuple[Tuple[Token, Tuple[int, bool]], ...]

    def pending(self, token: Token) -> int:
        return dict(self.state).get(token, (0, False))[0]

    def saw_normal_reverse(self, token: Token) -> bool:
        return dict(self.state).get(token, (0, False))[1]


@dataclass
class _BlockResult:
    normal: List[State]
    breaks: List[State]
    continues: List[State]
    exits: List[ExitPath]


def _dedupe(states: List[State]) -> List[State]:
    seen, out = set(), []
    for st in states:
        key = frozenset(st.items())
        if key not in seen:
            seen.add(key)
            out.append(st)
        if len(out) >= MAX_STATES:
            break
    return out


class _Interp:
    def __init__(self, events: Events):
        self.events = events

    # ------------------------------------------------------------- events
    def _expr_events(self, nodes) -> List[Tuple[Token, int]]:
        out: List[Tuple[Token, int]] = []
        stack = list(nodes)
        while stack:
            n = stack.pop(0)
            if isinstance(n, ast.Lambda):
                continue  # deferred body: does not run here
            ev = self.events.get(id(n))
            if ev:
                out.extend(ev)
            stack.extend(ast.iter_child_nodes(n))
        return out

    def _stmt_header_events(self, stmt) -> List[Tuple[Token, int]]:
        """Events in the statement's own expressions (child statements are
        walked recursively by the block walker, not here)."""
        if isinstance(stmt, (ast.If, ast.While)):
            nodes = [stmt.test]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            nodes = []  # iterator events are charged per-iteration
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            nodes = []  # with-item events handled by _walk_with
        elif isinstance(stmt, ast.Try):
            nodes = []
        else:
            nodes = [c for c in ast.iter_child_nodes(stmt)
                     if isinstance(c, ast.expr)]
        return self._expr_events(nodes)

    @staticmethod
    def _apply(state: State, evs, in_handler: bool) -> State:
        if not evs:
            return state
        st = dict(state)
        for token, delta in evs:
            pending, saw = st.get(token, (0, False))
            if delta > 0:
                pending = min(pending + delta, MAX_PENDING)
            else:
                pending = max(pending + delta, 0)
                if not in_handler:
                    saw = True
            st[token] = (pending, saw)
        return st

    def _apply_all(self, states, evs, in_handler) -> List[State]:
        if not evs:
            return list(states)
        return _dedupe([self._apply(s, evs, in_handler) for s in states])

    # ------------------------------------------------------------- blocks
    def walk_block(self, stmts, states, in_handler,
                   boundaries: Optional[List[State]] = None) -> _BlockResult:
        """``boundaries`` (when given) collects the abstract states at the
        *entry* of every statement — the try-body walker uses the union as
        the except-handler entry states.  Deliberately not the post-state
        of the raising statement itself: when ``fd = os.open(...)`` raises,
        the fd never existed, so the handler must not inherit its forward
        effect (effects buried mid-expression before the raise are missed —
        the usual under-reporting trade)."""
        normal = _dedupe(list(states))
        breaks: List[State] = []
        continues: List[State] = []
        exits: List[ExitPath] = []
        for stmt in stmts:
            if not normal:
                break  # unreachable: every path already left the block
            if boundaries is not None:
                boundaries.extend(normal)
            r = self.walk_stmt(stmt, normal, in_handler)
            normal = _dedupe(r.normal)
            breaks.extend(r.breaks)
            continues.extend(r.continues)
            exits.extend(r.exits)
        return _BlockResult(normal, _dedupe(breaks), _dedupe(continues),
                            exits)

    # --------------------------------------------------------- statements
    def walk_stmt(self, stmt, states, in_handler) -> _BlockResult:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return _BlockResult(list(states), [], [], [])
        if isinstance(stmt, ast.Return):
            after = self._apply_all(states, self._stmt_header_events(stmt),
                                    in_handler)
            return _BlockResult([], [], [], [
                ExitPath("return", stmt.lineno, in_handler,
                         tuple(sorted(st.items(), key=repr)))
                for st in after])
        if isinstance(stmt, ast.Raise):
            after = self._apply_all(states, self._stmt_header_events(stmt),
                                    in_handler)
            return _BlockResult([], [], [], [
                ExitPath("raise", stmt.lineno, in_handler,
                         tuple(sorted(st.items(), key=repr)))
                for st in after])
        if isinstance(stmt, ast.Break):
            return _BlockResult([], list(states), [], [])
        if isinstance(stmt, ast.Continue):
            return _BlockResult([], [], list(states), [])
        if isinstance(stmt, ast.If):
            base = self._apply_all(states, self._expr_events([stmt.test]),
                                   in_handler)
            rb = self.walk_block(stmt.body, base, in_handler)
            ro = self.walk_block(stmt.orelse, base, in_handler)
            return _BlockResult(rb.normal + ro.normal,
                                rb.breaks + ro.breaks,
                                rb.continues + ro.continues,
                                rb.exits + ro.exits)
        if isinstance(stmt, ast.While):
            return self._walk_loop(stmt, states, in_handler,
                                   test_events=self._expr_events([stmt.test]),
                                   iter_events=[])
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return self._walk_loop(stmt, states, in_handler, test_events=[],
                                   iter_events=self._expr_events([stmt.iter]))
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._walk_with(stmt, states, in_handler)
        if isinstance(stmt, ast.Try):
            return self._walk_try(stmt, states, in_handler)
        after = self._apply_all(states, self._stmt_header_events(stmt),
                                in_handler)
        return _BlockResult(after, [], [], [])

    def _walk_loop(self, stmt, states, in_handler, test_events,
                   iter_events) -> _BlockResult:
        def one_iteration(entry):
            body_entry = self._apply_all(entry, iter_events, in_handler)
            return self.walk_block(stmt.body, body_entry, in_handler)

        zero = self._apply_all(states, test_events, in_handler)
        r1 = one_iteration(zero)
        again = self._apply_all(r1.normal + r1.continues, test_events,
                                in_handler)
        r2 = one_iteration(again)
        # ``orelse`` runs only when the loop finishes WITHOUT break — break
        # states jump straight past it.  The distinction matters: the
        # retry-loop idiom releases in the handler and raises exhaustion
        # from the else clause, so break-path pending must not bleed in.
        no_break = _dedupe(zero
                           + self._apply_all(r1.normal + r1.continues,
                                             test_events, in_handler)
                           + self._apply_all(r2.normal + r2.continues,
                                             test_events, in_handler))
        broke = r1.breaks + r2.breaks
        ro = self.walk_block(stmt.orelse, no_break, in_handler) \
            if stmt.orelse else _BlockResult(no_break, [], [], [])
        return _BlockResult(_dedupe(ro.normal + broke), ro.breaks,
                            ro.continues, r1.exits + r2.exits + ro.exits)

    def _walk_with(self, stmt, states, in_handler) -> _BlockResult:
        item_events = self._expr_events(
            [i.context_expr for i in stmt.items])
        base = self._apply_all(states, item_events, in_handler)
        held = [(token, -1) for token, delta in item_events if delta > 0]
        r = self.walk_block(stmt.body, base, in_handler)
        if not held:
            return r
        # __exit__ runs on every way out of the block, exceptions included.
        normal = self._apply_all(r.normal, held, in_handler)
        breaks = self._apply_all(r.breaks, held, in_handler)
        continues = self._apply_all(r.continues, held, in_handler)
        exits = [
            ExitPath(e.kind, e.line, e.in_handler, tuple(sorted(
                self._apply(dict(e.state), held, e.in_handler).items(),
                key=repr)))
            for e in r.exits]
        return _BlockResult(normal, breaks, continues, exits)

    def _walk_try(self, stmt, states, in_handler) -> _BlockResult:
        boundaries: List[State] = []
        rb = self.walk_block(stmt.body, states, in_handler,
                             boundaries=boundaries)
        handler_entry = _dedupe(boundaries)
        h_normal: List[State] = []
        h_breaks: List[State] = []
        h_continues: List[State] = []
        h_exits: List[ExitPath] = []
        for handler in stmt.handlers:
            rh = self.walk_block(handler.body, handler_entry, True)
            h_normal.extend(rh.normal)
            h_breaks.extend(rh.breaks)
            h_continues.extend(rh.continues)
            h_exits.extend(rh.exits)
        ro = self.walk_block(stmt.orelse, rb.normal, in_handler) \
            if stmt.orelse else _BlockResult(rb.normal, [], [], [])
        normal = ro.normal + h_normal
        breaks = rb.breaks + ro.breaks + h_breaks
        continues = rb.continues + ro.continues + h_continues
        exits = rb.exits + ro.exits + h_exits
        if not stmt.finalbody:
            return _BlockResult(normal, breaks, continues, exits)

        extra_exits: List[ExitPath] = []

        def through_finally(sts, handler_flag):
            rf = self.walk_block(stmt.finalbody, sts, handler_flag)
            extra_exits.extend(rf.exits)
            return rf.normal, rf.breaks, rf.continues

        normal, f_breaks, f_continues = through_finally(normal, in_handler)
        out_breaks, out_continues = list(f_breaks), list(f_continues)
        for sts, sink in ((breaks, out_breaks), (continues, out_continues)):
            for st in sts:
                n, b, c = through_finally([st], in_handler)
                sink.extend(n)
                out_breaks.extend(b)
                out_continues.extend(c)
        new_exits: List[ExitPath] = []
        for e in exits:
            n, b, c = through_finally([dict(e.state)], e.in_handler)
            out_breaks.extend(b)
            out_continues.extend(c)
            for st in n:
                new_exits.append(ExitPath(
                    e.kind, e.line, e.in_handler,
                    tuple(sorted(st.items(), key=repr))))
        return _BlockResult(_dedupe(normal), _dedupe(out_breaks),
                            _dedupe(out_continues), new_exits + extra_exits)


def function_exits(fn, events: Events) -> List[ExitPath]:
    """Every explicit exit of ``fn`` (returns, raises, and the final
    fallthrough) with its abstract pair-effect state."""
    interp = _Interp(events)
    r = interp.walk_block(fn.body, [{}], in_handler=False)
    exits = list(r.exits)
    end = getattr(fn, "end_lineno", None) or fn.lineno
    for st in r.normal:
        exits.append(ExitPath("fallthrough", end, False,
                              tuple(sorted(st.items(), key=repr))))
    return exits


def iter_functions(tree) -> Iterator[Tuple[str, ast.AST, Optional[str]]]:
    """Yield ``(symbol, fn_node, class_name)`` for every function in a
    module — methods as "Class.method", nested defs as "outer.inner"."""

    def walk(body, prefix: str, cls: Optional[str]):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                symbol = f"{prefix}{stmt.name}" if prefix else stmt.name
                yield symbol, stmt, cls
                yield from walk(stmt.body, symbol + ".", None)
            elif isinstance(stmt, ast.ClassDef):
                yield from walk(stmt.body, stmt.name + ".", stmt.name)
            else:
                for attr in ("body", "orelse", "finalbody"):
                    child = getattr(stmt, attr, None)
                    if child:
                        yield from walk(child, prefix, cls)
                for handler in getattr(stmt, "handlers", ()) or ():
                    yield from walk(handler.body, prefix, cls)

    yield from walk(tree.body, "", None)


def calls_in_function(fn) -> Iterator[ast.Call]:
    """Every call executed by ``fn`` itself — nested ``def``/``lambda``
    bodies excluded (they run later, on their own schedule)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop(0)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))
