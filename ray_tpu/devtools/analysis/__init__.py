"""Framework-aware static analyzer for ray_tpu (``scripts/analyze.py``).

Pure AST + tokenize — never imports the code it analyzes.  Eight
framework-aware checkers run over the package in tier-1 CI: the lexical
five (lock-discipline, atomicity, blocking-in-handler,
registry-consistency, lockstep-divergence) plus the flow-sensitive
exit-path family built on ``cfg.py`` (paired-effect, task-lifecycle,
thread-ownership).  Accepted findings live in ``analysis_baseline.json``
with one-line justifications.  See docs/static-analysis.md for the
checker catalog and the annotation conventions.
"""

from ray_tpu.devtools.analysis import baseline, cfg, core
from ray_tpu.devtools.analysis.cache import run_cached
from ray_tpu.devtools.analysis.checkers import (
    ALL_CHECKERS,
    CHECKERS_BY_NAME,
    make_checkers,
)
from ray_tpu.devtools.analysis.core import (
    AnalysisContext,
    Checker,
    Finding,
    analyze_source,
    run,
)

__all__ = [
    "ALL_CHECKERS", "CHECKERS_BY_NAME", "make_checkers",
    "AnalysisContext", "Checker", "Finding", "analyze_source", "run",
    "run_cached", "baseline", "cfg", "core",
]
