"""Framework-aware static analyzer for ray_tpu (``scripts/analyze.py``).

Pure AST + tokenize — never imports the code it analyzes.  Five
framework-aware checkers (lock-discipline, atomicity,
blocking-in-handler, registry-consistency, lockstep-divergence) run over
the package in tier-1 CI; accepted findings live in
``analysis_baseline.json`` with one-line justifications.  See
docs/static-analysis.md for the checker catalog and the ``guarded_by``
annotation convention.
"""

from ray_tpu.devtools.analysis import baseline, core
from ray_tpu.devtools.analysis.checkers import (
    ALL_CHECKERS,
    CHECKERS_BY_NAME,
    make_checkers,
)
from ray_tpu.devtools.analysis.core import (
    AnalysisContext,
    Checker,
    Finding,
    analyze_source,
    run,
)

__all__ = [
    "ALL_CHECKERS", "CHECKERS_BY_NAME", "make_checkers",
    "AnalysisContext", "Checker", "Finding", "analyze_source", "run",
    "baseline", "core",
]
