"""Core of the framework-aware static analyzer.

Plugin architecture: each checker is a subclass of :class:`Checker`
registered in ``checkers/__init__.py``; the :func:`run` driver parses
every target file once (AST + comment map via ``tokenize``) and hands the
shared :class:`SourceModule` to each enabled checker.  Findings carry a
*stable key* (no line numbers) so the baseline survives unrelated edits.

Annotation conventions (see docs/static-analysis.md):

  ``# guarded_by: _lock``     on an attribute (or module global) assignment:
                              every later read/write must happen inside a
                              ``with <owner>.<_lock>`` scope (or between
                              ``acquire()``/``release()``).
  ``# requires_lock: _lock``  on a ``def`` line: the method assumes its
                              caller holds the lock (``*_locked`` method
                              names get this implicitly).
  ``# blocking_ok: reason``   suppress a blocking-in-handler finding.
  ``# lockstep_ok: reason``   suppress a collective-divergence finding.
  ``# pairs_with: name``      on a ``def`` line: every call to this method
                              must be reversed by ``name`` on the same
                              receiver before every exit (strict).  On a
                              call line: that call site carries the same
                              obligation (the reverse may also match the
                              call's assignment target).
  ``# detached_ok: reason``   on an ``asyncio.create_task``/``ensure_future``
                              line: the task is intentionally unawaited.
  ``# owned_by_thread: name`` on an attribute assignment: the attribute is
                              owned by the thread running method ``name``
                              (or an external thread when ``name`` is not a
                              method) — cross-thread access without a lock
                              is flagged.
  ``# analysis: ignore[check-id] reason``
                              suppress any finding on that line.

The analyzer is pure AST + tokenize — it never imports the code under
analysis, so it is safe to run on broken trees and fast enough for tier-1
(<10s over the whole package, enforced by tests/test_analysis_static.py).
"""

from __future__ import annotations

import ast
import fnmatch
import io
import os
import re
import time
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

_MARKER_RE = re.compile(
    r"#\s*(guarded_by|requires_lock|blocking_ok|lockstep_ok"
    r"|pairs_with|detached_ok|owned_by_thread)\s*:\s*(\S[^#]*)")
_IGNORE_RE = re.compile(r"#\s*analysis:\s*ignore\[([a-z0-9_,\- ]+)\]")


@dataclass(frozen=True)
class Finding:
    """One violation.  ``key`` is the stable identity used for baselining:
    check + file + enclosing symbol + detail, deliberately line-free."""

    check: str
    path: str  # repo-relative, '/'-separated
    line: int
    symbol: str  # "Class.method", "function", or "<module>"
    message: str
    detail: str  # stable discriminator (attr/point/span/metric name)

    @property
    def key(self) -> str:
        return f"{self.check}:{self.path}:{self.symbol}:{self.detail}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.check}] {self.message}"


class SourceModule:
    """One parsed file: AST + per-line comment map + annotation indexes."""

    def __init__(self, abspath: str, relpath: str, text: str):
        self.abspath = abspath
        self.path = relpath.replace(os.sep, "/")
        self.text = text
        self.tree = ast.parse(text, filename=relpath)
        #: line -> full comment text ("# ..."), from tokenize (comments
        #: inside string literals never leak in).
        self.comments: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError):  # pragma: no cover
            pass

    def marker(self, line: int, name: str) -> Optional[str]:
        """Value of ``# <name>: <value>`` on ``line`` (stripped), or None."""
        comment = self.comments.get(line)
        if not comment:
            return None
        m = _MARKER_RE.search(comment)
        if m and m.group(1) == name:
            return m.group(2).strip()
        return None

    def marker_near(self, line: int, name: str) -> Optional[str]:
        """Like :meth:`marker`, but also accepts the marker on its own
        comment line directly above (the usual lint-suppression layout
        when the flagged line is too long to annotate inline)."""
        return self.marker(line, name) or self.marker(line - 1, name)

    def ignored_checks(self, line: int) -> Set[str]:
        comment = self.comments.get(line)
        if not comment:
            return set()
        m = _IGNORE_RE.search(comment)
        if not m:
            return set()
        return {c.strip() for c in m.group(1).split(",") if c.strip()}


# --------------------------------------------------------------- annotations

@dataclass
class GuardMap:
    """guarded_by/requires_lock annotations for one module."""

    #: class qualname -> {attr name -> lock attr name}
    class_guards: Dict[str, Dict[str, str]] = field(default_factory=dict)
    #: class qualname -> {method name -> lock attr name} (caller must hold)
    requires_lock: Dict[str, Dict[str, str]] = field(default_factory=dict)
    #: module-global name -> module-global lock name
    module_guards: Dict[str, str] = field(default_factory=dict)


def _assign_names(node: ast.stmt) -> Iterator[ast.expr]:
    if isinstance(node, ast.Assign):
        yield from node.targets
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        yield node.target


def collect_guards(module: SourceModule) -> GuardMap:
    guards = GuardMap()
    for node in module.tree.body:
        for target in _assign_names(node):
            if isinstance(target, ast.Name):
                lock = module.marker(node.lineno, "guarded_by")
                if lock:
                    guards.module_guards[target.id] = lock
    for cls in ast.walk(module.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        attr_guards: Dict[str, str] = {}
        req: Dict[str, str] = {}
        for node in ast.walk(cls):
            for target in _assign_names(node):
                if (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"):
                    lock = module.marker(node.lineno, "guarded_by")
                    if lock:
                        attr_guards[target.attr] = lock
        default_lock = None
        locks = set(attr_guards.values())
        if len(locks) == 1:
            default_lock = next(iter(locks))
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            lock = module.marker(fn.lineno, "requires_lock")
            if lock is None and fn.name.endswith("_locked"):
                lock = default_lock
            if lock is not None:
                req[fn.name] = lock
        if attr_guards:
            guards.class_guards[cls.name] = attr_guards
        if req:
            guards.requires_lock[cls.name] = req
    return guards


def _thread_target_name(call: ast.Call) -> Optional[str]:
    """``self._pump`` -> "_pump" for ``threading.Thread(target=self._pump)``
    and ``threading.Timer(delay, self._fire)``; None otherwise."""
    func = call.func
    name = func.attr if isinstance(func, ast.Attribute) else (
        func.id if isinstance(func, ast.Name) else None)
    if name not in ("Thread", "Timer"):
        return None
    target: Optional[ast.expr] = None
    for kw in call.keywords:
        if kw.arg in ("target", "function"):
            target = kw.value
    if target is None and name == "Timer" and len(call.args) >= 2:
        target = call.args[1]
    if (isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"):
        return target.attr
    return None


def collect_thread_targets(module: SourceModule) -> Dict[str, Set[str]]:
    """class name -> method names spawned as thread entry points anywhere in
    that class (``threading.Thread(target=self._x)`` / ``Timer(.., self._x)``).

    Methods listed here run on their own thread; the cross-thread-ownership
    checker treats everything else in the class as "some other thread"."""
    out: Dict[str, Set[str]] = {}
    for cls in ast.walk(module.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        entries: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Call):
                target = _thread_target_name(node)
                if target is not None:
                    entries.add(target)
        if entries:
            out[cls.name] = entries
    return out


# ------------------------------------------------------------------ context

@dataclass
class AnalysisContext:
    """Shared state handed to every checker.

    Registries are loaded once (AST-extracted from the package sources, no
    imports) by ``load_registries``; fixture tests inject their own."""

    root: str = "."
    fault_points: Optional[Set[str]] = None
    span_names: Optional[Set[str]] = None
    span_prefixes: Optional[Tuple[str, ...]] = None
    slo_objectives: Optional[Set[str]] = None
    metric_prefixes: Tuple[str, ...] = ("ray_tpu_", "serve_")
    #: set when the scan covers the whole package — enables aggregate
    #: (cross-module) checks like "registered fault point never consulted"
    full_package: bool = False
    #: scratch space for aggregating checkers (keyed by checker name)
    scratch: Dict[str, object] = field(default_factory=dict)


def _extract_literal_dict_keys(tree: ast.AST, var_name: str) -> Set[str]:
    for node in ast.walk(tree):
        for target in _assign_names(node):
            if isinstance(target, ast.Name) and target.id == var_name:
                value = getattr(node, "value", None)
                if isinstance(value, ast.Dict):
                    return {k.value for k in value.keys
                            if isinstance(k, ast.Constant)
                            and isinstance(k.value, str)}
    return set()


def load_registries(ctx: AnalysisContext, package_dir: str) -> None:
    """Fill ctx's fault-point, span and SLO-objective registries from the
    package sources (AST only — the analyzer never imports the analyzed
    code)."""
    fi = os.path.join(package_dir, "_private", "fault_injection.py")
    tr = os.path.join(package_dir, "util", "tracing.py")
    sl = os.path.join(package_dir, "serve", "slo.py")
    if ctx.fault_points is None and os.path.exists(fi):
        with open(fi, encoding="utf-8") as f:
            ctx.fault_points = _extract_literal_dict_keys(
                ast.parse(f.read()), "FAULT_POINTS")
    if ctx.span_names is None and os.path.exists(tr):
        with open(tr, encoding="utf-8") as f:
            names = _extract_literal_dict_keys(ast.parse(f.read()),
                                               "SPAN_REGISTRY")
        # Prefix entries end in "::" (task::, submit::) or "_" (dynamic
        # bucket families like serve.ttft_<bucket>).
        ctx.span_prefixes = tuple(sorted(
            n for n in names if n.endswith("::") or n.endswith("_")))
        ctx.span_names = {n for n in names
                          if not (n.endswith("::") or n.endswith("_"))}
    if ctx.slo_objectives is None and os.path.exists(sl):
        with open(sl, encoding="utf-8") as f:
            ctx.slo_objectives = _extract_literal_dict_keys(
                ast.parse(f.read()), "SLO_OBJECTIVES")


# ------------------------------------------------------------------ checker

class Checker:
    name: str = ""
    description: str = ""

    def collect(self, module: SourceModule, ctx: AnalysisContext) -> None:
        """Pre-pass over every module before any ``check_module`` call —
        lets cross-module declarations (``# pairs_with:`` on a ``def``)
        reach call sites in other files.  Contributions go in
        ``ctx.scratch``; must be deterministic and idempotent per module."""

    def check_module(self, module: SourceModule,
                     ctx: AnalysisContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finalize(self, ctx: AnalysisContext) -> Iterator[Finding]:
        """Aggregate findings after every module was scanned (only called
        when ctx.full_package)."""
        return iter(())


# ------------------------------------------------------------------- driver

DEFAULT_EXCLUDE = ("*/__pycache__/*",)


def iter_python_files(paths: Sequence[str],
                      exclude: Sequence[str] = ()) -> Iterator[str]:
    patterns = tuple(exclude) + DEFAULT_EXCLUDE
    seen = set()

    def excluded(p: str) -> bool:
        q = p.replace(os.sep, "/")
        return any(fnmatch.fnmatch(q, pat) or fnmatch.fnmatch(
            os.path.basename(q), pat) for pat in patterns)

    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py") and not excluded(path) and path not in seen:
                seen.add(path)
                yield path
        else:
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    full = os.path.join(dirpath, fn)
                    if fn.endswith(".py") and not excluded(full) \
                            and full not in seen:
                        seen.add(full)
                        yield full


def parse_module(abspath: str, root: str) -> Optional[SourceModule]:
    rel = os.path.relpath(abspath, root)
    try:
        with open(abspath, encoding="utf-8") as f:
            text = f.read()
        return SourceModule(abspath, rel, text)
    except (SyntaxError, UnicodeDecodeError, OSError):
        return None


def analyze_source(text: str, checkers: Sequence[Checker],
                   ctx: Optional[AnalysisContext] = None,
                   path: str = "<fixture>.py") -> List[Finding]:
    """Analyze one source string — the fixture-test entry point."""
    ctx = ctx or AnalysisContext()
    module = SourceModule(path, path, text)
    out: List[Finding] = []
    for checker in checkers:
        checker.collect(module, ctx)
    for checker in checkers:
        for finding in checker.check_module(module, ctx):
            if checker.name in module.ignored_checks(finding.line):
                continue
            out.append(finding)
    return out


def run(paths: Sequence[str], checkers: Sequence[Checker],
        root: Optional[str] = None, exclude: Sequence[str] = (),
        ctx: Optional[AnalysisContext] = None) -> Tuple[List[Finding], dict]:
    """Run ``checkers`` over every .py file under ``paths``.

    Returns (findings, stats).  Inline ``# analysis: ignore[...]``
    suppressions are applied here; baseline suppression is the caller's
    job (scripts/analyze.py / baseline.py).
    """
    root = root or os.getcwd()
    ctx = ctx or AnalysisContext(root=root)
    t0 = time.monotonic()
    files = list(iter_python_files(paths, exclude))
    # Aggregate (cross-module) checks only make sense when the scan spans
    # the package: key off the fault-injection module being included.
    ctx.full_package = any(
        f.replace(os.sep, "/").endswith("_private/fault_injection.py")
        for f in files)
    package_dir = None
    for f in files:
        norm = f.replace(os.sep, "/")
        if norm.endswith("ray_tpu/_private/fault_injection.py"):
            package_dir = os.path.dirname(os.path.dirname(f))
            break
    if package_dir is None:
        # Fall back to a ray_tpu package next to the scan root (lets
        # `analyze.py scripts/` resolve registries too).
        candidate = os.path.join(root, "ray_tpu")
        if os.path.isdir(candidate):
            package_dir = candidate
    if package_dir is not None:
        load_registries(ctx, package_dir)

    # Two passes: collect (cross-module declarations such as def-site
    # ``# pairs_with:``) over every module first, then check.  Modules are
    # parsed once and kept — the package comfortably fits in memory and the
    # incremental cache (cache.py) depends on the same structure.
    findings: List[Finding] = []
    modules: List[SourceModule] = []
    for abspath in files:
        module = parse_module(abspath, root)
        if module is not None:
            modules.append(module)
    parsed = len(modules)
    for module in modules:
        for checker in checkers:
            checker.collect(module, ctx)
    for module in modules:
        for checker in checkers:
            for finding in checker.check_module(module, ctx):
                if checker.name in module.ignored_checks(finding.line):
                    continue
                findings.append(finding)
    if ctx.full_package:
        for checker in checkers:
            findings.extend(checker.finalize(ctx))
    stats = {"files": parsed, "seconds": time.monotonic() - t0,
             "checks": [c.name for c in checkers]}
    findings.sort(key=lambda f: (f.path, f.line, f.check))
    return findings, stats
