"""SARIF 2.1.0 serialisation of analyzer findings.

``scripts/analyze.py --format sarif`` emits one run with one rule per
checker, so editors and code-scanning UIs that speak SARIF can ingest
the analyzer without a custom adapter.  The stable finding key rides in
``partialFingerprints`` — the same identity the baseline uses."""

from __future__ import annotations

import json
from typing import List, Sequence

from . import core

SARIF_VERSION = "2.1.0"
_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
           "Schemata/sarif-schema-2.1.0.json")


def to_sarif(findings: List[core.Finding],
             checkers: Sequence[core.Checker],
             baselined_keys: Sequence[str] = ()) -> dict:
    rules = [{
        "id": c.name,
        "shortDescription": {"text": c.description or c.name},
    } for c in checkers]
    rule_index = {c.name: i for i, c in enumerate(checkers)}
    baselined = set(baselined_keys)
    results = []
    for f in findings:
        results.append({
            "ruleId": f.check,
            "ruleIndex": rule_index.get(f.check, -1),
            "level": "warning",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(f.line, 1)},
                },
                "logicalLocations": [{"fullyQualifiedName": f.symbol}],
            }],
            "partialFingerprints": {"stableKey/v1": f.key},
            "baselineState": "unchanged" if f.key in baselined else "new",
        })
    return {
        "$schema": _SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "ray_tpu-analysis",
                "informationUri":
                    "docs/static-analysis.md",
                "rules": rules,
            }},
            "results": results,
        }],
    }


def render_sarif(findings: List[core.Finding],
                 checkers: Sequence[core.Checker],
                 baselined_keys: Sequence[str] = ()) -> str:
    return json.dumps(to_sarif(findings, checkers, baselined_keys),
                      indent=2, sort_keys=True)
