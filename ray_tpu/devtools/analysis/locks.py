"""Lock-scope tracking shared by the lock-discipline, atomicity and
blocking-in-handler checkers.

A :class:`FunctionScan` walks one function body tracking which locks are
held at every expression:

* ``with self._lock:`` / ``with _POOL_LOCK:`` (every ``with`` item whose
  terminal name contains ``lock``) opens a new *region* — an integer id
  unique per acquisition, so the atomicity checker can tell two separate
  critical sections apart;
* ``self._lock.acquire()`` marks the rest of the enclosing block held,
  ``release()`` unmarks (the try/finally idiom resolves conservatively:
  statements after the ``try`` stay "held", which only ever under-reports).

Accesses are classified read vs write: plain ``Store``/``Del`` contexts,
stores through a subscript (``self._d[k] = v`` writes ``_d``), and calls
to known container mutators (``.append``/``.pop``/``.add``/...) all count
as writes; everything else is a read.  Nested ``def``/``class`` bodies are
scanned as separate functions with *no* inherited locks — a closure
created under a lock typically runs after it is released (callbacks), so
inheriting the scope would hide real races.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

#: method names that mutate their receiver container in place
MUTATORS = frozenset({
    "append", "appendleft", "add", "clear", "discard", "extend", "insert",
    "pop", "popleft", "popitem", "remove", "setdefault", "sort", "update",
    "__setitem__", "__delitem__",
})

LockToken = Tuple[str, str]  # ("self"|"global", lock name)


def _lock_token(expr: ast.expr) -> Optional[LockToken]:
    """("self", "_lock") for ``self._lock``, ("global", "_POOL_LOCK") for a
    bare name — only when the terminal name smells like a lock."""
    if isinstance(expr, ast.Attribute):
        if isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                and "lock" in expr.attr.lower():
            return ("self", expr.attr)
        return None
    if isinstance(expr, ast.Name) and "lock" in expr.id.lower():
        return ("global", expr.id)
    return None


@dataclass(frozen=True)
class Access:
    owner: str  # "self" | "global"
    name: str   # attribute / global name
    write: bool
    line: int
    #: lock token -> region id for every lock held at this access
    held: Tuple[Tuple[LockToken, int], ...]

    def holds(self, token: LockToken) -> bool:
        return any(t == token for t, _ in self.held)

    def region(self, token: LockToken) -> Optional[int]:
        for t, r in self.held:
            if t == token:
                return r
        return None


@dataclass(frozen=True)
class CallSite:
    node: ast.Call
    line: int
    held: Tuple[Tuple[LockToken, int], ...]

    def holds_any_lock(self) -> bool:
        return bool(self.held)


@dataclass
class FunctionScan:
    symbol: str               # "Class.method" or bare function name
    node: ast.AST
    is_async: bool
    is_init: bool
    entry_lock: Optional[str]  # requires_lock lock name (held at entry)
    accesses: List[Access] = field(default_factory=list)
    calls: List[CallSite] = field(default_factory=list)


#: entry-region id for requires_lock functions (held before any with-block)
ENTRY_REGION = 0


class _Walker:
    def __init__(self, scan: FunctionScan):
        self.scan = scan
        self._next_region = ENTRY_REGION + 1

    # ------------------------------------------------------------- blocks
    def walk_function(self) -> None:
        held: Dict[LockToken, int] = {}
        if self.scan.entry_lock:
            held[("self", self.scan.entry_lock)] = ENTRY_REGION
        self.walk_block(self.scan.node.body, held)

    def walk_block(self, stmts, held: Dict[LockToken, int]) -> None:
        held = dict(held)
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scopes are scanned separately, lock-free
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = dict(held)
                for item in stmt.items:
                    self.visit_expr(item.context_expr, held)
                    if item.optional_vars is not None:
                        self.visit_expr(item.optional_vars, held)
                    token = _lock_token(item.context_expr)
                    if token is not None:
                        inner[token] = self._next_region
                        self._next_region += 1
                self.walk_block(stmt.body, inner)
                continue
            token_toggle = self._acquire_release(stmt)
            if token_toggle is not None:
                token, acquired = token_toggle
                if acquired:
                    held[token] = self._next_region
                    self._next_region += 1
                else:
                    held.pop(token, None)
                continue
            self._visit_stmt_exprs(stmt, held)
            for attr in ("body", "orelse", "finalbody"):
                child = getattr(stmt, attr, None)
                if child:
                    self.walk_block(child, held)
            for handler in getattr(stmt, "handlers", ()) or ():
                self.walk_block(handler.body, held)

    @staticmethod
    def _acquire_release(stmt) -> Optional[Tuple[LockToken, bool]]:
        if not (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)):
            return None
        func = stmt.value.func
        if isinstance(func, ast.Attribute) and func.attr in ("acquire", "release"):
            token = _lock_token(func.value)
            if token is not None:
                return token, func.attr == "acquire"
        return None

    def _visit_stmt_exprs(self, stmt, held) -> None:
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self.visit_expr(child, held)

    # -------------------------------------------------------- expressions
    def _emit(self, owner: str, name: str, write: bool, node, held) -> None:
        self.scan.accesses.append(Access(
            owner=owner, name=name, write=write, line=node.lineno,
            held=tuple(sorted(held.items()))))

    def visit_expr(self, node: ast.expr, held: Dict[LockToken, int],
                   write: bool = False) -> None:
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and node.value.id == "self":
                self._emit("self", node.attr,
                           write or isinstance(node.ctx, (ast.Store, ast.Del)),
                           node, held)
                return
            self.visit_expr(node.value, held)
            return
        if isinstance(node, ast.Subscript):
            container_write = isinstance(node.ctx, (ast.Store, ast.Del))
            self.visit_expr(node.value, held, write=container_write)
            self.visit_expr(node.slice, held)
            return
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr in MUTATORS:
                self.visit_expr(func.value, held, write=True)
            else:
                self.visit_expr(func, held)
            for arg in node.args:
                self.visit_expr(arg, held)
            for kw in node.keywords:
                self.visit_expr(kw.value, held)
            self.scan.calls.append(CallSite(
                node=node, line=node.lineno, held=tuple(sorted(held.items()))))
            return
        if isinstance(node, ast.Name):
            self._emit("global", node.id,
                       write or isinstance(node.ctx, (ast.Store, ast.Del)),
                       node, held)
            return
        if isinstance(node, ast.Lambda):
            self.visit_expr(node.body, held)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.visit_expr(child, held)
            elif isinstance(child, ast.comprehension):
                self.visit_expr(child.target, held)
                self.visit_expr(child.iter, held)
                for cond in child.ifs:
                    self.visit_expr(cond, held)


def iter_function_scans(tree: ast.AST, requires_lock=None
                        ) -> Iterator[FunctionScan]:
    """Scan every function in a module (methods get "Class.method" symbols,
    nested defs "outer.inner").  ``requires_lock``: {class -> {method ->
    lock}} from core.collect_guards — those methods start with the lock
    held (region ENTRY_REGION)."""
    requires_lock = requires_lock or {}

    def scan_one(fn, symbol: str, cls: Optional[str]) -> Iterator[FunctionScan]:
        entry = None
        if cls is not None:
            entry = requires_lock.get(cls, {}).get(fn.name)
        scan = FunctionScan(
            symbol=symbol, node=fn,
            is_async=isinstance(fn, ast.AsyncFunctionDef),
            is_init=fn.name in ("__init__", "__new__"),
            entry_lock=entry)
        _Walker(scan).walk_function()
        yield scan

    def walk_body(body, prefix: str, cls: Optional[str]):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                symbol = f"{prefix}{stmt.name}" if prefix else stmt.name
                yield from scan_one(stmt, symbol, cls)
                # nested functions inside this one
                yield from walk_body(stmt.body, symbol + ".", None)
            elif isinstance(stmt, ast.ClassDef):
                yield from walk_body(stmt.body, stmt.name + ".", stmt.name)
            else:
                # functions defined under if/try at module level
                for attr in ("body", "orelse", "finalbody"):
                    child = getattr(stmt, attr, None)
                    if child:
                        yield from walk_body(child, prefix, cls)
                for handler in getattr(stmt, "handlers", ()) or ():
                    yield from walk_body(handler.body, prefix, cls)

    yield from walk_body(tree.body, "", None)
