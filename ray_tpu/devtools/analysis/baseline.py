"""Baseline handling — accepted findings that don't fail the build.

The baseline file (``analysis_baseline.json``) is a JSON list of
entries::

    [{"key": "lock-discipline:ray_tpu/x.py:Cls.meth:_attr",
      "reason": "double-checked locking; second read is under the lock"},
     ...]

Keys are :attr:`core.Finding.key` values — ``check:path:symbol:detail``
with **no line numbers**, so a baseline survives unrelated edits to the
file.  Every entry must carry a non-empty ``reason``: the baseline is a
list of *explained* exceptions, not a dumping ground.  Entries whose key
no longer matches any finding are *stale* and reported so the file can't
silently rot.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, List, Tuple

from ray_tpu.devtools.analysis import core


class BaselineError(ValueError):
    """Malformed baseline file (bad JSON, missing key/reason)."""


@dataclass(frozen=True)
class BaselineEntry:
    key: str
    reason: str


def load(path: str) -> List[BaselineEntry]:
    with open(path, "r", encoding="utf-8") as fh:
        try:
            raw = json.load(fh)
        except json.JSONDecodeError as exc:
            raise BaselineError(f"{path}: invalid JSON: {exc}") from exc
    if not isinstance(raw, list):
        raise BaselineError(f"{path}: expected a JSON list of entries")
    entries = []
    for i, item in enumerate(raw):
        if not isinstance(item, dict) or "key" not in item:
            raise BaselineError(f"{path}: entry {i} missing 'key'")
        reason = str(item.get("reason", "")).strip()
        if not reason:
            raise BaselineError(
                f"{path}: entry {i} ({item['key']}) has no reason — every "
                f"baselined finding must be justified")
        entries.append(BaselineEntry(key=str(item["key"]), reason=reason))
    return entries


def apply(findings: List[core.Finding], entries: List[BaselineEntry]
          ) -> Tuple[List[core.Finding], List[core.Finding],
                     List[BaselineEntry]]:
    """Split findings into (new, baselined) and return stale entries."""
    by_key: Dict[str, BaselineEntry] = {e.key: e for e in entries}
    new: List[core.Finding] = []
    baselined: List[core.Finding] = []
    matched = set()
    for f in findings:
        if f.key in by_key:
            baselined.append(f)
            matched.add(f.key)
        else:
            new.append(f)
    stale = [e for e in entries if e.key not in matched]
    return new, baselined, stale


def write(path: str, findings: List[core.Finding],
          reason: str = "TODO: justify or fix") -> None:
    """Write a baseline covering ``findings`` (dev convenience; each entry
    still needs a human-written reason before it should be committed)."""
    seen = set()
    entries = []
    for f in findings:
        if f.key in seen:
            continue
        seen.add(f.key)
        entries.append({"key": f.key, "reason": reason,
                        "message": f.message})
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(entries, fh, indent=2, sort_keys=False)
        fh.write("\n")
