"""Checker 3 — blocking-in-handler.

Two disciplines, both learned the hard way:

* **No blocking while holding a lock** — a ``time.sleep`` / ``ray_tpu.get``
  under ``with self._lock`` serializes every other thread through the
  sleeper (the reason ``FaultInjector.fires()`` sleeps *outside* its lock
  and ``ReplicaHolder`` materializes payloads before touching its map).
* **No sync blocking inside ``async def``** — serve replica handlers run
  as asyncio tasks on the replica's event loop; a blocking call there
  stalls every concurrent request on that replica.  Sync user code must
  ride ``serve/_sync.run_in_executor`` (which this checker deliberately
  does not flag: handing a *callable* to an executor is the fix, calling
  it inline is the bug).

``# blocking_ok: <reason>`` on the call line suppresses intentional cases
(e.g. a bounded get that is the whole point of the method).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ray_tpu.devtools.analysis import core, locks

#: dotted call names that block the calling thread
BLOCKING_CALLS = frozenset({
    "time.sleep",
    "ray_tpu.get", "ray_tpu.wait",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
    "socket.create_connection",
    "requests.get", "requests.post", "requests.request",
    "urllib.request.urlopen",
})


def _dotted(func: ast.expr) -> Optional[str]:
    parts = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class BlockingChecker(core.Checker):
    name = "blocking-in-handler"
    description = ("blocking call while holding a lock or inside an "
                   "async handler")

    def check_module(self, module: core.SourceModule,
                     ctx: core.AnalysisContext) -> Iterator[core.Finding]:
        guards = core.collect_guards(module)
        for scan in locks.iter_function_scans(module.tree,
                                              guards.requires_lock):
            for call in scan.calls:
                name = _dotted(call.node.func)
                if name is None or name not in BLOCKING_CALLS:
                    continue
                if module.marker_near(call.line, "blocking_ok"):
                    continue
                if call.holds_any_lock():
                    held = ", ".join(
                        (f"self.{n}" if owner == "self" else n)
                        for (owner, n), _ in call.held)
                    yield core.Finding(
                        check=self.name, path=module.path, line=call.line,
                        symbol=scan.symbol, detail=f"lock:{name}",
                        message=(f"{scan.symbol} calls blocking {name}() "
                                 f"while holding {held} — every other "
                                 f"thread on that lock stalls behind it"))
                elif scan.is_async:
                    yield core.Finding(
                        check=self.name, path=module.path, line=call.line,
                        symbol=scan.symbol, detail=f"async:{name}",
                        message=(f"async {scan.symbol} calls blocking "
                                 f"{name}() inline — it stalls the event "
                                 f"loop; dispatch via serve/_sync."
                                 f"run_in_executor or await an async "
                                 f"equivalent"))
