"""Checker 4 — registry consistency.

Every stringly-typed name the framework consults at runtime must appear
in its declared registry, so a typo'd fault point silently never fires,
a misprefixed metric, or an unregistered span name breaks CI instead of
an operator's dashboard:

* fault points — ``fault_injection.check("x")`` / ``injector.fires("x")``
  call sites must name a key of ``fault_injection.FAULT_POINTS`` (and,
  scanning the whole package, every registered point must be consulted
  somewhere: a dead registry row is a lie about coverage);
* span names — ``tracing.span("x")`` / ``record_span[_batch]("x")`` must
  name a key of ``tracing.SPAN_REGISTRY``; dynamic f-string names must
  start with a registered prefix entry (``...::`` or trailing-``_``
  families like ``serve.ttft_``);
* SLO objectives — ``SLOObjective("x", ...)`` call sites must name a key
  of ``serve.slo.SLO_OBJECTIVES``, and every registered objective must be
  wired into the watchdog's evaluation path (an objective nobody can
  evaluate is a lie about coverage);
* metric declarations — ``Counter/Gauge/Histogram("name", "help")`` with
  a literal name must be ``ray_tpu_``/``serve_`` prefixed, carry help
  text, and be declared at exactly one source site (the static half of
  the old ``scripts/check_metrics.py``).

The *runtime* half of the metrics lint (walks the live process registry,
catching dynamically-built declarations the AST cannot see) lives here
too as :func:`collect_runtime_metric_violations`; ``scripts/
check_metrics.py`` is now a thin shim over it.
"""

from __future__ import annotations

import ast
import os
from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

from ray_tpu.devtools.analysis import core

METRIC_CTORS = ("Counter", "Gauge", "Histogram")
#: the metric library itself declares no metrics; skip it and the analyzer
_METRIC_EXEMPT = ("ray_tpu/util/metrics.py", "ray_tpu/devtools/")
_FAULT_RECEIVERS = ("fault_injection", "injector", "inj")
_SPAN_FUNCS = ("span", "record_span", "record_span_batch")


def _first_arg_str(call: ast.Call) -> Optional[str]:
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    return None


def _fstring_prefix(call: ast.Call) -> Optional[str]:
    """Literal head of an f-string first arg ('submit::' of
    f"submit::{name}"), or None."""
    if not call.args or not isinstance(call.args[0], ast.JoinedStr):
        return None
    values = call.args[0].values
    if values and isinstance(values[0], ast.Constant) \
            and isinstance(values[0].value, str):
        return values[0].value
    return None


class RegistryConsistencyChecker(core.Checker):
    name = "registry-consistency"
    description = ("fault points / span names / metric declarations that "
                   "don't match their registries")

    # ----------------------------------------------------------- per-module
    def check_module(self, module: core.SourceModule,
                     ctx: core.AnalysisContext) -> Iterator[core.Finding]:
        consulted: Set[str] = ctx.scratch.setdefault(
            "fault_points_consulted", set())
        spans_used: Set[str] = ctx.scratch.setdefault("spans_used", set())
        metric_sites: Dict[str, List[Tuple[str, int]]] = ctx.scratch.setdefault(
            "metric_sites", {})
        in_fault_module = module.path.endswith("fault_injection.py")
        metric_exempt = any(module.path.startswith(p) or module.path == p
                            for p in _METRIC_EXEMPT) \
            or any(s in module.path for s in _METRIC_EXEMPT)

        # SLO objectives "in use": ctor call sites anywhere, plus the
        # watchdog's own evaluation wiring in serve/slo.py (dict keys /
        # comparisons naming an objective beyond its registry declaration
        # — e.g. _LATENCY_SERIES keys, the "availability" branch).
        if module.path.endswith("serve/slo.py") and ctx.slo_objectives:
            used: Set[str] = ctx.scratch.setdefault(
                "slo_objectives_used", set())
            decl_counts: Dict[str, int] = {}
            for node in ast.walk(module.tree):
                for target in core._assign_names(node):
                    if isinstance(target, ast.Name) \
                            and target.id == "SLO_OBJECTIVES":
                        value = getattr(node, "value", None)
                        if isinstance(value, ast.Dict):
                            for k in value.keys:
                                if isinstance(k, ast.Constant) \
                                        and isinstance(k.value, str):
                                    decl_counts[k.value] = \
                                        decl_counts.get(k.value, 0) + 1
            totals: Dict[str, int] = {}
            for node in ast.walk(module.tree):
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str) \
                        and node.value in ctx.slo_objectives:
                    totals[node.value] = totals.get(node.value, 0) + 1
            for name in ctx.slo_objectives:
                if totals.get(name, 0) > decl_counts.get(name, 0):
                    used.add(name)

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            # --- fault points ------------------------------------------
            if isinstance(func, ast.Attribute) and func.attr in ("check",
                                                                 "fires"):
                recv = func.value
                recv_name = recv.id if isinstance(recv, ast.Name) else None
                plausible = (func.attr == "fires"
                             or recv_name in _FAULT_RECEIVERS)
                point = _first_arg_str(node)
                if plausible and point is not None and not in_fault_module:
                    consulted.add(point)
                    if ctx.fault_points is not None \
                            and point not in ctx.fault_points:
                        yield core.Finding(
                            check=self.name, path=module.path,
                            line=node.lineno, symbol="<fault-point>",
                            detail=f"fault:{point}",
                            message=(f"fault point '{point}' is not "
                                     f"declared in fault_injection."
                                     f"FAULT_POINTS"))
            # --- spans --------------------------------------------------
            # --- SLO objectives ----------------------------------------
            ctor_name = None
            if isinstance(func, ast.Name):
                ctor_name = func.id
            elif isinstance(func, ast.Attribute):
                ctor_name = func.attr
            if ctor_name == "SLOObjective" \
                    and ctx.slo_objectives is not None:
                obj_name = _first_arg_str(node)
                if obj_name is None:
                    for kw in node.keywords:
                        if kw.arg == "name" \
                                and isinstance(kw.value, ast.Constant) \
                                and isinstance(kw.value.value, str):
                            obj_name = kw.value.value
                if obj_name is not None:
                    ctx.scratch.setdefault("slo_objectives_used",
                                           set()).add(obj_name)
                    if obj_name not in ctx.slo_objectives:
                        yield core.Finding(
                            check=self.name, path=module.path,
                            line=node.lineno, symbol="<slo-objective>",
                            detail=f"slo:{obj_name}",
                            message=(f"SLO objective '{obj_name}' is not "
                                     f"declared in serve.slo."
                                     f"SLO_OBJECTIVES"))
            span_func = None
            if isinstance(func, ast.Attribute) and func.attr in _SPAN_FUNCS:
                span_func = func.attr
            elif isinstance(func, ast.Name) and func.id in _SPAN_FUNCS:
                span_func = func.id
            if span_func is not None and ctx.span_names is not None:
                literal = _first_arg_str(node)
                prefix = _fstring_prefix(node)
                if literal is not None:
                    spans_used.add(literal)
                    if literal not in ctx.span_names:
                        yield core.Finding(
                            check=self.name, path=module.path,
                            line=node.lineno, symbol="<span>",
                            detail=f"span:{literal}",
                            message=(f"span name '{literal}' is not "
                                     f"declared in tracing.SPAN_REGISTRY"))
                elif prefix is not None:
                    prefixes = ctx.span_prefixes or ()
                    match = next((p for p in prefixes
                                  if prefix.startswith(p)), None)
                    if match is not None:
                        spans_used.add(match)
                    else:
                        yield core.Finding(
                            check=self.name, path=module.path,
                            line=node.lineno, symbol="<span>",
                            detail=f"span:{prefix}",
                            message=(f"dynamic span name f'{prefix}...' "
                                     f"matches no prefix entry ('::' or "
                                     f"trailing '_') in "
                                     f"tracing.SPAN_REGISTRY"))
            # --- metric declarations -----------------------------------
            ctor = None
            if isinstance(func, ast.Name) and func.id in METRIC_CTORS:
                ctor = func.id
            elif isinstance(func, ast.Attribute) and func.attr in METRIC_CTORS:
                ctor = func.attr
            if ctor is not None and not metric_exempt:
                mname = _first_arg_str(node)
                if mname is None:
                    continue
                metric_sites.setdefault(mname, []).append(
                    (module.path, node.lineno))
                if not mname.startswith(ctx.metric_prefixes):
                    yield core.Finding(
                        check=self.name, path=module.path, line=node.lineno,
                        symbol="<metric>", detail=f"metric-prefix:{mname}",
                        message=(f"metric '{mname}' is not prefixed with "
                                 f"one of {ctx.metric_prefixes}"))
                help_text = None
                if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
                    help_text = node.args[1].value
                if len(node.args) > 1 and isinstance(node.args[1], ast.Constant) \
                        and (not isinstance(help_text, str)
                             or not help_text.strip()):
                    yield core.Finding(
                        check=self.name, path=module.path, line=node.lineno,
                        symbol="<metric>", detail=f"metric-help:{mname}",
                        message=f"metric '{mname}' has blank help text")

    # ------------------------------------------------------------ aggregate
    def finalize(self, ctx: core.AnalysisContext) -> Iterator[core.Finding]:
        consulted = ctx.scratch.get("fault_points_consulted", set())
        if ctx.fault_points:
            for point in sorted(ctx.fault_points - consulted):
                yield core.Finding(
                    check=self.name,
                    path="ray_tpu/_private/fault_injection.py", line=1,
                    symbol="<fault-point>", detail=f"fault-unused:{point}",
                    message=(f"FAULT_POINTS entry '{point}' is never "
                             f"consulted by any check()/fires() call site"))
        spans_used = ctx.scratch.get("spans_used", set())
        if ctx.span_names:
            declared = set(ctx.span_names) | set(ctx.span_prefixes or ())
            for span in sorted(declared - spans_used):
                yield core.Finding(
                    check=self.name, path="ray_tpu/util/tracing.py", line=1,
                    symbol="<span>", detail=f"span-unused:{span}",
                    message=(f"SPAN_REGISTRY entry '{span}' is never opened "
                             f"by any span()/record_span call site"))
        slo_used = ctx.scratch.get("slo_objectives_used", set())
        if ctx.slo_objectives:
            for name in sorted(ctx.slo_objectives - slo_used):
                yield core.Finding(
                    check=self.name, path="ray_tpu/serve/slo.py", line=1,
                    symbol="<slo-objective>", detail=f"slo-unused:{name}",
                    message=(f"SLO_OBJECTIVES entry '{name}' is neither "
                             f"constructed at any SLOObjective call site "
                             f"nor wired into the watchdog evaluation"))
        for mname, sites in sorted(
                ctx.scratch.get("metric_sites", {}).items()):
            distinct = sorted(set(sites))
            if len(distinct) > 1:
                yield core.Finding(
                    check=self.name, path=distinct[0][0], line=distinct[0][1],
                    symbol="<metric>", detail=f"metric-dup:{mname}",
                    message=(f"metric '{mname}' declared at "
                             f"{len(distinct)} sites: "
                             + ", ".join(f"{p}:{l}" for p, l in distinct)))


# --------------------------------------------------------------- runtime lint
#: Every module that declares internal metrics at import time (module-level
#: Counter/Gauge/Histogram instances).  Keep in sync with new declarations —
#: a metric declared in a module not imported here is invisible to the
#: runtime lint (the static pass above sees it regardless).
METRIC_MODULES = (
    "ray_tpu._private.metrics_agent",
    "ray_tpu.serve.metrics",
    "ray_tpu.serve.router",
    "ray_tpu.serve.compiled_router",
    "ray_tpu.serve.batching",
    "ray_tpu.serve.continuous",
    "ray_tpu.serve.multiplex",
    "ray_tpu.serve.llm.metrics",
    "ray_tpu.serve.autoscaling",
    "ray_tpu.serve.deployment_state",
    "ray_tpu.checkpoint.metrics",
    "ray_tpu.train.metrics",
    "ray_tpu.data.ingest.metrics",
    "ray_tpu.util.flight_recorder",
    "ray_tpu.util.watchdog",
    "ray_tpu.util.device_telemetry",
    "ray_tpu.autoscaler.metrics",
)

ALLOWED_PREFIXES = ("ray_tpu_", "serve_")

#: Windowed accessor (dotted path under ray_tpu.serve) -> the registry
#: metric whose series it reads from the TimeSeriesAggregator.  The
#: runtime lint verifies the accessor exists AND its series matches a
#: declared metric name, so renaming a metric cannot silently strand an
#: accessor on a dead series (the SLO watchdog and the ROADMAP item 1
#: autoscaler consume these).
ACCESSOR_SERIES = {
    "metrics.request_rate": "serve_requests_total",
    "metrics.ttft_p99": "ray_tpu_llm_ttft_seconds",
    "metrics.inter_token_p99": "ray_tpu_llm_inter_token_seconds",
    "metrics.kv_utilization": "ray_tpu_llm_kv_blocks_in_use",
    "metrics.batch_occupancy": "ray_tpu_llm_batch_occupancy",
    "metrics.goodput_tokens_per_s": "ray_tpu_llm_decode_tokens_total",
    "metrics.recompute_waste_tokens_per_s":
        "ray_tpu_llm_recompute_tokens_total",
    "metrics.acceptance_rate": "ray_tpu_llm_spec_accepted_tokens_total",
    "metrics.prefix_hit_rate": "ray_tpu_llm_prefix_hit_tokens_total",
    "device.transfer_bw": "ray_tpu_device_transfer_bytes_total",
}


def _import_metric_modules() -> None:
    import importlib

    for mod in METRIC_MODULES:
        importlib.import_module(mod)
    # The runtime gauges are created lazily on first scrape; force them so
    # their names/help get linted too.
    from ray_tpu._private import metrics_agent

    metrics_agent._internal_gauges()


def collect_runtime_metric_violations() -> List[str]:
    """Walk the live process metric registry (catches declarations the AST
    pass cannot see: names built at runtime, metrics created in loops) and
    return violation strings — the old ``scripts/check_metrics.py`` body."""
    _import_metric_modules()

    import ray_tpu
    from ray_tpu.util import metrics as um

    pkg_root = os.path.realpath(os.path.dirname(ray_tpu.__file__))
    violations: List[str] = []
    # name -> {declaration file:line} for duplicate detection.  Multiple
    # *instances* from one site (e.g. a metric built per replica in a loop)
    # are legal; the same name from two different lines is a conflict.
    sites_by_name: Dict[str, set] = {}

    for group in um.registry().collect():
        for metric in group:
            declared_at = getattr(metric, "_declared_at", "<unknown>")
            decl_file = declared_at.rsplit(":", 1)[0]
            if not os.path.realpath(decl_file).startswith(pkg_root + os.sep):
                continue  # user/test metric sharing the process registry
            sites_by_name.setdefault(metric.name, set()).add(declared_at)
            if not (metric._description or "").strip():
                violations.append(
                    f"{metric.name}: missing help text ({declared_at})")
            if not metric.name.startswith(ALLOWED_PREFIXES):
                violations.append(
                    f"{metric.name}: internal metric not prefixed with one "
                    f"of {ALLOWED_PREFIXES} ({declared_at})")

    for name, sites in sorted(sites_by_name.items()):
        if len(sites) > 1:
            violations.append(
                f"{name}: declared at {len(sites)} sites: "
                + ", ".join(sorted(sites)))

    # Windowed-accessor wiring: each ACCESSOR_SERIES entry must resolve to
    # a callable under ray_tpu.serve and read a series that a declared
    # metric actually feeds (renames can't strand an accessor silently).
    from ray_tpu import serve as _serve

    for accessor, series in sorted(ACCESSOR_SERIES.items()):
        obj: Any = _serve
        for part in accessor.split("."):
            obj = getattr(obj, part, None)
            if obj is None:
                break
        if not callable(obj):
            violations.append(
                f"serve.{accessor}: accessor registered in ACCESSOR_SERIES "
                f"does not resolve to a callable")
        if series not in sites_by_name:
            violations.append(
                f"serve.{accessor}: reads series {series!r} which matches "
                f"no declared in-package metric")
    return violations
