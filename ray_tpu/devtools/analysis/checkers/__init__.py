"""Checker plugin registry.

A checker is any subclass of :class:`ray_tpu.devtools.analysis.core.
Checker` registered here.  ``scripts/analyze.py --list-checks`` prints
this table; ``--only``/``--skip`` select by ``name``.
"""

from __future__ import annotations

from typing import Dict, List, Type

from ray_tpu.devtools.analysis import core
from ray_tpu.devtools.analysis.checkers.atomicity import AtomicityChecker
from ray_tpu.devtools.analysis.checkers.blocking import BlockingChecker
from ray_tpu.devtools.analysis.checkers.lock_discipline import (
    LockDisciplineChecker,
)
from ray_tpu.devtools.analysis.checkers.lockstep import LockstepChecker
from ray_tpu.devtools.analysis.checkers.paired_effect import (
    PairedEffectChecker,
)
from ray_tpu.devtools.analysis.checkers.registry_consistency import (
    RegistryConsistencyChecker,
)
from ray_tpu.devtools.analysis.checkers.task_lifecycle import (
    TaskLifecycleChecker,
)
from ray_tpu.devtools.analysis.checkers.thread_ownership import (
    ThreadOwnershipChecker,
)

ALL_CHECKERS: List[Type[core.Checker]] = [
    LockDisciplineChecker,
    AtomicityChecker,
    BlockingChecker,
    RegistryConsistencyChecker,
    LockstepChecker,
    PairedEffectChecker,
    TaskLifecycleChecker,
    ThreadOwnershipChecker,
]

CHECKERS_BY_NAME: Dict[str, Type[core.Checker]] = {
    c.name: c for c in ALL_CHECKERS
}


def make_checkers(only=None, skip=None) -> List[core.Checker]:
    """Instantiate the selected checkers (all by default)."""
    selected = []
    for cls in ALL_CHECKERS:
        if only and cls.name not in only:
            continue
        if skip and cls.name in skip:
            continue
        selected.append(cls())
    return selected


__all__ = [
    "ALL_CHECKERS", "CHECKERS_BY_NAME", "make_checkers",
    "LockDisciplineChecker", "AtomicityChecker", "BlockingChecker",
    "RegistryConsistencyChecker", "LockstepChecker",
    "PairedEffectChecker", "TaskLifecycleChecker", "ThreadOwnershipChecker",
]
