"""Paired-effect leak detection (flow-sensitive, cfg.py-based).

The shape behind the worst review bugs of PRs 7/8/11: a *forward* effect
(slot acquired, inflight counter bumped, blocks allocated, sample
claimed) executes, and some exit path leaves the function without the
matching *reversal*.  The checker classifies call sites into
forward/reverse events per receiver and asks :func:`cfg.function_exits`
whether any explicit exit still has a pending forward effect.

Two strictness tiers:

* **Built-in pairs** (table below) are heuristics, so they use a lenient
  rule: a function is only flagged when at least one *normal* exit path
  (outside any except handler) does perform the reversal — proof the
  author intends same-function pairing — while another path leaks.
  Functions that never reverse on a normal path are treated as ownership
  transfer (``submit()`` hands its slot to the drain loop) and skipped;
  a reversal only inside an ``except`` handler is undo-on-error, not
  same-function pairing.
* **Declared pairs** are contracts and checked strictly on every path:
  ``# pairs_with: <reverse>`` on a ``def`` line binds every call of that
  method; on a call line it binds that site only.  For an annotated call
  assigned to a plain name (``table = BlockTable(alloc)``), the reversal
  may be a method on the assignment target (``table.release()``).

``finally`` and ``with`` reversal cover all paths (see cfg.py); suppress
an individual finding with ``# analysis: ignore[paired-effect] reason``
on the forward-call line.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterator, List, Optional, Tuple

from .. import cfg
from ..core import AnalysisContext, Checker, Finding, SourceModule

#: forward method name -> acceptable reversal names (lenient tier)
BUILTIN_PAIRS: Dict[str, FrozenSet[str]] = {
    "acquire_slot": frozenset({"release_slot"}),
    "on_request_sent": frozenset({"on_request_done"}),
    "allocate": frozenset({"free"}),
    "reserve": frozenset({"release"}),
    "claim": frozenset({"seal", "seal_all", "rollback", "retag"}),
    "track": frozenset({"untrack"}),
    "begin": frozenset({"end"}),
    "open": frozenset({"close"}),
    # Gauge-style counters: only paired when the same receiver is also
    # .dec()ed somewhere in the function (Counter.inc is monotonic and
    # must never be "reversed").
    "inc": frozenset({"dec"}),
}

_DECLARED_KEY = "paired-effect:declared"


def _call_name_receiver(call: ast.Call) -> Tuple[str, Optional[str]]:
    func = call.func
    if isinstance(func, ast.Attribute):
        return func.attr, ast.unparse(func.value)
    if isinstance(func, ast.Name):
        return func.id, None
    return "", None


def _parse_reverses(value: str) -> FrozenSet[str]:
    return frozenset(n.strip() for n in value.split(",") if n.strip())


def _assign_targets(fn) -> Dict[int, str]:
    """id(call) -> plain-name assignment target, for ``x = Call(...)``."""
    out: Dict[int, str] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            value = node.value
            if isinstance(value, ast.Await):
                value = value.value
            if isinstance(value, ast.Call):
                out[id(value)] = node.targets[0].id
    return out


class _Token:
    __slots__ = ("key", "forward", "match_key", "reverses", "strict", "line")

    def __init__(self, forward: str, match_key: str,
                 reverses: FrozenSet[str], strict: bool, line: int):
        self.key = (forward, match_key)
        self.forward = forward
        self.match_key = match_key
        self.reverses = reverses
        self.strict = strict
        self.line = line


class PairedEffectChecker(Checker):
    name = "paired-effect"
    description = ("forward effect (acquire/allocate/claim/...) with no "
                   "reversal dominating every exit path")

    # ------------------------------------------------------------ collect
    def collect(self, module: SourceModule, ctx: AnalysisContext) -> None:
        declared = ctx.scratch.setdefault(_DECLARED_KEY, {})
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                value = module.marker_near(node.lineno, "pairs_with")
                if value:
                    declared[node.name] = _parse_reverses(value)

    # ------------------------------------------------------------- checks
    def check_module(self, module: SourceModule,
                     ctx: AnalysisContext) -> Iterator[Finding]:
        declared: Dict[str, FrozenSet[str]] = ctx.scratch.get(
            _DECLARED_KEY, {})
        for symbol, fn, _cls in cfg.iter_functions(module.tree):
            yield from self._check_function(module, symbol, fn, declared)

    def _check_function(self, module: SourceModule, symbol: str, fn,
                        declared) -> Iterator[Finding]:
        calls = list(cfg.calls_in_function(fn))
        if not calls:
            return
        targets = None
        tokens: Dict[Tuple[str, str], _Token] = {}
        events: cfg.Events = {}
        # Pass 1: forward effects establish tokens.
        for call in calls:
            fname, receiver = _call_name_receiver(call)
            if not fname:
                continue
            # Exact-line only: ``marker_near`` would misread a def-line
            # marker as a site obligation for the first body statement.
            site_value = module.marker(call.lineno, "pairs_with")
            if site_value:
                reverses, strict = _parse_reverses(site_value), True
            elif fname in declared:
                reverses, strict = declared[fname], True
            elif fname in BUILTIN_PAIRS and receiver is not None:
                reverses, strict = BUILTIN_PAIRS[fname], False
                if fname == "inc" and not any(
                        _call_name_receiver(c) == ("dec", receiver)
                        for c in calls):
                    continue
            else:
                continue
            match_key = receiver
            if match_key is None:
                if targets is None:
                    targets = _assign_targets(fn)
                match_key = targets.get(id(call))
                if match_key is None:
                    continue  # no receiver and no named result to pair on
            token = tokens.get((fname, match_key))
            if token is None:
                token = _Token(fname, match_key, reverses, strict,
                               call.lineno)
                tokens[token.key] = token
            else:
                token.reverses = token.reverses | reverses
                token.strict = token.strict or strict
            events.setdefault(id(call), []).append((token.key, +1))
        if not tokens:
            return
        # Pass 2: reversals matched against established tokens.
        for call in calls:
            fname, receiver = _call_name_receiver(call)
            if not fname or receiver is None:
                continue
            for token in tokens.values():
                if fname in token.reverses and receiver == token.match_key:
                    events.setdefault(id(call), []).append((token.key, -1))
        exits = cfg.function_exits(fn, events)
        for token in tokens.values():
            leaks = [e for e in exits if e.pending(token.key) > 0]
            if not leaks:
                continue
            if not token.strict and not any(
                    not e.in_handler and e.saw_normal_reverse(token.key)
                    and e.pending(token.key) == 0 for e in exits):
                continue  # ownership transfer / undo-on-error idiom
            worst = min(leaks, key=lambda e: e.line)
            reverses = "/".join(sorted(token.reverses))
            yield Finding(
                check=self.name, path=module.path, line=token.line,
                symbol=symbol,
                message=(f"'{token.match_key}.{token.forward}' has no "
                         f"{reverses} on the {worst.kind} path at line "
                         f"{worst.line} ({len(leaks)} of {len(exits)} exit "
                         f"paths leak)"),
                detail=f"{token.forward}:{token.match_key}")
