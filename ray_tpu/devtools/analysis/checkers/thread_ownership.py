"""Cross-thread ownership: ``# owned_by_thread:`` attribute annotations.

The PR 8-review ``_ShardTracker`` shape: state written by a spawned pump
thread and read (or worse, mutated) from the consumer thread with no
lock.  ``# owned_by_thread: <owner>`` on an attribute assignment declares
which thread owns the attribute:

* When ``<owner>`` names a method of the class, that method must actually
  be spawned as a thread entry (``threading.Thread(target=self.<owner>)``
  — detected by ``core.collect_thread_targets``; a stale annotation is
  itself a finding).  The owner set is the entry method plus the private
  helpers reachable from it through ``self.*()`` calls; any access to the
  attribute from outside that set, without a lock held, is flagged.
* When ``<owner>`` is a free-form label ("worker thread", "event loop"),
  ownership is enforced externally; the checker only flags accesses from
  methods this class *does* spawn as thread entries — those provably run
  on a different thread.

``__init__`` is exempt (construction happens before any thread exists),
and an access under any held lock (``with self._lock:`` — reuse of the
locks.py scan) is always allowed.  Fully lock-guarded state should use
``guarded_by:`` instead.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Set

from ..core import (AnalysisContext, Checker, Finding, SourceModule,
                    _assign_names, collect_guards, collect_thread_targets)
from ..locks import iter_function_scans


def _owned_attrs(module: SourceModule, cls: ast.ClassDef) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in ast.walk(cls):
        for target in _assign_names(node):
            if (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                owner = module.marker(node.lineno, "owned_by_thread")
                if owner:
                    out[target.attr] = owner
    return out


def _self_call_graph(cls: ast.ClassDef) -> Dict[str, Set[str]]:
    graph: Dict[str, Set[str]] = {}
    for fn in cls.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        callees: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "self":
                callees.add(node.func.attr)
        graph[fn.name] = callees
    return graph


def _owner_closure(entry: str, graph: Dict[str, Set[str]]) -> Set[str]:
    """Entry method plus private helpers transitively reachable from it —
    the methods assumed to run on the owner thread."""
    closure, frontier = {entry}, [entry]
    while frontier:
        for callee in graph.get(frontier.pop(), ()):
            if callee in graph and callee.startswith("_") \
                    and callee not in closure:
                closure.add(callee)
                frontier.append(callee)
    return closure


class ThreadOwnershipChecker(Checker):
    name = "thread-ownership"
    description = ("# owned_by_thread: attribute accessed from a method "
                   "running on a different thread without a lock")

    def check_module(self, module: SourceModule,
                     ctx: AnalysisContext) -> Iterator[Finding]:
        spawned = collect_thread_targets(module)
        owned_by_class: Dict[str, Dict[str, str]] = {}
        graphs: Dict[str, Dict[str, Set[str]]] = {}
        for cls in ast.walk(module.tree):
            if isinstance(cls, ast.ClassDef):
                owned = _owned_attrs(module, cls)
                if owned:
                    owned_by_class[cls.name] = owned
                    graphs[cls.name] = _self_call_graph(cls)
        if not owned_by_class:
            return
        # Stale annotations: a method-name owner that is never spawned.
        for cls_name, owned in owned_by_class.items():
            for attr, owner in owned.items():
                if owner in graphs[cls_name] \
                        and owner not in spawned.get(cls_name, ()):
                    yield Finding(
                        check=self.name, path=module.path,
                        line=self._attr_line(module, cls_name, attr),
                        symbol=cls_name,
                        message=(f"'{attr}' is owned_by_thread '{owner}' "
                                 f"but {cls_name} never spawns a thread "
                                 f"with that target"),
                        detail=f"{attr}:unspawned:{owner}")
        guards = collect_guards(module)
        for scan in iter_function_scans(module.tree,
                                        guards.requires_lock):
            parts = scan.symbol.split(".")
            cls_name = parts[0] if len(parts) > 1 else None
            if cls_name not in owned_by_class:
                continue
            method = parts[1]
            if method in ("__init__", "__new__", "__del__"):
                continue
            owned = owned_by_class[cls_name]
            graph = graphs[cls_name]
            entries = spawned.get(cls_name, set())
            for access in scan.accesses:
                if access.owner != "self" or access.name not in owned:
                    continue
                if access.held:
                    continue  # a lock serialises the access
                owner = owned[access.name]
                if owner in graph:
                    allowed = _owner_closure(owner, graph)
                    # An unspawned owner already produced its own finding;
                    # don't cascade per-access noise on top.
                    bad = owner in entries and method not in allowed
                else:
                    bad = method in entries
                if bad:
                    yield Finding(
                        check=self.name, path=module.path,
                        line=access.line, symbol=scan.symbol,
                        message=(f"'{access.name}' is owned by thread "
                                 f"'{owner}' but is "
                                 f"{'written' if access.write else 'read'} "
                                 f"from {scan.symbol} with no lock held"),
                        detail=f"{access.name}:{method}")

    @staticmethod
    def _attr_line(module: SourceModule, cls_name: str, attr: str) -> int:
        for cls in ast.walk(module.tree):
            if isinstance(cls, ast.ClassDef) and cls.name == cls_name:
                for node in ast.walk(cls):
                    for target in _assign_names(node):
                        if (isinstance(target, ast.Attribute)
                                and target.attr == attr
                                and module.marker(node.lineno,
                                                  "owned_by_thread")):
                            return node.lineno
        return 1
