"""Checker 5 — collective lockstep divergence.

Collective ops (``allreduce``/``broadcast``/``barrier``/...) are
rendezvous points: every rank in the group must reach the same call in
the same order or the whole group deadlocks.  The dangerous shape is a
collective reachable under a conditional on *per-worker* state — a
stop-event, a rank test, an exhausted local shard — with no matching
collective on the other branch: ranks that take the other branch leave
their peers blocked in the collective forever (the elastic wind-down
hang that ``ElasticTrainer`` avoids by fencing at step boundaries and
destroying the group to wake blocked ranks).

Two shapes are flagged:

* **branch divergence** — ``if <per-worker cond>:`` where the two
  branches call different (multi)sets of collectives;
* **loop-exit divergence** — a loop whose body calls a collective and
  also contains ``break``/``return`` guarded by a per-worker condition
  placed so the exiting rank skips the collective its peers will sit in.

"Per-worker" is a heuristic on the condition expression: names
mentioning ``rank``/``stop``/``fence``/``preempt``, ``Event.is_set()``
calls, or ``x is None`` tests on locally-claimed work (``batch`` /
``claim`` / ``sample`` names).  Deliberate divergence (e.g. a
rank-0-only broadcast *source* pattern where the op itself is symmetric)
is suppressed with ``# lockstep_ok: <reason>`` on the ``if`` line.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from ray_tpu.devtools.analysis import core

#: symmetric rendezvous ops — every rank must participate
COLLECTIVE_OPS = frozenset({
    "allreduce", "reduce", "broadcast", "allgather",
    "reducescatter", "reduce_scatter", "barrier",
})

_PER_WORKER_NAME_HINTS = ("rank", "stop", "fence", "preempt", "shutdown",
                          "draining", "wind_down")
_CLAIM_NAME_HINTS = ("batch", "claim", "sample", "item", "work")


def _collective_aliases(tree: ast.AST) -> Set[str]:
    """Receiver names that refer to the ray_tpu.collective module, plus
    bare op names imported from it."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name in ("ray_tpu.collective", "collective"):
                    aliases.add(alias.asname
                                or alias.name.split(".")[-1])
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == "ray_tpu" or mod.endswith("collective"):
                for alias in node.names:
                    if alias.name == "collective":
                        aliases.add(alias.asname or "collective")
                    elif mod.endswith("collective") \
                            and alias.name in COLLECTIVE_OPS:
                        aliases.add(f"<bare>{alias.asname or alias.name}")
    return aliases


def _collective_op(call: ast.Call, aliases: Set[str]) -> Optional[str]:
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr in COLLECTIVE_OPS \
            and isinstance(func.value, ast.Name) \
            and func.value.id in aliases:
        return func.attr
    if isinstance(func, ast.Name) and f"<bare>{func.id}" in aliases:
        return func.id
    return None


def _collectives_in(stmts, aliases: Set[str]) -> List[ast.Call]:
    """Collective calls in a statement list, not descending into nested
    function/class definitions (those run on their own schedule)."""
    out: List[ast.Call] = []

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            if isinstance(child, ast.Call) \
                    and _collective_op(child, aliases) is not None:
                out.append(child)
            walk(child)

    for stmt in stmts:
        if isinstance(stmt, ast.Call) \
                and _collective_op(stmt, aliases) is not None:
            out.append(stmt)
        walk(stmt)
    return out


def _is_per_worker(cond: ast.expr) -> bool:
    for node in ast.walk(cond):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name is not None \
                and any(h in name.lower() for h in _PER_WORKER_NAME_HINTS):
            return True
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "is_set":
            return True
        if isinstance(node, ast.Compare) \
                and any(isinstance(op, (ast.Is, ast.IsNot))
                        for op in node.ops) \
                and any(isinstance(c, ast.Constant) and c.value is None
                        for c in node.comparators):
            for side in [node.left, *node.comparators]:
                sname = None
                if isinstance(side, ast.Name):
                    sname = side.id
                elif isinstance(side, ast.Attribute):
                    sname = side.attr
                if sname is not None and any(
                        h in sname.lower() for h in _CLAIM_NAME_HINTS):
                    return True
    return False


def _exits_in(stmts) -> List[ast.stmt]:
    """break/return statements in a statement list, not crossing into
    nested defs or nested loops (an inner loop's break exits that loop)."""
    out: List[ast.stmt] = []

    def walk(body):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.For, ast.AsyncFor,
                                 ast.While)):
                continue
            if isinstance(stmt, (ast.Break, ast.Return)):
                out.append(stmt)
            for attr in ("body", "orelse", "finalbody"):
                child = getattr(stmt, attr, None)
                if child:
                    walk(child)
            for handler in getattr(stmt, "handlers", ()) or ():
                walk(handler.body)

    walk(stmts)
    return out


class LockstepChecker(core.Checker):
    name = "lockstep-divergence"
    description = ("collective call reachable under per-worker conditional "
                   "with no matching collective on the other branch")

    def check_module(self, module: core.SourceModule,
                     ctx: core.AnalysisContext) -> Iterator[core.Finding]:
        aliases = _collective_aliases(module.tree)
        if not aliases:
            return
        for fn, symbol in self._functions(module.tree):
            yield from self._check_function(fn, symbol, module, aliases)

    @staticmethod
    def _functions(tree):
        def walk(body, prefix):
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    symbol = f"{prefix}{stmt.name}" if prefix else stmt.name
                    yield stmt, symbol
                    yield from walk(stmt.body, symbol + ".")
                elif isinstance(stmt, ast.ClassDef):
                    yield from walk(stmt.body, stmt.name + ".")
                else:
                    for attr in ("body", "orelse", "finalbody"):
                        child = getattr(stmt, attr, None)
                        if child:
                            yield from walk(child, prefix)
                    for handler in getattr(stmt, "handlers", ()) or ():
                        yield from walk(handler.body, prefix)

        yield from walk(tree.body, "")

    def _check_function(self, fn, symbol: str, module: core.SourceModule,
                        aliases: Set[str]) -> Iterator[core.Finding]:
        # ---- branch divergence -------------------------------------------
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                continue
            if not isinstance(node, ast.If):
                continue
            if not _is_per_worker(node.test):
                continue
            if module.marker_near(node.lineno, "lockstep_ok"):
                continue
            body_ops = sorted(_collective_op(c, aliases)
                              for c in _collectives_in(node.body, aliases))
            else_ops = sorted(_collective_op(c, aliases)
                              for c in _collectives_in(node.orelse, aliases))
            if body_ops == else_ops or not (body_ops or else_ops):
                continue
            taken, skipped = (("then", "else") if body_ops else
                              ("else", "then"))
            ops = body_ops or else_ops
            yield core.Finding(
                check=self.name, path=module.path, line=node.lineno,
                symbol=symbol, detail=f"branch:{','.join(sorted(set(ops)))}",
                message=(f"{symbol}: collective {'/'.join(sorted(set(ops)))} "
                         f"on the {taken}-branch of a per-worker conditional "
                         f"(line {node.lineno}) has no matching collective "
                         f"on the {skipped}-branch — ranks taking the "
                         f"{skipped}-branch leave peers blocked in the "
                         f"rendezvous"))
        # ---- loop-exit divergence ----------------------------------------
        for loop in ast.walk(fn):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            loop_colls = _collectives_in(loop.body, aliases)
            if not loop_colls:
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.If) \
                        or not _is_per_worker(node.test):
                    continue
                if module.marker_near(node.lineno, "lockstep_ok"):
                    continue
                exits = _exits_in(node.body)
                if not exits:
                    continue
                # Exits that themselves follow a matching collective inside
                # the guarded branch are the fenced wind-down idiom: every
                # rank reaches the same collective, then exits together.
                if _collectives_in(node.body, aliases):
                    continue
                coll_line = loop_colls[0].lineno
                op = _collective_op(loop_colls[0], aliases)
                yield core.Finding(
                    check=self.name, path=module.path, line=node.lineno,
                    symbol=symbol, detail=f"loop-exit:{op}",
                    message=(f"{symbol}: a rank can exit the loop under a "
                             f"per-worker condition (line {node.lineno}) "
                             f"while peers continue into {op}() at line "
                             f"{coll_line} — exiting rank never joins the "
                             f"rendezvous; fence the exit at a step "
                             f"boundary all ranks agree on"))
