"""Checker 2 — atomicity.

The exact ``FaultInjector.fires()`` bug shape from PR 6: a guarded
attribute is READ under one lock acquisition and WRITTEN under a *later,
separate* acquisition in the same method.  Between the two critical
sections another thread can interleave, so the write clobbers state the
read no longer describes — a read-modify-write torn across lock windows.

Only read→write across regions is flagged (write/write is a plain
last-writer-wins publish, and write→read is not an RMW); mutator calls
(``.pop``/``.add``/...) count as writes only, so the deliberate
handoff-in-two-sections idiom (add under lock A, discard under lock B,
as in ``CheckpointCoordinator.shard_complete``) stays clean.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

from ray_tpu.devtools.analysis import core, locks


class AtomicityChecker(core.Checker):
    name = "atomicity"
    description = ("read-modify-write of guarded state split across "
                   "separate lock acquisitions in one method")

    def check_module(self, module: core.SourceModule,
                     ctx: core.AnalysisContext) -> Iterator[core.Finding]:
        guards = core.collect_guards(module)
        if not guards.class_guards and not guards.module_guards:
            return
        for scan in locks.iter_function_scans(module.tree,
                                              guards.requires_lock):
            if scan.is_init:
                continue
            cls = scan.symbol.rsplit(".", 2)[0] if "." in scan.symbol else None
            attr_guards = guards.class_guards.get(cls, {}) if cls else {}
            #: (owner, name) -> (reads: [(region, line)], writes: [...])
            per_attr: Dict[Tuple[str, str],
                           Tuple[List[Tuple[int, int]],
                                 List[Tuple[int, int]]]] = {}
            for acc in scan.accesses:
                if acc.owner == "self" and acc.name in attr_guards:
                    token = ("self", attr_guards[acc.name])
                elif acc.owner == "global" and acc.name in guards.module_guards:
                    token = ("global", guards.module_guards[acc.name])
                else:
                    continue
                region = acc.region(token)
                if region is None:
                    continue  # unlocked access: lock-discipline's finding
                reads, writes = per_attr.setdefault(
                    (acc.owner, acc.name), ([], []))
                (writes if acc.write else reads).append((region, acc.line))
            for (owner, name), (reads, writes) in per_attr.items():
                hit = None
                for r_region, r_line in reads:
                    for w_region, w_line in writes:
                        if w_region > r_region:
                            hit = (r_line, w_line)
                            break
                    if hit:
                        break
                if hit:
                    yield core.Finding(
                        check=self.name, path=module.path, line=hit[1],
                        symbol=scan.symbol, detail=name,
                        message=(
                            f"'{name}' read under one lock acquisition "
                            f"(line {hit[0]}) and written under a later, "
                            f"separate one (line {hit[1]}) in {scan.symbol} "
                            f"— the read-evaluate-update is not atomic"))
