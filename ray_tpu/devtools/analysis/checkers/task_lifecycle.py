"""Async-task lifecycle: created tasks must be awaited or cancelled.

The PR 11 ``_actor_async_loop`` bug class: ``asyncio.create_task`` /
``ensure_future`` results that nobody awaits or cancels are abandoned
when the loop dies — their refs stay forever unresolved and every caller
blocked on them hangs.  The checker recognises these retention shapes:

* bare ``create_task(...)`` expression — fire-and-forget, flagged unless
  the line carries ``# detached_ok: <reason>``;
* ``t = create_task(...)`` — ``t`` must be awaited, ``.cancel()``ed,
  or handed to ``gather``/``wait``/``wait_for``/``shield``/
  ``as_completed`` somewhere in the function;
* ``self._t = create_task(...)`` — same search over the whole class
  (the canonical "loop task stored on the instance" layout);
* ``tasks = [ensure_future(...) for ...]`` — the container name is
  checked instead (the long-poll fan-out shape).

Anything fancier (task stored in a dict, returned to the caller) is
deliberately not flagged — the checker under-reports rather than guess.
``# detached_ok:`` requires a reason, same as ``blocking_ok``.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from .. import cfg
from ..core import AnalysisContext, Checker, Finding, SourceModule

_CREATORS = frozenset({"create_task", "ensure_future"})
_CONSUMER_FUNCS = frozenset({
    "gather", "wait", "wait_for", "shield", "as_completed"})
_CONSUMER_METHODS = frozenset({"cancel", "add_done_callback", "result"})


def _creator_call(node: ast.Call) -> Optional[str]:
    func = node.func
    if isinstance(func, ast.Attribute) and func.attr in _CREATORS:
        return func.attr
    if isinstance(func, ast.Name) and func.id in _CREATORS:
        return func.id
    return None


def _consumed(scope: ast.AST, name_text: str) -> bool:
    """True when ``name_text`` (a task or container of tasks) is awaited,
    cancelled, or passed to an asyncio consumer anywhere in ``scope``."""
    for node in ast.walk(scope):
        if isinstance(node, ast.Await) \
                and ast.unparse(node.value) == name_text:
            return True
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr in _CONSUMER_METHODS \
                    and ast.unparse(func.value) == name_text:
                return True
            fname = func.attr
        elif isinstance(func, ast.Name):
            fname = func.id
        else:
            continue
        if fname in _CONSUMER_FUNCS:
            for arg in node.args:
                inner = arg.value if isinstance(arg, ast.Starred) else arg
                if ast.unparse(inner) == name_text:
                    return True
                if isinstance(inner, (ast.List, ast.Tuple, ast.Set)) and any(
                        ast.unparse(e) == name_text for e in inner.elts):
                    return True
    return False


class TaskLifecycleChecker(Checker):
    name = "task-lifecycle"
    description = ("asyncio task created but never awaited/cancelled "
                   "(fire-and-forget needs # detached_ok: reason)")

    def check_module(self, module: SourceModule,
                     ctx: AnalysisContext) -> Iterator[Finding]:
        class_nodes = {n.name: n for n in ast.walk(module.tree)
                       if isinstance(n, ast.ClassDef)}
        for symbol, fn, cls in cfg.iter_functions(module.tree):
            for call in cfg.calls_in_function(fn):
                kind = _creator_call(call)
                if kind is None:
                    continue
                if module.marker_near(call.lineno, "detached_ok"):
                    continue
                coro = ast.unparse(call.args[0])[:60] if call.args else "?"
                finding = Finding(
                    check=self.name, path=module.path, line=call.lineno,
                    symbol=symbol, message="", detail=f"{kind}:{coro}")
                stmt = self._enclosing_stmt(fn, call)
                if isinstance(stmt, ast.Expr) and stmt.value is call:
                    yield self._msg(finding, f"fire-and-forget {kind}() — "
                                    "retain and await/cancel the task, or "
                                    "annotate '# detached_ok: reason'")
                    continue
                name, scope = self._retention(stmt, call, fn, cls,
                                              class_nodes)
                if name is None:
                    continue  # unrecognised retention: under-report
                if not _consumed(scope, name):
                    where = ("anywhere in the class" if scope is not fn
                             else "in this function")
                    yield self._msg(finding, f"task '{name}' from {kind}() "
                                    f"is never awaited or cancelled {where}")

    # ------------------------------------------------------------ helpers
    @staticmethod
    def _enclosing_stmt(fn, call) -> Optional[ast.stmt]:
        found = None
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                continue
            if isinstance(node, ast.stmt) and any(
                    sub is call for sub in ast.walk(node)):
                found = node  # walk is breadth-first: last hit is innermost
        return found

    @staticmethod
    def _retention(stmt, call, fn, cls, class_nodes):
        """(tracked name, search scope) or (None, None)."""
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            return None, None
        target = stmt.targets[0]
        value = stmt.value
        direct = value is call or (
            isinstance(value, (ast.ListComp, ast.SetComp))
            and any(sub is call for sub in ast.walk(value)))
        if not direct:
            return None, None
        if isinstance(target, ast.Name):
            return target.id, fn
        if isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name) \
                and target.value.id == "self" and cls in class_nodes:
            return f"self.{target.attr}", class_nodes[cls]
        return None, None

    def _msg(self, finding: Finding, message: str) -> Finding:
        return Finding(check=finding.check, path=finding.path,
                       line=finding.line, symbol=finding.symbol,
                       message=message, detail=finding.detail)
