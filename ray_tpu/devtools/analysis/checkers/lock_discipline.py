"""Checker 1 — lock-discipline.

Every attribute annotated ``# guarded_by: <lock>`` (and every module
global annotated the same way) must only be read or written while the
named lock is held: inside ``with self.<lock>:`` / ``with <lock>:``, or
between ``<lock>.acquire()`` and ``<lock>.release()``, or in a method
marked ``# requires_lock: <lock>`` (``*_locked`` names get this for
free), or in ``__init__`` (the object is not shared yet).

Also enforces the dual: a ``requires_lock`` method must only be *called*
with the lock held — ``self._foo_locked()`` from an unlocked scope is the
PR 5 commit/sweep shape (state escaping its lock window through a helper).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ray_tpu.devtools.analysis import core, locks


class LockDisciplineChecker(core.Checker):
    name = "lock-discipline"
    description = ("guarded_by-annotated state accessed outside its lock")

    def check_module(self, module: core.SourceModule,
                     ctx: core.AnalysisContext) -> Iterator[core.Finding]:
        guards = core.collect_guards(module)
        if not guards.class_guards and not guards.module_guards:
            return
        for scan in locks.iter_function_scans(module.tree,
                                              guards.requires_lock):
            if scan.is_init:
                continue
            cls = scan.symbol.rsplit(".", 2)[0] if "." in scan.symbol else None
            attr_guards = guards.class_guards.get(cls, {}) if cls else {}
            req = guards.requires_lock.get(cls, {}) if cls else {}
            seen = set()
            for acc in scan.accesses:
                if acc.owner == "self" and acc.name in attr_guards:
                    lock = attr_guards[acc.name]
                    token = ("self", lock)
                elif acc.owner == "global" and acc.name in guards.module_guards:
                    lock = guards.module_guards[acc.name]
                    token = ("global", lock)
                else:
                    continue
                if acc.holds(token):
                    continue
                verb = "written" if acc.write else "read"
                dedup = (acc.name, acc.line)
                if dedup in seen:
                    continue
                seen.add(dedup)
                yield core.Finding(
                    check=self.name, path=module.path, line=acc.line,
                    symbol=scan.symbol, detail=acc.name,
                    message=(f"'{acc.name}' (guarded_by {lock}) {verb} in "
                             f"{scan.symbol} without holding {lock}"))
            # Dual: calling a requires_lock helper from an unlocked scope.
            if not req:
                continue
            for call in scan.calls:
                func = call.node.func
                if not (isinstance(func, ast.Attribute)
                        and isinstance(func.value, ast.Name)
                        and func.value.id == "self"
                        and func.attr in req):
                    continue
                lock = req[func.attr]
                if call.holds_any_lock() and any(
                        t == ("self", lock) for t, _ in call.held):
                    continue
                yield core.Finding(
                    check=self.name, path=module.path, line=call.line,
                    symbol=scan.symbol, detail=f"call:{func.attr}",
                    message=(f"{scan.symbol} calls {func.attr}() "
                             f"(requires_lock {lock}) without holding "
                             f"{lock}"))
