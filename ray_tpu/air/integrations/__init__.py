"""Experiment-tracking integrations (ref: python/ray/air/integrations/ —
wandb.py, mlflow.py, comet.py).  Each logger is a Tune callback
(on_trial_start/result/complete hooks, tune_controller.py) that forwards
results to its tracking backend; backends not installed in the image fall
back to a local file sink with the same record shape, so experiments are
never silently unlogged."""

from ray_tpu.air.integrations.mlflow import MLflowLoggerCallback, setup_mlflow
from ray_tpu.air.integrations.tensorboard import TBXLoggerCallback
from ray_tpu.air.integrations.wandb import WandbLoggerCallback, setup_wandb

__all__ = ["MLflowLoggerCallback", "TBXLoggerCallback",
           "WandbLoggerCallback", "setup_mlflow", "setup_wandb"]
