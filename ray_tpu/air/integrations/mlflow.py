"""MLflow integration (ref: python/ray/air/integrations/mlflow.py
MLflowLoggerCallback:35 + setup_mlflow:150).

With ``mlflow`` importable, each trial becomes its OWN MLflow run driven
through ``MlflowClient`` by run id (never the global active-run stack —
concurrent trials would cross-log otherwise).  Without it (this image),
the fallback writes ``mlruns_offline/<trial_id>.jsonl`` with the same
params/metrics records, so the adapter is exercised end-to-end offline."""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

from ray_tpu.air.integrations._common import JsonlSink, numeric_metrics


def _mlflow_module():
    try:
        import mlflow  # noqa: F401

        return mlflow
    except ImportError:
        return None


class _ClientRun:
    """One trial's MLflow run, addressed by run_id via MlflowClient."""

    def __init__(self, client, run_id: str):
        self._client = client
        self._run_id = run_id

    def log_params(self, params: Dict[str, Any]) -> None:
        for k, v in (params or {}).items():
            self._client.log_param(self._run_id, k, v)

    def log_metrics(self, metrics: Dict[str, Any],
                    step: Optional[int] = None) -> None:
        ts = int(time.time() * 1000)
        for k, v in numeric_metrics(metrics).items():
            self._client.log_metric(self._run_id, k, v, timestamp=ts,
                                    step=step or 0)

    def end_run(self, status: str = "FINISHED") -> None:
        self._client.set_terminated(self._run_id, status=status)


class _OfflineMLflow:
    """mlflow-run-shaped shim over the JSONL sink."""

    def __init__(self, root: str, run_id: str, config):
        self._sink = JsonlSink(root, run_id,
                               {"type": "params", "params": config or {}})
        self.path = self._sink.path

    def log_params(self, params: Dict[str, Any]) -> None:
        self._sink.write({"type": "params", "params": params})

    def log_metrics(self, metrics: Dict[str, Any],
                    step: Optional[int] = None) -> None:
        self._sink.write({"type": "metrics", "step": step,
                          "metrics": numeric_metrics(metrics)})

    def end_run(self, status: str = "FINISHED") -> None:
        self._sink.close({"type": "end", "status": status})


def _client_run(mlflow, experiment_name: str,
                tracking_uri: Optional[str]) -> _ClientRun:
    client = mlflow.tracking.MlflowClient(tracking_uri=tracking_uri)
    exp = client.get_experiment_by_name(experiment_name)
    exp_id = exp.experiment_id if exp is not None \
        else client.create_experiment(experiment_name)
    run = client.create_run(exp_id)
    return _ClientRun(client, run.info.run_id)


def setup_mlflow(config: Optional[Dict[str, Any]] = None, *,
                 experiment_name: Optional[str] = None,
                 tracking_uri: Optional[str] = None, **kwargs):
    """Inside a train_loop/trainable: configure (or shim) mlflow
    (ref: integrations/mlflow.py setup_mlflow)."""
    mlflow = _mlflow_module()
    if mlflow is not None:
        run = _client_run(mlflow, experiment_name or "ray_tpu", tracking_uri)
        if config:
            run.log_params(config)
        return run
    import uuid

    run_id = experiment_name or f"run-{os.getpid()}-{uuid.uuid4().hex[:6]}"
    return _OfflineMLflow(os.path.join(os.getcwd(), "mlruns_offline"),
                          run_id, config)


class MLflowLoggerCallback:
    """Tune callback: one MLflow run per trial
    (ref: integrations/mlflow.py:35)."""

    def __init__(self, experiment_name: str = "ray_tpu",
                 tracking_uri: Optional[str] = None,
                 save_dir: Optional[str] = None, **kwargs):
        self.experiment_name = experiment_name
        self.tracking_uri = tracking_uri
        self.save_dir = save_dir
        self.kwargs = kwargs
        self._runs: Dict[str, Any] = {}

    def _run_for(self, trial):
        run = self._runs.get(trial.trial_id)
        if run is None:
            mlflow = _mlflow_module()
            if mlflow is not None:
                run = _client_run(mlflow, self.experiment_name,
                                  self.tracking_uri)
                run.log_params(dict(trial.config or {}))
            else:
                base = self.save_dir or getattr(trial, "logdir", None) or "."
                run = _OfflineMLflow(os.path.join(base, "mlruns_offline"),
                                     trial.trial_id,
                                     dict(trial.config or {}))
            self._runs[trial.trial_id] = run
        return run

    def on_trial_start(self, trial=None, **kw) -> None:
        self._run_for(trial)

    def on_trial_result(self, trial=None, result=None, **kw) -> None:
        self._run_for(trial).log_metrics(
            result, step=int(result.get("training_iteration", 0)))

    def on_trial_complete(self, trial=None, **kw) -> None:
        run = self._runs.pop(trial.trial_id, None)
        if run is not None:
            run.end_run()

    def on_trial_error(self, trial=None, **kw) -> None:
        run = self._runs.pop(trial.trial_id, None)
        if run is not None:
            run.end_run(status="FAILED")

    def on_experiment_end(self, trials=None, **kw) -> None:
        for run in self._runs.values():
            run.end_run()
        self._runs.clear()
